"""Fleet collective + collective op tests.

Reference analogs: test_fleet_* meta-optimizer tests (assert on rewritten
program ops), test_collective_* (numeric checks of each c_* op over a
localhost NCCL ring — here a shard_map over the virtual 8-device mesh),
and ParallelExecutor loss-parity tests.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, optimizer
from paddle_tpu.distributed import fleet
from paddle_tpu.framework.layer_helper import LayerHelper
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.spmd import build_spmd_step


def _collective_program(op_type, x_shape, attrs):
    main, startup = pt.default_main_program(), pt.default_startup_program()
    with pt.program_guard(main, startup):
        x = layers.data("x", list(x_shape), append_batch_size=False)
        h = LayerHelper(op_type)
        out = h.create_variable_for_type_inference("float32")
        h.append_op(op_type, inputs={"X": [x]}, outputs={"Out": [out]},
                    attrs=attrs)
    return main, out


def _run_collective(op_type, xv, attrs):
    main, out = _collective_program(op_type, xv.shape, attrs)
    mesh = make_mesh({"dp": 8})
    fn, mut_in, const_in, _ = build_spmd_step(main, ["x"], [out.name], mesh)
    fetches, _, _ = fn((xv,), (), (), np.int32(1))
    return np.asarray(fetches[0])


def test_c_allreduce_sum():
    xv = np.arange(8, dtype="float32").reshape(8, 1)
    got = _run_collective("c_allreduce_sum", xv, {"ring_id": 0})
    # every participant holds the sum; fetch concatenates the 8 copies
    np.testing.assert_allclose(got, np.full((8, 1), xv.sum()))


def test_c_allreduce_max():
    xv = np.arange(8, dtype="float32").reshape(8, 1)
    got = _run_collective("c_allreduce_max", xv, {"ring_id": 0})
    np.testing.assert_allclose(got, np.full((8, 1), 7.0))


def test_c_broadcast():
    xv = np.arange(8, dtype="float32").reshape(8, 1)
    got = _run_collective("c_broadcast", xv, {"ring_id": 0, "root": 3})
    np.testing.assert_allclose(got, np.full((8, 1), 3.0))


def test_c_allgather():
    xv = np.arange(8, dtype="float32").reshape(8, 1)
    got = _run_collective("c_allgather", xv,
                          {"ring_id": 0, "nranks": 8})
    # each participant gathers the full [8,1]; concatenated -> [64,1]
    assert got.shape == (64, 1)
    np.testing.assert_allclose(got[:8], xv)


def test_c_reducescatter():
    xv = np.arange(64 * 4, dtype="float32").reshape(64, 4)  # local [8,4]
    got = _run_collective("c_reducescatter", xv,
                          {"ring_id": 0, "nranks": 8})
    # participant i receives sum over participants p of their i-th row
    # slice; concatenating the 8 participants' [1,4] results -> [8,4]
    locals_ = xv.reshape(8, 8, 4)  # [participant, row, col]
    expected = locals_.sum(axis=0)
    assert got.shape == (8, 4)
    np.testing.assert_allclose(got, expected)


def test_fleet_rewrite_inserts_allreduce():
    fleet.init(is_collective=True)
    main, startup = pt.default_main_program(), pt.default_startup_program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [16, 8], append_batch_size=False)
        y = layers.data("y", [16, 1], dtype="int64",
                        append_batch_size=False)
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.fc(layers.fc(x, 32, act="relu"), 4), y))
        opt = fleet.distributed_optimizer(optimizer.SGDOptimizer(0.1),
                                          fleet.DistributedStrategy())
        opt.minimize(loss)
    types = [op.type for op in main.global_block().ops]
    assert types.count("c_allreduce_sum") == 4  # one per param grad
    assert types.count("scale") >= 4
    startup_types = [op.type for op in startup.global_block().ops]
    assert "c_gen_nccl_id" in startup_types
    assert "c_comm_init" in startup_types


def test_fleet_lamb_meta_optimizer():
    fleet.init(is_collective=True)
    main, startup = pt.default_main_program(), pt.default_startup_program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [8, 4], append_batch_size=False)
        loss = layers.mean(layers.fc(x, 2))
        strategy = fleet.DistributedStrategy()
        strategy.lamb = True
        opt = fleet.distributed_optimizer(
            optimizer.AdamOptimizer(0.01), strategy)
        opt.minimize(loss)
    types = [op.type for op in main.global_block().ops]
    assert "lamb" in types
    assert "adam" not in types
    assert "LambOptimizer" in \
        fleet.fleet_instance()._applied_meta_optimizers


def test_fleet_dp_loss_matches_single_device():
    """Collective-DP (explicit allreduce over shard_map) must track the
    single-device run on the same global batch (reference
    TestDistBase.check_with_place loss comparison)."""
    rng = np.random.RandomState(0)
    xv = rng.rand(16, 8).astype("float32")
    yv = rng.randint(0, 4, (16, 1)).astype("int64")

    def build():
        x = layers.data("x", [16, 8], append_batch_size=False)
        y = layers.data("y", [16, 1], dtype="int64",
                        append_batch_size=False)
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.fc(layers.fc(x, 32, act="relu"), 4), y))
        return loss

    from paddle_tpu.ops.registry import reset_op_seed

    # single device
    reset_op_seed()
    main1, startup1 = pt.Program(), pt.Program()
    startup1._is_startup = True
    with pt.program_guard(main1, startup1):
        loss1 = build()
        optimizer.SGDOptimizer(0.1).minimize(loss1)
    exe = pt.Executor()
    scope1 = pt.Scope()
    exe.run(startup1, scope=scope1)
    ref = [float(exe.run(main1, feed={"x": xv, "y": yv},
                         fetch_list=[loss1], scope=scope1)[0])
           for _ in range(4)]

    # fleet dp over 8 virtual devices
    reset_op_seed()
    fleet.init(is_collective=True)
    main2, startup2 = pt.Program(), pt.Program()
    startup2._is_startup = True
    with pt.program_guard(main2, startup2):
        loss2 = build()
        opt = fleet.distributed_optimizer(optimizer.SGDOptimizer(0.1),
                                          fleet.DistributedStrategy())
        opt.minimize(loss2)
    scope2 = pt.Scope()
    exe2 = pt.Executor()  # fresh: init randomness is keyed by step count
    exe2.run(startup2, scope=scope2)
    compiled = pt.CompiledProgram(main2).with_data_parallel(
        loss_name=loss2.name)
    got = []
    for _ in range(4):
        l = exe2.run(compiled, feed={"x": xv, "y": yv},
                     fetch_list=[loss2], scope=scope2)[0]
        # per-participant local losses; global mean = mean of locals
        got.append(float(np.mean(l)))
    np.testing.assert_allclose(got, ref, rtol=2e-5)


def test_compiled_program_gspmd_path():
    """Program WITHOUT collective ops takes the GSPMD lowering."""
    main, startup = pt.default_main_program(), pt.default_startup_program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [16, 8], append_batch_size=False)
        loss = layers.mean(layers.fc(x, 4))
        optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(startup)
    compiled = pt.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    xv = np.random.rand(16, 8).astype("float32")
    l0 = exe.run(compiled, feed={"x": xv}, fetch_list=[loss])[0]
    l1 = exe.run(compiled, feed={"x": xv}, fetch_list=[loss])[0]
    assert "gspmd" in compiled._compiled
    assert float(np.mean(l1)) < float(np.mean(l0))


# round-5 legacy dense surfaces (reference collective/allreduce_op.cc,
# broadcast_op.cc, c_scatter_op.cc + c_allreduce_prod reduce flavor)
def test_allreduce_legacy():
    xv = np.arange(8, dtype="float32").reshape(8, 1)
    got = _run_collective("allreduce", xv, {"ring_id": 0})
    np.testing.assert_allclose(got, np.full((8, 1), xv.sum()))


def test_broadcast_legacy():
    xv = np.arange(8, dtype="float32").reshape(8, 1)
    got = _run_collective("broadcast", xv, {"ring_id": 0, "root": 5})
    np.testing.assert_allclose(got, np.full((8, 1), 5.0))


def test_c_reduce_prod():
    xv = (np.arange(8, dtype="float32") % 2 + 1).reshape(8, 1)
    got = _run_collective("c_reduce_prod", xv, {"ring_id": 0})
    np.testing.assert_allclose(got, np.full((8, 1), 16.0))


def test_c_scatter():
    # root holds [8,1]; each rank gets its 1-row chunk
    xv = np.arange(64, dtype="float32").reshape(64, 1)
    got = _run_collective("c_scatter", xv,
                          {"ring_id": 0, "root": 0, "nranks": 8})
    # shard b of the dp axis feeds rows 8b..8b+8; root=0's value is
    # rows 0..8, rank r takes chunk r -> r
    assert got.shape == (8, 1)
    np.testing.assert_allclose(got.reshape(-1), np.arange(8.0))
