"""Beam-search decoding stack: ops, decoder layers, and the
machine-translation book model (VERDICT r3 #2).

Reference: operators/beam_search_op.cc, beam_search_decode_op.cc,
gather_tree_op.cc, python/paddle/fluid/layers/rnn.py (BeamSearchDecoder,
dynamic_decode), tests/book/test_machine_translation.py.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, optimizer

BOS, EOS = 0, 1


def _run(program, feed, fetch, scope=None):
    exe = pt.Executor()
    return exe.run(program, feed=feed, fetch_list=fetch, scope=scope)


# ---------------------------------------------------------------------------
# ops
# ---------------------------------------------------------------------------

def test_gather_tree_matches_reference_loop():
    """Vectorized reverse-scan vs the reference scalar backtrack
    (gather_tree_op.h:40)."""
    T, B, K = 5, 2, 3
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 9, (T, B, K)).astype("int64")
    parents = rng.randint(0, K, (T, B, K)).astype("int64")
    ref = np.zeros_like(ids)
    for b in range(B):
        for k in range(K):
            ref[T - 1, b, k] = ids[T - 1, b, k]
            parent = parents[T - 1, b, k]
            for t in range(T - 2, -1, -1):
                ref[t, b, k] = ids[t, b, parent]
                parent = parents[t, b, parent]

    main_p, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main_p, startup):
        i = layers.data("ids", [T, B, K], dtype="int64",
                        append_batch_size=False)
        p = layers.data("par", [T, B, K], dtype="int64",
                        append_batch_size=False)
        out = layers.gather_tree(i, p)
    exe = pt.Executor()
    exe.run(startup)
    got, = exe.run(main_p, feed={"ids": ids, "par": parents},
                   fetch_list=[out])
    assert (np.asarray(got) == ref).all()


def test_beam_search_step_finished_semantics():
    """A finished hypothesis persists as an end-token self-continuation
    at frozen score and spawns nothing else."""
    B, K, W = 1, 2, 3
    end_id = 7
    pre_ids = np.array([[4, end_id]], "int64")        # hyp 1 finished
    pre_scores = np.array([[-1.0, -0.5]], "float32")
    cand_ids = np.tile(np.array([1, 2, 3], "int64"), (B, K, 1))
    cand_scores = np.array(
        [[[-1.2, -1.5, -3.0], [-0.9, -2.0, -2.5]]], "float32")

    main_p, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main_p, startup):
        pi = layers.data("pi", [B, K], dtype="int64",
                         append_batch_size=False)
        ps = layers.data("ps", [B, K], append_batch_size=False)
        ci = layers.data("ci", [B, K, W], dtype="int64",
                         append_batch_size=False)
        cs = layers.data("cs", [B, K, W], append_batch_size=False)
        sid, ssc, par = layers.beam_search(pi, ps, ci, cs, beam_size=K,
                                           end_id=end_id)
    exe = pt.Executor()
    exe.run(startup)
    si, sc, pr = exe.run(
        main_p, feed={"pi": pre_ids, "ps": pre_scores, "ci": cand_ids,
                      "cs": cand_scores},
        fetch_list=[sid, ssc, par])
    # best: the frozen finished hyp (-0.5), then hyp0's token 1 (-1.2)
    assert np.asarray(si).tolist() == [[end_id, 1]]
    assert np.asarray(pr).tolist() == [[1, 0]]
    np.testing.assert_allclose(np.asarray(sc), [[-0.5, -1.2]], atol=1e-6)


def test_beam_search_decode_padding_and_lengths():
    T, B, K = 4, 1, 2
    end_id = 9
    # beam 0 emits end at t=1; beam 1 never finishes
    ids = np.array([[[3, 4]], [[end_id, 5]], [[6, 6]], [[7, 7]]], "int64")
    parents = np.zeros((T, B, K), "int64")
    parents[:, :, 1] = 1
    scores = np.array([[-0.3, -0.9]], "float32")
    main_p, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main_p, startup):
        i = layers.data("ids", [T, B, K], dtype="int64",
                        append_batch_size=False)
        p = layers.data("par", [T, B, K], dtype="int64",
                        append_batch_size=False)
        s = layers.data("sc", [B, K], append_batch_size=False)
        sent, sc, ln = layers.beam_search_decode(i, p, s, end_id=end_id)
    exe = pt.Executor()
    exe.run(startup)
    sv, scv, lnv = exe.run(main_p,
                           feed={"ids": ids, "par": parents, "sc": scores},
                           fetch_list=[sent, sc, ln])
    sv, lnv = np.asarray(sv), np.asarray(lnv)
    assert sv.shape == (B, K, T)
    assert lnv[0, 0] == 2 and lnv[0, 1] == T
    assert (sv[0, 0, 2:] == end_id).all()       # padded past the end
    assert sv[0, 0, 0] == 3 and sv[0, 0, 1] == end_id


# ---------------------------------------------------------------------------
# cells + rnn()
# ---------------------------------------------------------------------------

def test_gru_lstm_cells_train():
    """Cell-based rnn() trains a toy classifier (loss drops)."""
    B, T, D, H = 8, 5, 6, 12
    rng = np.random.RandomState(0)
    xv = rng.rand(B, T, D).astype("float32")
    yv = (xv.sum((1, 2)) > np.median(xv.sum((1, 2)))).astype(
        "int64")[:, None]
    main_p, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main_p, startup):
        x = layers.data("x", [B, T, D], append_batch_size=False)
        y = layers.data("y", [B, 1], dtype="int64", append_batch_size=False)
        out_g, _ = layers.rnn(layers.GRUCell(H), x)
        out_l, (h, c) = layers.rnn(layers.LSTMCell(H), x)
        feat = layers.concat(
            [layers.squeeze(layers.slice(out_g, axes=[1], starts=[T - 1],
                                         ends=[T]), [1]), h], axis=1)
        logits = layers.fc(feat, 2)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        optimizer.AdamOptimizer(1e-2).minimize(loss)
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    losses = [float(np.asarray(exe.run(
        main_p, feed={"x": xv, "y": yv}, fetch_list=[loss],
        scope=scope)[0]).reshape(-1)[0]) for _ in range(40)]
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])


# ---------------------------------------------------------------------------
# machine-translation book model: train + beam decode (BLEU smoke)
# ---------------------------------------------------------------------------

def test_machine_translation_book_model():
    """The reference book MT model flow on a toy copy task: teacher-
    forced training converges, and beam decode emits the source sequence
    (exact-match on most rows) with well-formed finished hypotheses."""
    from paddle_tpu.models.seq2seq import (build_seq2seq_train,
                                           build_seq2seq_infer)

    V = 12            # tokens 2..11 are content; 0=bos, 1=eos
    B, S = 16, 5
    TRG = S + 1       # content + eos
    rng = np.random.RandomState(0)

    def make_batch():
        content = rng.randint(2, V, (B, S)).astype("int64")
        src_mask = np.ones((B, S), "float32")
        trg_in = np.concatenate(
            [np.full((B, 1), BOS, "int64"), content], axis=1)
        trg_out = np.concatenate(
            [content, np.full((B, 1), EOS, "int64")], axis=1)
        trg_mask = np.ones((B, TRG), "float32")
        return content, src_mask, trg_in, trg_out, trg_mask

    train_p, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(train_p, startup):
        feeds, outs = build_seq2seq_train(B, S, TRG, V, V, emb_dim=32,
                                          hidden=32)
        optimizer.AdamOptimizer(5e-3).minimize(outs["loss"])

    infer_p, infer_startup = pt.Program(), pt.Program()
    infer_startup._is_startup = True
    with pt.program_guard(infer_p, infer_startup):
        ifeeds, iouts = build_seq2seq_infer(B, S, V, V, emb_dim=32,
                                            hidden=32, beam_size=4,
                                            max_len=TRG + 2, bos_id=BOS,
                                            eos_id=EOS)

    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    losses = []
    for i in range(150):
        content, src_mask, trg_in, trg_out, trg_mask = make_batch()
        l, = exe.run(train_p,
                     feed={"src_ids": content, "src_mask": src_mask,
                           "trg_in": trg_in, "trg_out": trg_out,
                           "trg_mask": trg_mask},
                     fetch_list=[outs["loss"]], scope=scope)
        losses.append(float(np.asarray(l).reshape(-1)[0]))
    assert losses[-1] < 0.35 * losses[0], (losses[0], losses[-1])

    content, src_mask, *_ = make_batch()
    ids, scores, lengths = exe.run(
        infer_p, feed={"src_ids": content, "src_mask": src_mask},
        fetch_list=[iouts["ids"], iouts["scores"], iouts["lengths"]],
        scope=scope)
    ids = np.asarray(ids)
    scores = np.asarray(scores)
    lengths = np.asarray(lengths)
    K, Tmax = ids.shape[1], ids.shape[2]
    assert ids.shape == (B, K, Tmax)
    # hypotheses well-formed: scores sorted, padding after first EOS
    assert (np.diff(scores, axis=1) <= 1e-5).all()
    for b in range(B):
        for k in range(K):
            ln = lengths[b, k]
            if ln < Tmax:
                assert (ids[b, k, ln:] == EOS).all()
    # BLEU smoke: top beam reproduces the source on most rows
    exact = 0
    for b in range(B):
        hyp = ids[b, 0, :lengths[b, 0]]
        hyp = hyp[hyp != EOS]
        exact += int(len(hyp) == S and (hyp == content[b]).all())
    assert exact >= int(0.7 * B), f"{exact}/{B} exact copies"
