"""OpTest harness: per-op forward checks vs numpy references and grad
checks vs central finite differences.

Reference: tests/unittests/op_test.py (check_output:226,
check_grad:1250, numeric gradient:101 get_numeric_gradient) — rebuilt on
the graph API: each case builds a tiny program (feeds -> op -> weighted
scalar loss), runs it through the real Executor (so the jax lowering and
the 'auto' vjp grads are what's under test), and compares.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

import paddle_tpu as pt
from paddle_tpu.framework.backward import append_backward
from paddle_tpu.framework.core import grad_var_name, reset_unique_name
from paddle_tpu.ops.registry import reset_op_seed


class OpCase:
    """One test case for one op type.

    inputs:  slot -> ndarray (or list of ndarrays for multi-var slots)
    outputs: slot -> number of output vars in that slot
    ref:     callable(**inputs, **attrs) -> dict slot->ndarray (or single
             ndarray, meaning the first output slot); None = skip forward
             value check (grad-only case)
    grad:    list of input slot names to grad-check (float inputs only)
    """

    def __init__(self, op_type: str, inputs: Dict, outputs: Dict = None,
                 attrs: Dict = None, ref: Optional[Callable] = None,
                 grad: Sequence[str] = (), rtol=1e-5, atol=1e-6,
                 grad_rtol=5e-2, grad_atol=5e-3, eps=2e-3,
                 check_dtype=True, name=None):
        self.op_type = op_type
        self.inputs = {k: v for k, v in inputs.items()}
        self.outputs = outputs or {"Out": 1}
        self.attrs = attrs or {}
        self.ref = ref
        self.grad = list(grad)
        self.rtol, self.atol = rtol, atol
        self.grad_rtol, self.grad_atol = grad_rtol, grad_atol
        self.eps = eps
        self.check_dtype = check_dtype
        self.name = name or op_type


def _as_list(v):
    return v if isinstance(v, (list, tuple)) else [v]


def _build(case: OpCase, with_loss: bool):
    """Build (program, feed, out_names, loss_name, loss_weights)."""
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    reset_unique_name()
    reset_op_seed()
    feed = {}
    with pt.program_guard(main, startup):
        block = main.global_block()
        in_slots = {}
        for slot, vals in case.inputs.items():
            names = []
            for j, arr in enumerate(_as_list(vals)):
                arr = np.asarray(arr)
                n = f"in_{slot}_{j}"
                block.create_var(name=n, shape=arr.shape,
                                 dtype=str(arr.dtype), is_data=True,
                                 stop_gradient=not np.issubdtype(
                                     arr.dtype, np.floating))
                feed[n] = arr
                names.append(n)
            in_slots[slot] = names
        out_slots = {}
        for slot, cnt in case.outputs.items():
            out_slots[slot] = [f"out_{slot}_{j}" for j in range(cnt)]
        op = block.append_op(case.op_type, inputs=in_slots,
                             outputs=out_slots, attrs=dict(case.attrs))
        out_names = [n for ns in out_slots.values() for n in ns]
        loss_name = None
        weights = {}
        if with_loss:
            # scalar loss = sum over float outputs of sum(out * W) with a
            # fixed random W per output (reference OpTest's
            # user_defined_grad_outputs analog)
            parts = []
            rng = np.random.RandomState(7)
            for n in out_names:
                v = block.var(n)
                if v.dtype not in ("float32", "float64", "bfloat16",
                                  "float16"):
                    continue
                if v.shape and 0 in v.shape:
                    continue  # XShape-style metadata outputs
                w = rng.uniform(0.5, 1.5,
                                [d if d > 0 else 1 for d in
                                 (v.shape or [1])]).astype("float32")
                weights[n] = w
                wn = f"w_{n}"
                block.create_var(name=wn, shape=w.shape, dtype="float32",
                                 is_data=True, stop_gradient=True)
                feed[wn] = w
                prod = pt.layers.elementwise_mul(block.var(n),
                                                 block.var(wn))
                parts.append(pt.layers.reduce_sum(prod))
            assert parts, f"{case.op_type}: no float output to form a loss"
            loss = parts[0]
            for p in parts[1:]:
                loss = pt.layers.elementwise_add(loss, p)
            loss_name = loss.name
    return main, startup, feed, out_names, loss_name


def check_forward(case: OpCase):
    main, startup, feed, out_names, _ = _build(case, with_loss=False)
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    got = exe.run(main, feed=feed, fetch_list=out_names, scope=scope)
    # infer-vs-runtime drift gate (round-5: a conv2d_transpose stride
    # bug hid because only value equality was checked and the test
    # configs happened to coincide): every fully-static declared shape
    # must match what the lowering actually produced.
    block = main.global_block()
    for name, val in zip(out_names, got):
        v = block._find_var_recursive(name)
        decl = getattr(v, "shape", None) if v is not None else None
        run_shape = tuple(np.shape(np.asarray(val)))
        if (decl is not None and len(decl) == len(run_shape)
                and all(int(d) >= 0 for d in decl)):
            assert tuple(int(d) for d in decl) == run_shape, (
                f"{case.op_type}: output {name!r} infer declared "
                f"{tuple(decl)} but the lowering produced {run_shape}")
    if case.ref is None:
        return got
    kwargs = {}
    for slot, vals in case.inputs.items():
        vs = _as_list(vals)
        kwargs[slot] = vs[0] if len(vs) == 1 else list(vs)
    expected = case.ref(**kwargs, **case.attrs)
    if not isinstance(expected, dict):
        first_slot = next(iter(case.outputs))
        expected = {first_slot: expected}
    # compare slot by slot (only slots present in expected)
    name_of = {}
    i = 0
    for slot, cnt in case.outputs.items():
        for j in range(cnt):
            name_of[(slot, j)] = i
            i += 1
    for slot, exp in expected.items():
        for j, e in enumerate(_as_list(exp)):
            g = np.asarray(got[name_of[(slot, j)]])
            e = np.asarray(e)
            assert g.shape == tuple(e.shape), \
                f"{case.name}: {slot}[{j}] shape {g.shape} != {e.shape}"
            if case.check_dtype and e.dtype.kind == "f":
                assert g.dtype.kind == "f", \
                    f"{case.name}: {slot}[{j}] dtype {g.dtype}"
            np.testing.assert_allclose(
                g.astype("float64"), e.astype("float64"),
                rtol=case.rtol, atol=case.atol,
                err_msg=f"{case.name}: output {slot}[{j}]")
    return got


def check_grad(case: OpCase):
    """Analytic grads (append_backward over the real lowering) vs central
    finite differences of the scalar loss."""
    main, startup, feed, _outs, loss_name = _build(case, with_loss=True)
    block = main.global_block()
    with pt.program_guard(main, startup):
        append_backward(block.var(loss_name))
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)

    grad_names = []
    for slot in case.grad:
        for n in ([f"in_{slot}_{j}" for j in
                   range(len(_as_list(case.inputs[slot])))]):
            grad_names.append((n, grad_var_name(n)))

    analytic = exe.run(main, feed=feed,
                       fetch_list=[g for _, g in grad_names], scope=scope)

    # numeric: re-run the forward-only loss per perturbed element
    fmain, fstartup, ffeed, _, floss = _build(case, with_loss=True)
    fexe = pt.Executor()
    fscope = pt.Scope()
    fexe.run(fstartup, scope=fscope)

    def loss_at(feed_dict):
        out = fexe.run(fmain, feed=feed_dict, fetch_list=[floss],
                       scope=fscope)
        return float(np.asarray(out[0]).reshape(-1)[0])

    for (in_name, gname), got in zip(grad_names, analytic):
        base = ffeed[in_name].astype("float64")
        num = np.zeros_like(base, dtype="float64")
        flat = base.reshape(-1)
        for i in range(flat.size):
            for sgn in (+1, -1):
                pert = flat.copy()
                pert[i] += sgn * case.eps
                f2 = dict(ffeed)
                f2[in_name] = pert.reshape(base.shape).astype(
                    ffeed[in_name].dtype)
                if sgn > 0:
                    up = loss_at(f2)
                else:
                    down = loss_at(f2)
            num.reshape(-1)[i] = (up - down) / (2 * case.eps)
        got = np.asarray(got, dtype="float64").reshape(base.shape)
        # reference OpTest-style relative comparison
        denom = np.maximum(np.abs(num), 1.0)
        err = np.abs(got - num) / denom
        assert (err < case.grad_rtol).all() or \
            np.allclose(got, num, rtol=case.grad_rtol,
                        atol=case.grad_atol), (
                f"{case.name}: grad mismatch for {in_name}\n"
                f"analytic={got.reshape(-1)[:8]}\n"
                f"numeric={num.reshape(-1)[:8]}\nmax err={err.max()}")


def run_case(case: OpCase):
    check_forward(case)
    if case.grad:
        check_grad(case)
