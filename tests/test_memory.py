"""Memory subsystem surface (reference memory/allocation/ + monitor STAT
counters). On the CPU test backend PJRT reports no allocator stats, so
the contract here is: the API exists, returns well-typed values, never
raises, and the strategy knob round-trips + validates."""
import pytest

import paddle_tpu
from paddle_tpu import memory


def test_stats_api_shape():
    stats = memory.memory_stats()
    assert isinstance(stats, dict)
    assert isinstance(memory.memory_allocated(), int)
    assert isinstance(memory.max_memory_allocated(), int)
    assert isinstance(memory.memory_reserved(), int)
    assert isinstance(memory.device_memory_capacity(), int)
    assert memory.memory_allocated() >= 0
    assert memory.max_memory_allocated() >= 0


def test_reset_peak_monotone():
    memory.reset_peak()
    # after a reset the windowed peak can only be >= 0 and <= the raw peak
    raw = memory.memory_stats().get("peak_bytes_in_use", 0)
    assert 0 <= memory.max_memory_allocated() <= max(raw, 0)


def test_strategy_roundtrip_and_validation():
    old = memory.get_allocator_strategy()
    try:
        with pytest.warns(UserWarning):
            # backend is already up in tests -> must warn, not silently no-op
            memory.set_allocator_strategy("naive_best_fit",
                                          memory_fraction=0.5)
        assert memory.get_allocator_strategy() == "naive_best_fit"
        import os
        assert os.environ["XLA_PYTHON_CLIENT_PREALLOCATE"] == "true"
        with pytest.raises(ValueError):
            memory.set_allocator_strategy("best_fit_with_coalescing")
    finally:
        with pytest.warns(UserWarning):
            memory.set_allocator_strategy(old)


def test_flags_registered():
    got = paddle_tpu.get_flags(["FLAGS_allocator_strategy",
                                "FLAGS_fraction_of_gpu_memory_to_use"])
    assert set(got) == {"FLAGS_allocator_strategy",
                       "FLAGS_fraction_of_gpu_memory_to_use"}
