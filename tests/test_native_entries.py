"""Native C++ entries (reference paddle/fluid/train/demo/demo_trainer.cc
and inference/capi/): the C++ train binary drives a saved program pair
end-to-end without a user Python script; a C client consumes the
inference ABI shared library. Skipped when the toolchain or libpython
is unavailable."""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.native import _DIR, build_c_api, build_train_demo
from paddle_tpu.native.embed import save_train_artifacts

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _child_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _build_regression_artifacts(dirname):
    """y = x @ w + noise regression; loss must drop under SGD."""
    pt.framework.core.reset_unique_name()
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        x = pt.layers.data("x", shape=[8], dtype="float32")
        y = pt.layers.data("y", shape=[1], dtype="float32")
        pred = pt.layers.fc(x, 1)
        loss = pt.layers.reduce_mean(
            pt.layers.square(pt.layers.elementwise_sub(pred, y)))
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
    save_train_artifacts(
        dirname, main, startup,
        feeds={"x": ([16, 8], "float32", "uniform"),
               "y": ([16, 1], "float32", "linear_of:x")},
        fetch_name=loss.name)


def test_cpp_train_demo(tmp_path):
    binary = build_train_demo()
    if binary is None:
        pytest.skip("no C++ toolchain / libpython")
    model_dir = str(tmp_path / "train_model")
    _build_regression_artifacts(model_dir)
    r = subprocess.run([binary, model_dir, "20"], env=_child_env(),
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    lines = [l for l in r.stdout.splitlines() if l.startswith("step")]
    assert len(lines) == 20
    first = float(lines[0].split()[-1])
    last = float(lines[-1].split()[-1])
    assert last < first  # the C++ side also asserts via exit code
    assert "train_demo: OK" in r.stdout


def test_c_api_inference(tmp_path):
    lib = build_c_api()
    if lib is None:
        pytest.skip("no C++ toolchain / libpython")
    # export a tiny inference model
    pt.framework.core.reset_unique_name()
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        x = pt.layers.data("x", shape=[4], dtype="float32")
        out = pt.layers.fc(x, 3, act="relu")
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    model_dir = str(tmp_path / "infer_model")
    from paddle_tpu.framework.executor import scope_guard

    with scope_guard(scope):
        pt.io.save_inference_model(model_dir, ["x"], [out], exe,
                                   main_program=main)
    # reference output via the Python predictor
    from paddle_tpu.inference import Predictor

    ref = Predictor(model_dir).run(
        {"x": np.ones((2, 4), np.float32)})[0]

    # compile + run the C client against the shared library
    src = os.path.join(_DIR, "capi_demo.c")
    exe_path = str(tmp_path / "capi_demo")
    cc = subprocess.run(
        ["g++", "-O2", "-o", exe_path, src, "-I", _DIR, lib,
         f"-Wl,-rpath,{os.path.dirname(lib)}"],
        capture_output=True, text=True, timeout=180)
    # the library just built with the same g++: a demo compile error
    # is a real API/ABI bug, not a missing-toolchain condition
    assert cc.returncode == 0, f"capi_demo compile failed: {cc.stderr}"
    r = subprocess.run([exe_path, model_dir, "4"], env=_child_env(),
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "capi_demo: OK" in r.stdout
    # the C client's first output element matches the Python predictor
    line = [l for l in r.stdout.splitlines() if "output0" in l][0]
    numel = int(line.split("numel")[1].split()[0])
    first = float(line.split("first")[1].split()[0])
    assert numel == ref.size
    np.testing.assert_allclose(first, ref.reshape(-1)[0], rtol=1e-5)


def test_go_client_abi_sequence(tmp_path):
    """No Go toolchain in this image (predictor.go documents that) — so
    replay the Go client's byte-identical ABI call sequence from C
    (native/go_mirror_harness.c) against the same model the Python
    Predictor serves (VERDICT r4 #8)."""
    lib = build_c_api()
    if lib is None:
        pytest.skip("no C++ toolchain / libpython")
    pt.framework.core.reset_unique_name()
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        x = pt.layers.data("x", shape=[4], dtype="float32")
        out = pt.layers.fc(x, 3, act="relu")
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    model_dir = str(tmp_path / "go_model")
    from paddle_tpu.framework.executor import scope_guard
    with scope_guard(scope):
        pt.io.save_inference_model(model_dir, ["x"], [out], exe,
                                   main_program=main)
    from paddle_tpu.inference import Predictor
    ref = Predictor(model_dir).run({"x": np.ones((2, 4), np.float32)})[0]

    src = os.path.join(_DIR, "go_mirror_harness.c")
    exe_path = str(tmp_path / "go_mirror")
    cc = subprocess.run(
        ["g++", "-O2", "-o", exe_path, src, "-I", _DIR, lib,
         f"-Wl,-rpath,{os.path.dirname(lib)}"],
        capture_output=True, text=True, timeout=180)
    assert cc.returncode == 0, f"go_mirror compile failed: {cc.stderr}"
    r = subprocess.run([exe_path, model_dir, "4"], env=_child_env(),
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "go_mirror: OK" in r.stdout
    line = [l for l in r.stdout.splitlines() if "go_mirror: numel" in l][0]
    assert int(line.split("numel")[1].split()[0]) == ref.size
    first = float(line.split("first")[1].split()[0])
    np.testing.assert_allclose(first, float(np.asarray(ref).reshape(-1)[0]),
                               rtol=1e-5, atol=1e-6)
