"""Smoke + numeric tests for the 2.0-convenience layer batch
(reference fluid.layers / paddle.tensor surface)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _run(fetches, feed=None):
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    return [np.asarray(v) for v in
            exe.run(feed=feed or {}, fetch_list=fetches)]


def test_creation_and_clamp():
    a = layers.full([2, 3], 2.5)
    b = layers.arange(1, 7, 2, dtype="float32")
    x = layers.data("x", [3], append_batch_size=False)
    c = layers.full_like(x, 7.0)
    d = layers.clamp(x, min=-0.5, max=0.5)
    fa, fb, fc, fd = _run([a, b, c, d],
                          {"x": np.array([-1.0, 0.2, 3.0], "float32")})
    np.testing.assert_allclose(fa, np.full((2, 3), 2.5))
    np.testing.assert_allclose(fb, [1, 3, 5])
    np.testing.assert_allclose(fc, [7, 7, 7])
    np.testing.assert_allclose(fd, [-0.5, 0.2, 0.5])


def test_indexing_and_sorting():
    xv = np.array([[3.0, 1.0, 2.0], [6.0, 5.0, 4.0]], "float32")
    x = layers.data("x", [2, 3], append_batch_size=False)
    idx = layers.data("i", [2], dtype="int64", append_batch_size=False)
    sel = layers.index_select(x, idx, axis=1)
    rolled = layers.roll(x, 1, axis=1)
    flipped = layers.flip(x, axis=1)
    vals, order = layers.sort(x, axis=1)
    ss = layers.strided_slice(x, axes=[1], starts=[0], ends=[3],
                              strides=[2])
    outs = _run([sel, rolled, flipped, vals, order, ss],
                {"x": xv, "i": np.array([2, 0], "int64")})
    np.testing.assert_allclose(outs[0], xv[:, [2, 0]])
    np.testing.assert_allclose(outs[1], np.roll(xv, 1, 1))
    np.testing.assert_allclose(outs[2], xv[:, ::-1])
    np.testing.assert_allclose(outs[3], np.sort(xv, 1))
    np.testing.assert_allclose(outs[4], np.argsort(xv, 1))
    np.testing.assert_allclose(outs[5], xv[:, ::2])


def test_linalg_and_diag():
    a = np.random.RandomState(0).rand(3, 4).astype("float32")
    b = np.random.RandomState(1).rand(4, 2).astype("float32")
    x = layers.data("a", [3, 4], append_batch_size=False)
    y = layers.data("b", [4, 2], append_batch_size=False)
    base = layers.data("c", [3, 2], append_batch_size=False)
    mm = layers.mm(x, y)
    am = layers.addmm(base, x, y, beta=0.5, alpha=2.0)
    tt = layers.t(y)
    v = layers.data("v", [4], append_batch_size=False)
    dg = layers.diag(v)
    dgv = layers.diag(x)
    cv = np.random.RandomState(2).rand(3, 2).astype("float32")
    vv = np.array([1., 2., 3., 4.], "float32")
    outs = _run([mm, am, tt, dg, dgv],
                {"a": a, "b": b, "c": cv, "v": vv})
    np.testing.assert_allclose(outs[0], a @ b, rtol=1e-5)
    np.testing.assert_allclose(outs[1], 0.5 * cv + 2.0 * (a @ b),
                               rtol=1e-5)
    np.testing.assert_allclose(outs[2], b.T)
    np.testing.assert_allclose(outs[3], np.diag(vv))
    np.testing.assert_allclose(outs[4], np.diag(a), rtol=1e-6)


def test_finite_predicates_and_shard_index():
    x = layers.data("x", [3], append_batch_size=False)
    fin = layers.isfinite(x)
    hn = layers.has_nan(x)
    hi = layers.has_inf(x)
    ids = layers.data("ids", [4], dtype="int64", append_batch_size=False)
    si = layers.shard_index(ids, index_num=20, nshards=2, shard_id=1)
    outs = _run([fin, hn, hi, si],
                {"x": np.array([1.0, np.nan, 2.0], "float32"),
                 "ids": np.array([3, 10, 15, 19], "int64")})
    assert bool(outs[0].reshape(-1)[0]) is False
    assert bool(outs[1].reshape(-1)[0]) is True
    assert bool(outs[2].reshape(-1)[0]) is False
    np.testing.assert_array_equal(outs[3], [-1, 0, 5, 9])


def test_nn_conveniences():
    a = np.random.RandomState(3).rand(2, 5).astype("float32") + 0.1
    b = np.random.RandomState(4).rand(2, 5).astype("float32") + 0.1
    x = layers.data("x", [2, 5], append_batch_size=False)
    y = layers.data("y", [2, 5], append_batch_size=False)
    cs = layers.cos_sim(x, y)
    nm = layers.norm(x, p=2, axis=1)
    ds = layers.dist(x, y, p=2)
    outs = _run([cs, nm, ds], {"x": a, "y": b})
    ref_cs = (a * b).sum(1) / np.sqrt((a * a).sum(1) * (b * b).sum(1))
    np.testing.assert_allclose(outs[0], ref_cs, rtol=1e-5)
    np.testing.assert_allclose(outs[1], np.linalg.norm(a, axis=1),
                               rtol=1e-5)
    np.testing.assert_allclose(outs[2].reshape(-1)[0],
                               np.linalg.norm((a - b).ravel()),
                               rtol=1e-5)


def test_image_conveniences():
    img = np.random.RandomState(5).rand(1, 4, 4, 4).astype("float32")
    x = layers.data("img", [1, 4, 4, 4], append_batch_size=False)
    p2 = layers.pad2d(x, [1, 1, 2, 2], pad_value=0.5)
    rs = layers.image_resize(x, out_shape=[8, 8], resample="NEAREST",
                             align_corners=False)
    sd = layers.space_to_depth(x, 2)
    small = layers.data("small", [1, 4, 2, 2], append_batch_size=False)
    pcl = layers.pad_constant_like(x, small, pad_value=0.0)
    cr = layers.crop_tensor(x, shape=[1, 4, 2, 2], offsets=[0, 0, 1, 1])
    sv = np.ones((1, 4, 2, 2), "float32")
    outs = _run([p2, rs, sd, pcl, cr], {"img": img, "small": sv})
    assert outs[0].shape == (1, 4, 6, 8)
    np.testing.assert_allclose(outs[0][:, :, 0, :], 0.5)
    np.testing.assert_allclose(outs[1], np.repeat(np.repeat(img, 2, 2),
                                                  2, 3))
    assert outs[2].shape == (1, 16, 2, 2)
    assert outs[3].shape == (1, 4, 4, 4) and outs[3][0, 0, 3, 3] == 0
    np.testing.assert_allclose(outs[4], img[:, :, 1:3, 1:3])


def test_expand_as_and_grads_flow():
    from paddle_tpu import optimizer
    x = layers.data("x", [1, 4], append_batch_size=False)
    tgt = layers.data("t", [3, 4], append_batch_size=False)
    e = layers.expand_as(x, tgt)
    loss = layers.mean(layers.square_error_cost(e, tgt))
    optimizer.SGDOptimizer(0.1).minimize(loss)  # grads flow through
    out, = _run([e], {"x": np.ones((1, 4), "float32"),
                      "t": np.zeros((3, 4), "float32")})
    np.testing.assert_allclose(out, np.ones((3, 4)))
