"""Dygraph deployment: jit.save / TracedLayer.save_inference_model /
jit.load round trips + py_func op (VERDICT r4 #5).

Reference: python/paddle/fluid/dygraph/jit.py:159 (save / TracedLayer),
operators/py_func_op.cc:44.
"""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.dygraph import Linear, to_variable
from op_test import OpCase, run_case

R = np.random.RandomState


def _train_tiny_layer():
    """A dygraph Linear trained a few steps; returns (layer, x, ref_out)."""
    with pt.dygraph.guard():
        layer = Linear(4, 2)
        opt = pt.optimizer.SGDOptimizer(
            0.1, parameter_list=layer.parameters())
        x = R(0).randn(8, 4).astype("float32")
        target = R(1).randn(8, 2).astype("float32")
        for _ in range(5):
            out = layer(to_variable(x))
            loss = pt.layers.reduce_mean(
                pt.layers.square(out - to_variable(target)))
            loss.backward()
            opt.minimize(loss)
            layer.clear_gradients()
        ref = layer(to_variable(x)).numpy()
    return layer, x, ref


def test_traced_layer_save_inference_model(tmp_path):
    layer, x, ref = _train_tiny_layer()
    d = str(tmp_path / "traced")
    with pt.dygraph.guard():
        out, traced = pt.dygraph.TracedLayer.trace(
            layer, [to_variable(x)])
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5,
                                   atol=1e-6)
        traced.save_inference_model(d)
    # reload through the static io path in THIS process
    exe = pt.Executor()
    scope = pt.Scope()
    with pt.scope_guard(scope) if hasattr(pt, "scope_guard") else \
            _scope_guard(scope):
        prog, feeds, fetches = pt.io.load_inference_model(d, exe)
        got, = exe.run(prog, feed={feeds[0]: x}, fetch_list=fetches,
                       scope=scope)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-5,
                               atol=1e-6)


def _scope_guard(scope):
    from paddle_tpu.framework.executor import scope_guard
    return scope_guard(scope)


def test_jit_save_load_roundtrip(tmp_path):
    layer, x, ref = _train_tiny_layer()
    d = str(tmp_path / "jitsaved")
    with pt.dygraph.guard():
        pt.jit.save(layer, d,
                    input_spec=[pt.static.InputSpec([8, 4], "float32")]
                    if hasattr(pt, "static") else [x])
        loaded = pt.jit.load(d)
        got = loaded(to_variable(x))
        np.testing.assert_allclose(got.numpy(), ref, rtol=1e-5,
                                   atol=1e-6)


def test_jit_save_polymorphic_batch(tmp_path):
    """InputSpec([None, D]) must export a batch-polymorphic program:
    the saved feed var keeps -1 (not a frozen sample size of 1), so one
    export serves any batch (ADVICE.md jit.py:172 finding — the
    prerequisite for serving exported generative models)."""
    layer, x, ref = _train_tiny_layer()
    d = str(tmp_path / "poly")
    with pt.dygraph.guard():
        pt.jit.save(layer, d,
                    input_spec=[pt.static.InputSpec([None, 4],
                                                    "float32")])
        loaded = pt.jit.load(d)
        for b in (1, 3, 8):
            got = loaded(to_variable(x[:b]))
            np.testing.assert_allclose(got.numpy(), ref[:b],
                                       rtol=1e-5, atol=1e-6)
    # the static io path agrees on the exported contract
    exe = pt.Executor()
    scope = pt.Scope()
    with _scope_guard(scope):
        prog, feeds, fetches = pt.io.load_inference_model(d, exe)
        v = prog.global_block().var(feeds[0])
        assert v.shape[0] == -1, \
            f"batch dim frozen to {v.shape[0]} in the export"
        got, = exe.run(prog, feed={feeds[0]: x[:5]},
                       fetch_list=fetches, scope=scope)
    np.testing.assert_allclose(np.asarray(got), ref[:5], rtol=1e-5,
                               atol=1e-6)


def test_jit_save_serves_in_fresh_process(tmp_path):
    """Train dygraph -> jit.save -> a clean process serves it through
    BOTH jit.load and inference.Predictor (the deployment promise)."""
    layer, x, ref = _train_tiny_layer()
    d = str(tmp_path / "deploy")
    with pt.dygraph.guard():
        pt.jit.save(layer, d, input_spec=[x])
    np.save(str(tmp_path / "x.npy"), x)
    child = textwrap.dedent(f"""
        import os
        os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \\
            " --xla_force_host_platform_device_count=8"
        import jax; jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.inference import Predictor
        xs = np.load({str(tmp_path / 'x.npy')!r})
        out1 = Predictor({d!r}).run({{"__ts_arg_0": xs}})[0]
        with pt.dygraph.guard():
            out2 = pt.jit.load({d!r})(xs).numpy()
        np.save({str(tmp_path / 'o1.npy')!r}, np.asarray(out1))
        np.save({str(tmp_path / 'o2.npy')!r}, out2)
        print("DEPLOYED")
    """)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get(
        "PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", child], env=env,
                       capture_output=True, text=True, timeout=240)
    assert "DEPLOYED" in r.stdout, (r.stdout, r.stderr)
    np.testing.assert_allclose(np.load(str(tmp_path / "o1.npy")), ref,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.load(str(tmp_path / "o2.npy")), ref,
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# py_func
# ---------------------------------------------------------------------------
def test_py_func_forward():
    x = R(2).randn(3, 4).astype("float32")
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        xv = pt.layers.data(name="pfx", shape=[4], dtype="float32")
        block = main.global_block()
        out = block.create_var(name="pf_out", shape=[3, 4],
                               dtype="float32")
        pt.layers.py_func(lambda a: np.tanh(a) * 2.0, xv, out)
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    got, = exe.run(main, feed={"pfx": x}, fetch_list=["pf_out"],
                   scope=scope)
    np.testing.assert_allclose(np.asarray(got), np.tanh(x) * 2.0,
                               rtol=1e-5, atol=1e-6)


def test_py_func_backward():
    """backward_func supplies the gradient; compare to the analytic
    grad of sum(w * tanh(x)*2)."""
    x = R(3).randn(3, 4).astype("float32")
    w = R(4).uniform(0.5, 1.5, (3, 4)).astype("float32")
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        xv = pt.layers.data(name="pbx", shape=[4], dtype="float32")
        xv.stop_gradient = False
        block = main.global_block()
        out = block.create_var(name="pb_out", shape=[3, 4],
                               dtype="float32")
        pt.layers.py_func(
            lambda a: np.tanh(a) * 2.0, xv, out,
            backward_func=lambda a, o, do: do * 2.0
            * (1.0 - np.tanh(a) ** 2))
        wv = pt.layers.data(name="pbw", shape=[4], dtype="float32")
        loss = pt.layers.reduce_sum(
            pt.layers.elementwise_mul(out, wv))
        from paddle_tpu.framework.backward import append_backward
        append_backward(loss)
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    g, = exe.run(main, feed={"pbx": x, "pbw": w},
                 fetch_list=["pbx@GRAD"], scope=scope)
    want = w * 2.0 * (1.0 - np.tanh(x) ** 2)
    np.testing.assert_allclose(np.asarray(g), want, rtol=1e-5,
                               atol=1e-6)


def test_py_func_multi_io():
    a = R(5).randn(2, 3).astype("float32")
    b = R(6).randn(2, 3).astype("float32")
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        av = pt.layers.data(name="ma", shape=[3], dtype="float32")
        bv = pt.layers.data(name="mb", shape=[3], dtype="float32")
        block = main.global_block()
        o1 = block.create_var(name="mo1", shape=[2, 3],
                              dtype="float32")
        o2 = block.create_var(name="mo2", shape=[2, 3],
                              dtype="float32")
        pt.layers.py_func(lambda p, q: (p + q, p * q), [av, bv],
                          [o1, o2])
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    g1, g2 = exe.run(main, feed={"ma": a, "mb": b},
                     fetch_list=["mo1", "mo2"], scope=scope)
    np.testing.assert_allclose(np.asarray(g1), a + b, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g2), a * b, rtol=1e-6)


# ---------------------------------------------------------------------------
# run_program + distributed_lookup_table (catalog completions)
# ---------------------------------------------------------------------------
def test_run_program_op():
    x = R(7).randn(2, 3).astype("float32")
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        xv = pt.layers.data(name="rpx", shape=[3], dtype="float32")
        block = main.global_block()
        sub = main._create_block()
        with pt.program_guard(main, startup):
            pass
        # build the captured block's ops directly
        sub_out = sub.create_var(name="rp_out", shape=[2, 3],
                                 dtype="float32")
        sub.append_op("scale", inputs={"X": [xv.name]},
                      outputs={"Out": ["rp_out"]},
                      attrs={"scale": 3.0, "bias": 1.0})
        main._rollback()
        block.create_var(name="rp_out", shape=[2, 3], dtype="float32")
        block.append_op("run_program", inputs={"X": [xv.name]},
                        outputs={"Out": ["rp_out"]},
                        attrs={"sub_block": sub.idx})
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    got, = exe.run(main, feed={"rpx": x}, fetch_list=["rp_out"],
                   scope=scope)
    np.testing.assert_allclose(np.asarray(got), x * 3.0 + 1.0,
                               rtol=1e-6)


def test_distributed_lookup_table():
    w = R(8).randn(10, 4).astype("float32")
    ids1 = np.array([[1], [3]], "int64")
    ids2 = np.array([[0], [9]], "int64")
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        block = main.global_block()
        for n, a in (("dlt_w", w), ("dlt_i1", ids1), ("dlt_i2", ids2)):
            block.create_var(name=n, shape=a.shape, dtype=str(a.dtype),
                             is_data=True)
        block.append_op(
            "distributed_lookup_table",
            inputs={"Ids": ["dlt_i1", "dlt_i2"], "W": ["dlt_w"]},
            outputs={"Outputs": ["dlt_o1", "dlt_o2"]},
            attrs={"table_names": ["t0"]})
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    o1, o2 = exe.run(
        main, feed={"dlt_w": w, "dlt_i1": ids1, "dlt_i2": ids2},
        fetch_list=["dlt_o1", "dlt_o2"], scope=scope)
    np.testing.assert_allclose(np.asarray(o1), w[[1, 3]], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(o2), w[[0, 9]], rtol=1e-6)
