"""Generation serving tests: KV-cached decode bit-exactness, slot-based
continuous batching vs FIFO head-run static batching, shedding
semantics, and the HTTP ``/generate`` front end.

The load-bearing contracts:

* **Bit-exactness** — cached decode logits must equal the uncached
  full-forward logits step-for-step at tolerance 0 (``np.array_equal``)
  with requests of ragged lengths decoding *concurrently* in the slot
  grid.  Both sides pin ``attn_impl="xla"`` (the einsum formulation
  ``cached_attention`` mirrors); the "auto" blockwise-scan softmax is a
  different reduction order and only agrees to ~1e-7.
* **Continuous batching ≥ 2x static** — on a deterministic long-tail
  workload (three short sequences and one long per four slots), slot
  reclaim must finish the same token set in under half the wall time of
  batch-drain scheduling, at no worse p99 (ISSUE 7 acceptance bar).
"""
import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.models.llama import build_llama_forward
from paddle_tpu.serving import (GenerationEngine, OverloadedError,
                                ServingEngine, batcher, serve)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# tiny GQA config shared by the module fixture (kv_heads < heads so the
# repeat-interleave cache expansion is under test, not just MHA)
MODEL = dict(vocab_size=61, hidden=32, num_layers=2, num_heads=4,
             num_kv_heads=2, intermediate=64)


@pytest.fixture(scope="module")
def gen_engine():
    """Shared KV-cached engine: 3 slots, keep_logits for the
    bit-exactness comparisons, attn_impl pinned to the einsum
    formulation."""
    eng = GenerationEngine(MODEL, num_slots=3, max_seq_len=48,
                           max_new_tokens=8, keep_logits=True,
                           attn_impl="xla", seed=0, queue_cap=64,
                           deadline_ms=600000.0)
    yield eng
    eng.close()


def _reference_logits(eng, token_ids):
    """Uncached full causal forward over ``token_ids`` sharing the
    engine's scope weights; returns [S, V] logits (rows past
    ``len(token_ids)`` are pad garbage).

    The forward runs right-padded at the engine's fixed
    ``max_seq_len`` — causality makes the pad tail inert, and the
    fixed contraction length matches the decode path's cache-width
    reductions bit-for-bit.  A reference rebuilt at every request's
    exact length drifts ~5e-7 on threaded CPU backends: XLA picks a
    different reduction tiling per shape, which is a different
    accumulation order, not a decode-path bug."""
    S = eng.max_seq_len
    assert len(token_ids) <= S
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        _feeds, fetches = build_llama_forward(
            1, S, name=eng.name, attn_impl="xla", **MODEL)
    padded = np.zeros((S,), "int64")
    padded[:len(token_ids)] = token_ids
    out = pt.Executor().run(
        main, feed={"input_ids": padded[None]},
        fetch_list=[fetches["logits"]], scope=eng.scope)
    return out[0][0]


# ---------------------------------------------------------------------------
# batcher: prompt buckets + ragged-length pad/stack round trip
# ---------------------------------------------------------------------------

def test_prompt_bucket_policy():
    assert batcher.prompt_buckets(64) == (8, 16, 32, 64)
    assert batcher.prompt_buckets(48) == (8, 16, 32, 48)
    assert batcher.prompt_buckets(64, buckets=[16, 64]) == (16, 64)
    assert batcher.prompt_bucket_for(9, (8, 16, 32)) == 16
    assert batcher.prompt_bucket_for(8, (8, 16, 32)) == 8
    with pytest.raises(ValueError):
        batcher.prompt_bucket_for(33, (8, 16, 32))
    with pytest.raises(ValueError):
        batcher.prompt_buckets(64, buckets=[16, 128])  # > max_len


def test_pad_prompt():
    ids = np.arange(1, 6)
    padded = batcher.pad_prompt(ids, 8)
    assert padded.shape == (8,) and padded.dtype == np.int64
    assert np.array_equal(padded[:5], ids)
    assert np.all(padded[5:] == 0)
    with pytest.raises(ValueError):
        batcher.pad_prompt(np.arange(9), 8)


def test_pad_stack_split_rows_ragged_lengths():
    """Requests with ragged sequence lengths ride one batch: each pads
    to the shared bucket, pad_stack concatenates the ragged row counts,
    split_rows is a bit-exact inverse."""
    rng = np.random.RandomState(0)
    raw = [rng.randint(1, 50, size=n) for n in (3, 9, 14)]
    bucket_len = 16
    reqs = [(batcher.pad_prompt(ids, bucket_len)[None].repeat(rows, 0),)
            for ids, rows in zip(raw, (1, 3, 2))]
    padded, real_rows = batcher.pad_stack(reqs, 8)
    assert real_rows == 6
    assert padded[0].shape == (8, bucket_len)
    # pad rows replicate row 0 (a real row: no NaN/garbage reaches XLA)
    assert np.array_equal(padded[0][6], padded[0][0])
    outs = [padded[0] * 2]  # any row-wise "model" output
    split = batcher.split_rows(outs, [1, 3, 2])
    assert [s[0].shape[0] for s in split] == [1, 3, 2]
    for req, got in zip(reqs, split):
        assert np.array_equal(got[0], req[0] * 2)


# ---------------------------------------------------------------------------
# KV-cache decode ops
# ---------------------------------------------------------------------------

def test_kv_cache_write_ragged_positions():
    """Per-row dynamic offsets: each batch row's fresh K/V lands at its
    own cache offset, other cache rows untouched."""
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        block = main.global_block()
        cache = block.create_var(name="t_cache", persistable=True,
                                 shape=[2, 1, 8, 2], dtype="float32",
                                 stop_gradient=True)
        new = layers.data("new", [2, 1, 1, 2], dtype="float32",
                          append_batch_size=False)
        positions = layers.data("positions", [2], dtype="int32",
                                append_batch_size=False)
        out = layers.kv_cache_write(cache, new, positions)
    scope = pt.Scope()
    base = np.arange(32, dtype="float32").reshape(2, 1, 8, 2)
    scope.set_var("t_cache", base.copy())
    fresh = np.full((2, 1, 1, 2), -1.0, "float32")
    got = pt.Executor().run(
        main, feed={"new": fresh, "positions": np.array([0, 3], "int32")},
        fetch_list=[out], scope=scope)[0]
    want = base.copy()
    want[0, 0, 0] = -1.0
    want[1, 0, 3] = -1.0
    assert np.array_equal(got, want)


# ---------------------------------------------------------------------------
# bit-exactness: cached decode == uncached full forward, tolerance 0
# ---------------------------------------------------------------------------

def test_cached_decode_bitexact_concurrent_ragged(gen_engine):
    """Three prompts of ragged lengths (crossing prefill buckets)
    decode CONCURRENTLY in the slot grid — per-slot positions differ
    every step — and every request's per-step next-token logits are
    bit-equal to its own uncached full forward."""
    eng = gen_engine
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, MODEL["vocab_size"], size=n).tolist()
               for n in (3, 9, 14)]  # buckets 8, 16, 16
    steps = [6, 4, 7]
    futs = [eng.submit(p, n) for p, n in zip(prompts, steps)]
    results = [f.result(120) for f in futs]
    for prompt, n, res in zip(prompts, steps, results):
        assert res["finish"] == "length" and res["steps"] == n - 1
        assert len(res["tokens"]) == n == len(res["logits"])
        ref = _reference_logits(eng, prompt + res["tokens"][:-1])
        for i, got in enumerate(res["logits"]):
            want = ref[len(prompt) - 1 + i]
            assert np.array_equal(np.asarray(got), want), \
                f"step {i}: cached decode drifted from the uncached " \
                f"forward (max |d|=" \
                f"{np.abs(np.asarray(got) - want).max()})"
        # greedy argmax over bit-equal logits: token streams agree too
        assert res["tokens"] == [int(np.argmax(ref[len(prompt) - 1 + i]))
                                 for i in range(n)]


def test_eos_frees_slot(gen_engine):
    """EOS finish: re-run a known stream with eos_id set to its second
    token — generation stops there with finish='eos'."""
    eng = gen_engine
    prompt = [5, 11, 2, 9]
    base = eng.generate(prompt, 6)
    assert base["finish"] == "length"
    eos = base["tokens"][1]
    old = eng.eos_id
    try:
        eng.eos_id = eos
        res = eng.generate(prompt, 6)
    finally:
        eng.eos_id = old
    assert res["finish"] == "eos"
    assert res["tokens"] == base["tokens"][:2]


def test_cache_full_finish(gen_engine):
    """A budget beyond the cache capacity left after the prompt decodes
    until the slot cache fills: finish='cache_full' with exactly
    max_seq_len - prompt_len + 1 tokens (the last written cache index
    is max_seq_len - 1 — the out-of-bounds guard fires BEFORE a write
    could clamp onto the last row)."""
    eng = gen_engine
    prompt = [5, 11, 2]
    res = eng.generate(prompt, eng.max_seq_len * 2)
    assert res["finish"] == "cache_full"
    assert len(res["tokens"]) == eng.max_seq_len - len(prompt) + 1
    # the capped stream is a prefix of what a roomier budget yields
    # step-for-step (same caches, same weights): compare via logits
    # against the uncached forward on the LAST step, whose cache row
    # sits at max_seq_len - 1
    ref = _reference_logits(eng, prompt + res["tokens"][:-1])
    assert np.array_equal(np.asarray(res["logits"][-1]),
                          ref[len(prompt) - 1 + len(res["tokens"]) - 1])


def test_prompt_validation(gen_engine):
    with pytest.raises(ValueError):
        gen_engine.submit([])
    with pytest.raises(ValueError):
        gen_engine.submit([[1, 2], [3, 4]])
    with pytest.raises(ValueError):
        gen_engine.submit([0.5, 1.5])
    with pytest.raises(ValueError):  # beyond the largest prefill bucket
        gen_engine.submit(list(range(1, eng_max(gen_engine) + 2)))


def eng_max(eng):
    return eng.max_prompt_len


def test_introspection(gen_engine):
    eng = gen_engine
    s = eng.stats()
    assert s["slots"] == 3 and s["queue_cap"] == 64
    assert s["counters"]["served"] >= 4
    assert s["counters"]["decode_steps"] > 0
    # cache accounting: slots * n_kv * max_seq * head_dim * 4B * 2KV * L
    head_dim = MODEL["hidden"] // MODEL["num_heads"]
    want = (3 * MODEL["num_kv_heads"] * 48 * head_dim * 4
            * 2 * MODEL["num_layers"])
    assert eng.kv_cache_bytes == want == s["kv_cache_bytes"]
    intro = eng.introspect()
    assert intro["decode_executables"]["entries"], \
        "decode executor compiled nothing?"
    man = intro["decode_manifest"]
    if man is not None:  # backend exposes cost analysis (CPU/TPU do)
        assert man["flops"] > 0
        assert intro["decode_mfu"] is None or intro["decode_mfu"] > 0


# ---------------------------------------------------------------------------
# continuous batching >= 2x FIFO head-run static batching
# ---------------------------------------------------------------------------

def _run_workload(continuous):
    """Deterministic long-tail workload (3 short + 1 long per claim
    group of 4): all requests queued BEFORE the scheduler starts, so
    claim order — and therefore the static grouping — is exact.  The
    long sequences (88 tokens vs 2) put the structural step ratio near
    3.2x, so the measured wall-clock 2x bar survives per-dispatch
    overhead jitter on a loaded shared host."""
    eng = GenerationEngine(MODEL, num_slots=4, max_seq_len=96,
                           max_new_tokens=88, continuous=continuous,
                           autostart=False, seed=0, queue_cap=64,
                           deadline_ms=600000.0, attn_impl="xla")
    eng.warmup()  # compiles off the timed path
    prompts, lens = [], []
    rng = np.random.RandomState(3)
    for _g in range(4):
        for n in (2, 2, 2, 88):
            prompts.append(rng.randint(
                1, MODEL["vocab_size"], size=4).tolist())
            lens.append(n)
    t0 = time.monotonic()
    futs = [eng.submit(p, n) for p, n in zip(prompts, lens)]
    eng.start()
    results = [f.result(300) for f in futs]
    wall = time.monotonic() - t0
    tokens = sum(len(r["tokens"]) for r in results)
    p99 = float(np.percentile([r["total_ms"] for r in results], 99))
    stats = eng.stats()
    eng.close()
    assert tokens == sum(lens)  # every request ran to its budget
    return tokens / wall, p99, stats


def test_continuous_2x_over_static():
    """The ISSUE 7 acceptance bar: >= 2x tokens/sec at no worse p99,
    plus the noise-free structural form — the static scheduler needs
    over 2x the decode steps for the same token set because drained
    slots idle until the group's longest sequence finishes.  The
    structural assertions are deterministic and never retried; the
    wall-clock ratio gets one retry because a CPU-contended host can
    inflate either side's dispatch cost asymmetrically."""
    for attempt in (1, 2):
        tps_static, p99_static, st_static = _run_workload(False)
        tps_cont, p99_cont, st_cont = _run_workload(True)
        steps_static = st_static["counters"]["decode_steps"]
        steps_cont = st_cont["counters"]["decode_steps"]
        # structural (deterministic): batch drain pays max(lens) per
        # group
        assert steps_static >= 2 * steps_cont, \
            f"static {steps_static} steps vs continuous {steps_cont}"
        assert st_cont["counters"]["slot_reclaims"] > 0
        assert st_static["counters"]["slot_reclaims"] == 0
        if tps_cont >= 2.0 * tps_static and p99_cont <= p99_static:
            break
        if attempt == 2:
            # measured (the published metric): >= 2x tokens/sec, p99
            # no worse
            assert tps_cont >= 2.0 * tps_static, \
                f"continuous {tps_cont:.0f} tok/s < 2x static " \
                f"{tps_static:.0f}"
            assert p99_cont <= p99_static, \
                f"continuous p99 {p99_cont:.0f}ms worse than static " \
                f"{p99_static:.0f}ms"


# ---------------------------------------------------------------------------
# admission control / shedding
# ---------------------------------------------------------------------------

def test_queue_full_and_draining_shed():
    eng = GenerationEngine(MODEL, num_slots=1, max_seq_len=48,
                           queue_cap=2, autostart=False, seed=0,
                           deadline_ms=600000.0)
    f1 = eng.submit([1, 2, 3])
    f2 = eng.submit([4, 5])
    with pytest.raises(OverloadedError) as ei:
        eng.submit([6])
    assert ei.value.reason == "queue_full"
    eng.close(drain=False)
    for f in (f1, f2):
        with pytest.raises(OverloadedError) as ei:
            f.result(5)
        assert ei.value.reason == "draining"
    with pytest.raises(OverloadedError) as ei:
        eng.submit([7])
    assert ei.value.reason == "draining"
    # queue_full + two queued futures shed at close + the post-close
    # submit = 4 sheds
    assert eng.stats()["counters"]["shed"] == 4


def test_deadline_shed_before_claim():
    eng = GenerationEngine(MODEL, num_slots=1, max_seq_len=48,
                           queue_cap=8, autostart=False, seed=0,
                           deadline_ms=1.0)
    futs = [eng.submit([1, 2, 3]), eng.submit([4, 5])]
    time.sleep(0.05)  # both requests outlive the 1ms deadline queued
    eng.start()
    for f in futs:
        with pytest.raises(OverloadedError) as ei:
            f.result(30)
        assert ei.value.reason == "deadline"
    eng.close()


# ---------------------------------------------------------------------------
# HTTP front end: POST /generate
# ---------------------------------------------------------------------------

def _post(url, doc):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.status, json.loads(r.read())


def _tiny_predictor():
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        out = layers.fc(x, 2, name="gen_http_fc")
    scope = pt.Scope()
    pt.Executor().run(startup, scope=scope)
    from paddle_tpu.inference import Predictor
    return Predictor(main, ["x"], [out], scope=scope)


def test_http_generate(gen_engine):
    eng = ServingEngine(_tiny_predictor(), workers=1, max_batch=2,
                        max_delay_ms=1.0, deadline_ms=60000)
    srv = serve(eng)
    try:
        # no generator attached yet -> 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(srv.url + "/generate", {"prompt": [1, 2, 3]})
        assert ei.value.code == 404

        eng.attach_generator(gen_engine)
        code, doc = _post(srv.url + "/generate",
                          {"prompt": [3, 1, 4, 1, 5], "max_new_tokens": 4})
        assert code == 200
        ref = gen_engine.generate([3, 1, 4, 1, 5], 4)
        assert doc["tokens"] == ref["tokens"]
        assert doc["prompt_len"] == 5 and doc["finish"] == "length"
        assert "ms" in doc and "queue_wait_ms" in doc

        # malformed bodies -> 400
        for bad in ({"prompt": "abc"}, {"nope": 1},
                    {"prompt": list(range(1, 200))}):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(srv.url + "/generate", bad)
            assert ei.value.code == 400, bad

        # generation stats ride /healthz and /statusz
        with urllib.request.urlopen(srv.url + "/healthz",
                                    timeout=30) as r:
            hz = json.loads(r.read())
        assert hz["generation"]["counters"]["served"] >= 1
        with urllib.request.urlopen(srv.url + "/statusz",
                                    timeout=30) as r:
            sz = json.loads(r.read())
        assert "generator" in sz["engine"]
    finally:
        eng.generator = None  # module fixture owns the generator
        srv.close()
        eng.close()


# ---------------------------------------------------------------------------
# loadgen --generate CLI
# ---------------------------------------------------------------------------

def test_prompt_maker_distributions():
    """Deterministic factory; bimodal preserves the requested mean but
    carries a heavier tail than geometric (the grid's longest draw is
    what static batch-drain scheduling pays for)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "lg", os.path.join(REPO, "tools", "serving_loadgen.py"))
    lg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lg)
    for dist in ("geometric", "bimodal"):
        mk = lg.prompt_maker(64, 4, 8, 16.0, 128, pool=512, dist=dist)
        mk2 = lg.prompt_maker(64, 4, 8, 16.0, 128, pool=512, dist=dist)
        lens = [mk(i)[1] for i in range(512)]
        assert lens == [mk2(i)[1] for i in range(512)]  # deterministic
        assert all(1 <= n <= 128 for n in lens)
        assert abs(np.mean(lens) - 16.0) < 4.0, (dist, np.mean(lens))
        p = mk(3)[0]
        assert p.dtype == np.int64 and 4 <= p.size <= 8
        assert p.min() >= 1 and p.max() < 64
    with pytest.raises(ValueError):
        lg.prompt_maker(64, 4, 8, 16.0, 128, dist="zipf")


@pytest.mark.slow
def test_loadgen_generate_cli(tmp_path):
    out = tmp_path / "rep.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "serving_loadgen.py"),
         "--generate", "--mode", "closed", "--requests", "6",
         "--concurrency", "3", "--gen-slots", "2", "--gen-max-seq",
         "32", "--gen-out-mean", "4", "--gen-out-max", "8",
         "--gen-hidden", "32", "--gen-vocab", "64",
         "--out", str(out)],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    rep = json.loads(out.read_text())
    assert rep["ok"] == 6 and rep["generated_tokens"] > 0
    assert rep["tokens_per_sec"] > 0
    assert rep["engine"]["counters"]["served"] == 6
