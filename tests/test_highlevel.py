"""High-level API tests: metrics module, 2.0 namespaces, hapi Model.

Reference analogs: tests/unittests/test_metrics.py, test_model.py
(hapi), and the paddle 2.0 namespace surface.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import metric, metrics, nn, optimizer
from paddle_tpu.reader import TensorDataset


# ---------------------------------------------------------------------------
# fluid metrics
# ---------------------------------------------------------------------------
def test_accuracy_metric_weighted_stream():
    m = metrics.Accuracy()
    m.update(0.8, weight=10)
    m.update(0.6, weight=30)
    np.testing.assert_allclose(m.eval(), (8 + 18) / 40)
    m.reset()
    with pytest.raises(ValueError):
        m.eval()


def test_precision_recall():
    preds = np.array([1, 1, 0, 1, 0])
    labels = np.array([1, 0, 0, 1, 1])
    p = metrics.Precision()
    r = metrics.Recall()
    p.update(preds, labels)
    r.update(preds, labels)
    np.testing.assert_allclose(p.eval(), 2 / 3)   # tp=2, fp=1
    np.testing.assert_allclose(r.eval(), 2 / 3)   # tp=2, fn=1


def test_auc_matches_exact():
    rng = np.random.RandomState(0)
    pos = rng.uniform(0.4, 1.0, 200)
    neg = rng.uniform(0.0, 0.6, 200)
    preds = np.concatenate([pos, neg])
    labels = np.concatenate([np.ones(200), np.zeros(200)]).astype("int64")
    m = metrics.Auc()
    m.update(preds, labels)
    # exact AUC by rank statistic
    order = np.argsort(preds)
    ranks = np.empty(len(preds))
    ranks[order] = np.arange(1, len(preds) + 1)
    exact = (ranks[labels == 1].sum() - 200 * 201 / 2) / (200 * 200)
    np.testing.assert_allclose(m.eval(), exact, atol=5e-3)


def test_composite_metric():
    c = metrics.CompositeMetric()
    c.add_metric(metrics.Precision())
    c.add_metric(metrics.Recall())
    c.update(np.array([1, 0]), np.array([1, 1]))
    assert c.eval() == [1.0, 0.5]


# ---------------------------------------------------------------------------
# 2.0 metric namespace
# ---------------------------------------------------------------------------
def test_metric20_topk_accuracy():
    m = metric.Accuracy(topk=(1, 2))
    pred = np.array([[0.1, 0.7, 0.2], [0.6, 0.3, 0.1]])
    label = np.array([[1], [2]])
    correct = m.compute(pred, label)
    m.update(correct)
    acc1, acc2 = m.accumulate()
    np.testing.assert_allclose(acc1, 0.5)   # sample0 top1 correct
    np.testing.assert_allclose(acc2, 0.5)   # label 2 not in top2 of s1


# ---------------------------------------------------------------------------
# nn namespace + hapi Model
# ---------------------------------------------------------------------------
def _toy_data(n=64, d=6, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, d).astype("float32")
    y = (x.sum(1) > d / 2).astype("int64")[:, None]
    return x, y


def test_nn_namespace_builds_and_runs():
    from paddle_tpu import dygraph
    with dygraph.guard():
        net = nn.Sequential(
            nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 2))
        x = dygraph.to_variable(np.ones((3, 6), "float32"))
        out = net(x)
        assert tuple(out.shape) == (3, 2)
        loss = nn.CrossEntropyLoss()(out, dygraph.to_variable(
            np.zeros((3, 1), "int64")))
        assert np.isfinite(float(np.asarray(loss.numpy()).reshape(-1)[0]))
        mse = nn.MSELoss()(out, dygraph.to_variable(
            np.zeros((3, 2), "float32")))
        l1 = nn.L1Loss()(out, dygraph.to_variable(
            np.zeros((3, 2), "float32")))
        assert float(mse.numpy().reshape(-1)[0]) >= 0
        assert float(l1.numpy().reshape(-1)[0]) >= 0
        y = nn.functional.relu(x)
        assert tuple(y.shape) == (3, 6)


def test_hapi_model_fit_evaluate_predict(tmp_path):
    x, y = _toy_data()
    from paddle_tpu import dygraph
    with dygraph.guard():
        net = nn.Sequential(nn.Linear(6, 16), nn.Tanh(),
                            nn.Linear(16, 2))
    model = pt.Model(net)
    model.prepare(optimizer=optimizer.AdamOptimizer(5e-2),
                  loss=nn.CrossEntropyLoss(),
                  metrics=metric.Accuracy())
    ds = TensorDataset(x, y)
    hist = model.fit(ds, batch_size=16, epochs=25, verbose=0)
    assert hist["loss"][-1] < 0.5 * hist["loss"][0]

    res = model.evaluate(ds, batch_size=16, verbose=0)
    assert res["loss"] is not None and res["acc"] > 0.7, res

    preds = model.predict(TensorDataset(x), batch_size=16)
    assert len(preds) == 4 and preds[0].shape == (16, 2)

    # save / load roundtrip preserves the metric
    path = str(tmp_path / "hapi_model")
    model.save(path)
    with dygraph.guard():
        net2 = nn.Sequential(nn.Linear(6, 16), nn.Tanh(),
                             nn.Linear(16, 2))
    model2 = pt.Model(net2)
    model2.prepare(loss=nn.CrossEntropyLoss(),
                   metrics=metric.Accuracy())
    model2.load(path)
    res2 = model2.evaluate(ds, batch_size=16, verbose=0)
    np.testing.assert_allclose(res2["acc"], res["acc"])


def test_static_namespace():
    from paddle_tpu import static
    main, startup = static.Program(), static.Program()
    startup._is_startup = True
    with static.program_guard(main, startup):
        x = static.data("sx", [4], dtype="float32")
        w = static.create_parameter([4, 2], "float32")
        out = pt.layers.matmul(x, w)
    exe = static.Executor()
    exe.run(startup)
    got = exe.run(main, feed={"sx": np.ones((3, 4), "float32")},
                  fetch_list=[out])
    assert np.asarray(got[0]).shape == (3, 2)
    spec = static.InputSpec([None, 4], "float32", "x")
    assert "InputSpec" in repr(spec)


def test_io20_namespace():
    from paddle_tpu import io
    assert io.DataLoader is pt.DataLoader
    ds = io.TensorDataset(np.arange(6).reshape(3, 2))
    assert len(ds) == 3


# ---------------------------------------------------------------------------
# callbacks (VERDICT r3 #9)
# ---------------------------------------------------------------------------

def _cb_model():
    import paddle_tpu as pt
    from paddle_tpu import nn, hapi
    import paddle_tpu.optimizer as opt
    from paddle_tpu.nn import CrossEntropyLoss
    with pt.dygraph.guard():
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m = hapi.Model(net)
    m.prepare(optimizer=opt.AdamOptimizer(1e-2),
              loss=CrossEntropyLoss())
    return m


def _cb_data(n=32):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 4).astype("float32")
    y = (x.sum(1) > 0).astype("int64")[:, None]
    return [(x[i], y[i]) for i in range(n)]


def test_callbacks_hooks_fire_in_order():
    from paddle_tpu.hapi import Callback

    events = []

    class Recorder(Callback):
        def on_train_begin(self, logs=None):
            events.append("train_begin")

        def on_epoch_begin(self, epoch, logs=None):
            events.append(f"epoch_begin_{epoch}")

        def on_train_batch_end(self, step, logs=None):
            if step == 0:
                events.append(f"batch_end_{step}")
                assert "loss" in (logs or {})

        def on_epoch_end(self, epoch, logs=None):
            events.append(f"epoch_end_{epoch}")

        def on_train_end(self, logs=None):
            events.append("train_end")

    m = _cb_model()
    m.fit(_cb_data(), batch_size=8, epochs=2, verbose=0,
          callbacks=[Recorder()])
    assert events == ["train_begin", "epoch_begin_0", "batch_end_0",
                      "epoch_end_0", "epoch_begin_1", "batch_end_0",
                      "epoch_end_1", "train_end"]


def test_model_checkpoint_callback(tmp_path):
    from paddle_tpu.hapi import ModelCheckpoint

    m = _cb_model()
    save_dir = str(tmp_path / "ckpt")
    m.fit(_cb_data(), batch_size=8, epochs=2, verbose=0,
          callbacks=[ModelCheckpoint(save_freq=1, save_dir=save_dir)])
    import os
    assert os.path.exists(os.path.join(save_dir, "0.pdparams"))
    assert os.path.exists(os.path.join(save_dir, "1.pdparams"))
    assert os.path.exists(os.path.join(save_dir, "final.pdparams"))
    # weights reload into a fresh model
    m2 = _cb_model()
    m2.load(os.path.join(save_dir, "final"))


def test_early_stopping_callback():
    from paddle_tpu.hapi import EarlyStopping

    m = _cb_model()
    # patience 0 + impossible baseline: stops after the first epoch
    es = EarlyStopping(monitor="loss", mode="min", patience=0,
                       baseline=-1e9, verbose=0)
    m.fit(_cb_data(), batch_size=8, epochs=50, verbose=0,
          callbacks=[es])
    assert m.stop_training


# ---------------------------------------------------------------------------
# paddle.tensor / paddle.amp namespaces (VERDICT r3 #9)
# ---------------------------------------------------------------------------

def test_tensor_namespace_smoke():
    import paddle_tpu as pt
    import paddle_tpu.tensor as T

    main_p, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main_p, startup):
        x = pt.layers.data("x", [3, 4], append_batch_size=False)
        y = T.add(T.multiply(x, x), T.ones_like(x))
        s = T.sum(y, dim=1)
        mx = T.argmax(y, axis=1)
        lse = T.logsumexp(x, axis=1)
        tri = T.tril(x)
        top_v, top_i = T.topk(x, k=2)
    exe = pt.Executor()
    exe.run(startup)
    xv = np.arange(12, dtype="float32").reshape(3, 4)
    sv, mv, lv, tv, tvv = exe.run(
        main_p, feed={"x": xv}, fetch_list=[s, mx, lse, tri, top_v])
    np.testing.assert_allclose(np.asarray(sv), (xv * xv + 1).sum(1))
    np.testing.assert_allclose(np.asarray(mv), np.argmax(xv * xv + 1, 1))
    np.testing.assert_allclose(
        np.asarray(lv),
        np.log(np.exp(xv - xv.max(1, keepdims=True)).sum(1))
        + xv.max(1), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(tv), np.tril(xv))
    np.testing.assert_allclose(np.asarray(tvv), np.sort(xv, 1)[:, -2:][:, ::-1])


def test_amp_namespace_smoke():
    import paddle_tpu as pt
    from paddle_tpu import amp

    with pt.dygraph.guard():
        import paddle_tpu.dygraph as dg
        lin = pt.nn.Linear(4, 4)
        x = dg.to_variable(np.ones((2, 4), "float32"))
        with amp.auto_cast():
            y = lin(x)
        scaler = amp.GradScaler(init_loss_scaling=128.0)
        loss = pt.layers.reduce_mean(y)
        scaled = scaler.scale(loss)
        assert scaled is not None
    assert callable(amp.decorate)


def test_hapi_model_full_train_state_resume(tmp_path):
    """save/load now carries optimizer accumulators (.pdopt): resuming
    from a checkpoint continues the EXACT Adam trajectory (reference
    Model.save training=True contract)."""
    x, y = _toy_data()
    ds = TensorDataset(x, y)

    def build(seed_net=None):
        from paddle_tpu import dygraph
        with dygraph.guard():
            net = nn.Sequential(nn.Linear(6, 16), nn.Tanh(),
                                nn.Linear(16, 2))
        m = pt.Model(net)
        m.prepare(optimizer.AdamOptimizer(
            5e-2, parameter_list=net.parameters()),
            loss=nn.CrossEntropyLoss())
        return m

    model = build()
    model.fit(ds, batch_size=16, epochs=5, verbose=0)
    path = str(tmp_path / "resume_ck")
    model.save(path)
    import os
    assert os.path.exists(path + ".pdopt")  # optimizer state on disk
    direct = model.fit(ds, batch_size=16, epochs=3, shuffle=False,
                       verbose=0)["loss"]

    resumed = build()
    resumed.load(path)
    replay = resumed.fit(ds, batch_size=16, epochs=3, shuffle=False,
                         verbose=0)["loss"]
    np.testing.assert_allclose(replay, direct, rtol=1e-5, atol=1e-6)


def test_hapi_model_inference_export(tmp_path):
    """save(training=False) exports via jit.save using specs inferred
    from the first fit batch; Predictor + jit.load serve it."""
    x, y = _toy_data()
    from paddle_tpu import dygraph
    with dygraph.guard():
        net = nn.Sequential(nn.Linear(6, 16), nn.Tanh(),
                            nn.Linear(16, 2))
    model = pt.Model(net)
    model.prepare(optimizer.AdamOptimizer(
        5e-2, parameter_list=net.parameters()),
        loss=nn.CrossEntropyLoss())
    model.fit(TensorDataset(x, y), batch_size=16, epochs=2, verbose=0)
    assert model._inputs is not None  # specs inferred from fit
    with dygraph.guard():
        want = np.asarray(net(dygraph.to_variable(x[:16])).numpy())
    d = str(tmp_path / "hapi_infer")
    model.save(d, training=False)
    with dygraph.guard():
        got = pt.jit.load(d)(x[:16])
        np.testing.assert_allclose(np.asarray(got.numpy()), want,
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.xfail(
    reason="this image's jax 0.4.37 XLA CPU backend raises "
           "'Multiprocess computations aren't implemented on the CPU "
           "backend' for cross-process collectives (works on real "
           "TPU/GPU backends)", strict=False)
def test_hapi_distributed_fit_with_resume(tmp_path):
    """Book MLP under real 2-process DP (launch + DataParallel grad
    allreduce) with a checkpoint resume mid-run (VERDICT r4 #10)."""
    import json
    import os
    import subprocess
    import sys

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(os.path.dirname(__file__),
                          "hapi_dist_worker.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", "2", "--coordinator_port", "23873",
           script, str(tmp_path)]
    r = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                       text=True, timeout=280)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    res = {}
    for rank in (0, 1):
        p = tmp_path / f"hapi_result.{rank}.json"
        assert p.exists(), (r.stdout[-2000:], r.stderr[-2000:])
        res[rank] = json.loads(p.read_text())
    # training converged under DP
    for rank in (0, 1):
        assert res[rank]["last_loss"] < res[rank]["first_loss"] * 0.5
    # grad allreduce kept both ranks' parameters identical
    np.testing.assert_allclose(res[0]["param_sum"], res[1]["param_sum"],
                               rtol=1e-5)
    np.testing.assert_allclose(res[0]["param_absmax"],
                               res[1]["param_absmax"], rtol=1e-5)
    # checkpoint resume replays the direct trajectory on every rank
    for rank in (0, 1):
        np.testing.assert_allclose(res[rank]["resume_losses"],
                                   res[rank]["direct_losses"],
                                   rtol=1e-4, atol=1e-5)


def test_hapi_inference_export_is_deterministic_with_dropout(tmp_path):
    """save(training=False) must trace in eval mode: a net with dropout
    exported right after fit() (which leaves the net in train mode)
    has to serve deterministic outputs."""
    x, y = _toy_data()
    from paddle_tpu import dygraph
    with dygraph.guard():
        net = nn.Sequential(nn.Linear(6, 16), nn.Dropout(0.5),
                            nn.Linear(16, 2))
    model = pt.Model(net)
    model.prepare(optimizer.AdamOptimizer(
        5e-2, parameter_list=net.parameters()),
        loss=nn.CrossEntropyLoss())
    model.fit(TensorDataset(x, y), batch_size=16, epochs=1, verbose=0)
    d = str(tmp_path / "dropout_infer")
    model.save(d, training=False)
    assert getattr(net, "training", False)  # fit's train mode restored
    with dygraph.guard():
        loaded = pt.jit.load(d)
        o1 = np.asarray(loaded(x[:8]).numpy())
        o2 = np.asarray(loaded(x[:8]).numpy())
    np.testing.assert_array_equal(o1, o2)  # no live dropout
    with dygraph.guard():
        net.eval()
        want = np.asarray(net(dygraph.to_variable(x[:8])).numpy())
    np.testing.assert_allclose(o1, want, rtol=1e-5, atol=1e-6)
