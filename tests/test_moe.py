"""Expert parallelism (MoE) tests — VERDICT r4 #9, SURVEY §2.6 EP row.

Covers: Switch top-1 gating math vs a numpy reference, ep8 shard_map
all_to_all parity vs the dense path, capacity-factor dropping, balanced
routing, and a small training run with the auxiliary loss.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, optimizer
from paddle_tpu.parallel import build_spmd_step, make_mesh

R = np.random.RandomState

N, H, E, I = 16, 8, 4, 12


def _np_softmax(x):
    e = np.exp(x - x.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def _np_moe(x, gate_w, w1, b1, w2, b2, capacity_factor=1.25):
    """Loop reference of the Switch math (top-1, capacity, gelu)."""
    n, h = x.shape
    e = gate_w.shape[1]
    probs = _np_softmax(x @ gate_w)
    expert = probs.argmax(-1)
    gate = probs[np.arange(n), expert]
    C = max(1, int(np.ceil(n / e * capacity_factor)))
    out = np.zeros_like(x)
    counts = np.zeros(e)
    slots = np.zeros(e, int)
    for t in range(n):
        ex = expert[t]
        counts[ex] += 1
        if slots[ex] >= C:
            continue  # dropped: zero contribution
        slots[ex] += 1
        hdd = x[t] @ w1[ex] + b1[ex]
        g = 0.5 * hdd * (1 + np.tanh(np.sqrt(2 / np.pi)
                                     * (hdd + 0.044715 * hdd ** 3)))
        out[t] = (g @ w2[ex] + b2[ex]) * gate[t]
    frac = np.eye(e)[expert].mean(0)
    aux = e * (frac * probs.mean(0)).sum()
    return out, aux, counts


def _weights(seed=0):
    r = R(seed)
    return dict(
        gate_w=r.randn(H, E).astype("float32") * 0.5,
        w1=r.randn(E, H, I).astype("float32") * 0.3,
        b1=r.randn(E, I).astype("float32") * 0.1,
        w2=r.randn(E, I, H).astype("float32") * 0.3,
        b2=r.randn(E, H).astype("float32") * 0.1)


def _moe_program(ws, shape=(N, H)):
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    feed = {}
    with pt.program_guard(main, startup):
        block = main.global_block()
        x = block.create_var(name="mx", shape=list(shape),
                             dtype="float32", is_data=True)
        slots = {"X": ["mx"]}
        for slot, key in [("GateW", "gate_w"), ("W1", "w1"),
                          ("B1", "b1"), ("W2", "w2"), ("B2", "b2")]:
            nm = f"m_{key}"
            block.create_var(name=nm, shape=ws[key].shape,
                             dtype="float32", is_data=True)
            feed[nm] = ws[key]
            slots[slot] = [nm]
        for nm, shp, dt in [("m_out", list(shape), "float32"),
                            ("m_aux", [], "float32"),
                            ("m_cnt", [E], "float32")]:
            block.create_var(name=nm, shape=shp, dtype=dt)
        block.append_op("moe_ffn", inputs=slots,
                        outputs={"Out": ["m_out"], "AuxLoss": ["m_aux"],
                                 "ExpertCount": ["m_cnt"]},
                        attrs={"capacity_factor": 1.25,
                               "activation": "gelu"})
    return main, startup, feed


def test_moe_matches_numpy_reference():
    ws = _weights()
    x = R(1).randn(N, H).astype("float32")
    want, aux_ref, counts_ref = _np_moe(x, **ws)
    main, startup, feed = _moe_program(ws)
    feed["mx"] = x
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    out, aux, cnt = exe.run(main, feed=feed,
                            fetch_list=["m_out", "m_aux", "m_cnt"],
                            scope=scope)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(float(np.asarray(aux)), aux_ref,
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(cnt), counts_ref)


def test_moe_ep8_all_to_all_matches_dense():
    """{dp:1, ep:8} shard_map: the all_to_all dispatch/combine must
    reproduce the dense single-device output exactly."""
    ws = _weights(2)
    # E must divide ep axis: use E=8 experts here
    r = R(3)
    ws = dict(gate_w=r.randn(H, 8).astype("float32") * 0.5,
              w1=r.randn(8, H, I).astype("float32") * 0.3,
              b1=r.randn(8, I).astype("float32") * 0.1,
              w2=r.randn(8, I, H).astype("float32") * 0.3,
              b2=r.randn(8, H).astype("float32") * 0.1)
    x = R(4).randn(N, H).astype("float32")
    want, _, _ = _np_moe(x, **ws)

    main, startup, feed = _moe_program(ws)
    feed["mx"] = x
    mesh = make_mesh({"dp": 1, "ep": 8})
    fn, mut_in, const_in, _ = build_spmd_step(
        main, list(feed), ["m_out"], mesh)
    fetches, _, _ = fn(tuple(feed.values()), (), (), np.int32(1))
    got = np.asarray(fetches[0])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_moe_capacity_drops_overflow():
    """All tokens forced onto expert 0: rows past capacity contribute
    zero (Switch overflow semantics — the caller's residual carries
    them)."""
    ws = _weights(5)
    ws["gate_w"] = np.zeros((H, E), "float32")
    ws["gate_w"][:, 0] = 5.0  # expert 0 wins everywhere
    x = np.abs(R(6).randn(N, H)).astype("float32")
    main, startup, feed = _moe_program(ws)
    feed["mx"] = x
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    out, cnt = exe.run(main, feed=feed, fetch_list=["m_out", "m_cnt"],
                       scope=scope)
    out, cnt = np.asarray(out), np.asarray(cnt)
    C = int(np.ceil(N / E * 1.25))  # 5
    assert cnt[0] == N
    kept = (np.abs(out).sum(1) > 1e-6).sum()
    assert kept == C, (kept, C)  # only the first C tokens served


def test_moe_balanced_routing_spreads_tokens():
    ws = _weights(7)
    x = R(8).randn(64, H).astype("float32")
    main, startup, feed = _moe_program(ws, shape=(64, H))
    feed["mx"] = x
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    aux, cnt = exe.run(main, feed=feed, fetch_list=["m_aux", "m_cnt"],
                       scope=scope)
    cnt = np.asarray(cnt)
    assert cnt.sum() == 64
    assert (cnt > 0).all(), cnt  # random gate: every expert used
    # aux loss is ~1 when balanced, E when collapsed
    assert 0.9 < float(np.asarray(aux)) < 2.5


def test_moe_layer_trains_with_aux_loss():
    """layers.moe_ffn end-to-end: regression target through the expert
    path; loss (incl. 0.01*aux) must drop and routing must not
    collapse."""
    x = R(9).randn(32, H).astype("float32")
    y = np.tanh(x @ R(10).randn(H, H).astype("float32"))

    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        xv = layers.data("x", [H], dtype="float32")
        yv = layers.data("y", [H], dtype="float32")
        out, aux = layers.moe_ffn(xv, num_experts=E, d_ff=I)
        res = pt.layers.elementwise_add(out, xv)  # residual
        mse = layers.mean(layers.square(res - yv))
        loss = pt.layers.elementwise_add(
            mse, pt.layers.scale(aux, scale=0.01))
        optimizer.AdamOptimizer(5e-3).minimize(loss)
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    losses = []
    for _ in range(60):
        l, = exe.run(main, feed={"x": x, "y": y}, fetch_list=[mse],
                     scope=scope)
        losses.append(float(np.asarray(l).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_moe_rules_shard_expert_weights():
    from paddle_tpu.parallel import megatron_rules, moe_rules
    from jax.sharding import PartitionSpec as P
    mesh = make_mesh({"dp": 2, "ep": 4})
    rules = moe_rules(mesh, inner=megatron_rules(mesh))
    assert rules.spec("moe_ffn.w_1", (8, 16, 32)) == P("ep", None, None)
    assert rules.spec("fc.w_0", (16, 32)) == P()  # no mp axis here
    mesh2 = make_mesh({"dp": 2, "mp": 2, "ep": 2})
    rules2 = moe_rules(mesh2, inner=megatron_rules(mesh2))
    assert rules2.spec("moe_ffn.w_1", (8, 16, 32)) == P("ep", None,
                                                        None)
    assert rules2.spec("fc.w_0", (16, 32)) == P(None, "mp")
