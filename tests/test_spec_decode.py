"""Speculative decoding tests: n-gram self-drafting, one-chunk
verification, bit-exact acceptance, and rejected-draft page rollback.

The load-bearing contracts (ISSUE 17 acceptance):

* **Bit-exact vs non-speculative decode** — a speculating engine's
  token streams AND per-step logits equal the plain engine's at
  tolerance 0 (``np.array_equal``): verify row 0 writes exactly what
  the plain step writes, accepted rows replay the same argmax chain,
  and rejected rows' garbage K/V is causally masked and overwritten.
  Holds at page-boundary ±1 prompt lengths, with concurrent MIXED
  speculating/plain slots, and through prefix-index hits.
* **Drafter** — longest-suffix n-gram match over the sequence's own
  prompt + generated history; the LAST earlier occurrence wins; no
  match / degenerate history / k<1 propose nothing (the slot falls
  through to the plain one-token step).
* **Rollback accounting** — rejected drafts decref their provisional
  pages; after every request drains the pool returns to zero live
  pages, including when the pool exhausts MID-DRAFT.
* **Opt-out** — ``submit(..., speculate=False)`` (and the HTTP
  ``"speculate"`` field) bypasses drafting per-request.

All engines share the dense reference's scope: weight init depends on
global state, so only shared-scope engines bind identical weights
(the ``tests/test_paged_generation.py`` pattern).  Two paged engines
sharing a scope share pool buffers — they run SEQUENTIALLY, never
concurrently.
"""
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.serving import GenerationEngine, ServingEngine, serve
from paddle_tpu.serving.generation import ngram_draft

MODEL = dict(vocab_size=61, hidden=32, num_layers=2, num_heads=4,
             num_kv_heads=2, intermediate=64)
PAGE = 8


@pytest.fixture(scope="module")
def dense_ref():
    """Dense-cache non-speculative reference; spec engines share its
    scope so both sides bind identical weights."""
    eng = GenerationEngine(MODEL, num_slots=3, max_seq_len=96,
                           max_new_tokens=8, keep_logits=True,
                           attn_impl="xla", seed=0, queue_cap=64,
                           deadline_ms=600000.0, paged=False)
    yield eng
    eng.close()


def _spec(dense, **kw):
    base = dict(num_slots=3, max_seq_len=96, max_new_tokens=8,
                keep_logits=True, attn_impl="xla", seed=0,
                queue_cap=64, deadline_ms=600000.0, paged=True,
                page_tokens=PAGE, prefill_chunk=0, prefix_reuse=False,
                speculate=True, spec_tokens=4, spec_ngram=3)
    base.update(kw)
    return GenerationEngine(MODEL, scope=dense.scope, **base)


def _repetitive(rng, n, period=4):
    """A period-`period` prompt: every suffix n-gram has an earlier
    occurrence, so the drafter proposes every round."""
    pattern = rng.randint(1, MODEL["vocab_size"], size=period).tolist()
    return (pattern * (n // period + 1))[:n]


# ---------------------------------------------------------------------------
# drafter units
# ---------------------------------------------------------------------------

def test_ngram_draft_hit():
    # suffix [2, 3] recurs at index 1; the following tokens are the draft
    assert ngram_draft([1, 2, 3, 4, 2, 3], 3, 3) == [4, 2, 3]
    # k caps the proposal
    assert ngram_draft([1, 2, 3, 4, 2, 3], 1, 3) == [4]


def test_ngram_draft_last_occurrence_wins():
    # [1, 2] occurs at 0 (followed by 9) and at 3 (followed by 7): the
    # most recent occurrence is the better n-gram LM estimate
    assert ngram_draft([1, 2, 9, 1, 2, 7, 1, 2], 1, 2) == [7]


def test_ngram_draft_longest_ngram_first():
    # the trigram [9, 1, 2] matches at index 2 and beats the more
    # recent bigram-only match of [1, 2]
    h = [5, 9, 1, 2, 8, 1, 2, 6, 9, 1, 2]
    assert ngram_draft(h, 1, 3) == [8]


def test_ngram_draft_miss_and_guards():
    assert ngram_draft([1, 2, 3], 3, 3) == []     # no recurrence
    assert ngram_draft([1, 2, 3, 4], 0, 3) == []  # k < 1
    assert ngram_draft([7], 3, 3) == []           # history too short
    assert ngram_draft([], 3, 3) == []


def test_ngram_draft_degenerate_repetition():
    # [5, 5, 5, 5]: suffix trigram matches at index 0, only one token
    # follows — a short draft, not an infinite self-match
    assert ngram_draft([5, 5, 5, 5], 4, 3) == [5]


# ---------------------------------------------------------------------------
# bit-exactness: speculating == plain, tolerance 0
# ---------------------------------------------------------------------------

def _assert_streams_equal(ref_results, got_results):
    for a, b in zip(ref_results, got_results):
        assert a["tokens"] == b["tokens"]
        assert a["finish"] == b["finish"]
        for i, (la, lb) in enumerate(zip(a["logits"], b["logits"])):
            assert np.array_equal(np.asarray(la), np.asarray(lb)), \
                f"step {i}: speculative logits drifted (max |d|=" \
                f"{np.abs(np.asarray(la) - np.asarray(lb)).max()})"


def test_spec_bitexact_concurrent_ragged(dense_ref):
    """Repetitive prompts of page-1 / page / page+1 tokens decode
    concurrently with speculation on; every stream and per-step logit
    vector is bit-equal to the dense non-speculative engine's, and the
    drafter demonstrably fired (otherwise the test is vacuous)."""
    rng = np.random.RandomState(11)
    prompts = [_repetitive(rng, n) for n in (PAGE - 1, PAGE, PAGE + 1)]
    steps = [6, 5, 7]
    rd = [f.result(120) for f in
          [dense_ref.submit(p, n) for p, n in zip(prompts, steps)]]
    eng = _spec(dense_ref)
    try:
        rs = [f.result(120) for f in
              [eng.submit(p, n) for p, n in zip(prompts, steps)]]
        _assert_streams_equal(rd, rs)
        sp = eng.stats()["speculate"]
        assert sp["drafts"] > 0 and sp["tokens_proposed"] > 0
        assert sp["tokens_accepted"] <= sp["tokens_proposed"]
        assert eng._pool.live_pages == 0
    finally:
        eng.close()


def test_spec_bitexact_mixed_slots(dense_ref):
    """Speculating and per-request-opted-out slots decode CONCURRENTLY
    in one grid (the mixed-grid path: ``_decode_step(skip=...)``);
    every stream matches dense regardless of which side of the fence
    it decoded on."""
    rng = np.random.RandomState(13)
    prompts = [_repetitive(rng, n) for n in (PAGE - 1, PAGE + 1, 12)]
    steps = [7, 6, 7]
    flags = [None, False, None]  # slot 1 opts out mid-grid
    rd = [f.result(120) for f in
          [dense_ref.submit(p, n) for p, n in zip(prompts, steps)]]
    eng = _spec(dense_ref)
    try:
        fs = [eng.submit(p, n, speculate=sp)
              for p, n, sp in zip(prompts, steps, flags)]
        rs = [f.result(120) for f in fs]
        _assert_streams_equal(rd, rs)
        assert eng.stats()["speculate"]["drafts"] > 0
    finally:
        eng.close()


def test_spec_bitexact_prefix_hits(dense_ref):
    """Streams riding prefix-index hits (borrowed COW pages, tail-only
    prefill) speculate bit-exactly: a plain paged engine and a
    speculating one see the same submission order, take the same index
    hits, and emit identical tokens AND logits."""
    rng = np.random.RandomState(17)
    header = _repetitive(rng, 2 * PAGE)  # two full shared pages
    prompts = [header + _repetitive(rng, 5) for _ in range(3)]
    steps = [6, 6, 6]

    def run(speculate):
        eng = _spec(dense_ref, prefix_reuse=True, speculate=speculate)
        try:
            out = [eng.submit(p, n).result(120)
                   for p, n in zip(prompts, steps)]
            st = eng.stats()
            return out, st
        finally:
            eng.close()

    # sequential, never concurrent: the two paged engines share pool
    # buffer names in the common scope
    plain, st_plain = run(False)
    spec, st_spec = run(True)
    _assert_streams_equal(plain, spec)
    assert st_plain["counters"]["prefix_hits"] > 0
    assert st_spec["counters"]["prefix_hits"] > 0
    assert st_spec["speculate"]["drafts"] > 0


# ---------------------------------------------------------------------------
# rollback accounting
# ---------------------------------------------------------------------------

def test_spec_rollback_refcount_balance(dense_ref):
    """Rejected drafts roll their provisional pages back: rollbacks
    fire (the tiny random model rarely follows the prompt's period),
    accepted <= proposed, and the pool drains to ZERO live pages once
    every request finishes."""
    rng = np.random.RandomState(19)
    eng = _spec(dense_ref)
    try:
        for n in (PAGE - 1, PAGE, PAGE + 1, 12):
            eng.generate(_repetitive(rng, n), 8)
        sp = eng.stats()["speculate"]
        assert sp["drafts"] > 0
        assert sp["rollbacks"] >= 1
        assert sp["rollbacks"] <= sp["drafts"]
        assert sp["tokens_accepted"] <= sp["tokens_proposed"]
        assert 0.0 <= sp["acceptance_rate"] <= 1.0
        assert eng._pool.live_pages == 0
    finally:
        eng.close()


def test_spec_pool_exhaustion_mid_draft(dense_ref):
    """A draft that cannot get pages falls through to the plain step,
    which finishes the sequence ``cache_full`` at EXACTLY the plain
    engine's truncation point with the plain engine's tokens — then
    the freed pages serve the next request (full recovery)."""
    def run(speculate):
        eng = GenerationEngine(MODEL, scope=dense_ref.scope,
                               num_slots=1, max_seq_len=96,
                               attn_impl="xla", seed=0, queue_cap=64,
                               deadline_ms=600000.0, paged=True,
                               page_tokens=PAGE, num_pages=5,
                               prefill_chunk=0, prefix_reuse=False,
                               speculate=speculate, spec_tokens=4,
                               spec_ngram=3)
        try:
            rng = np.random.RandomState(23)
            prompt = _repetitive(rng, 10)
            res = eng.generate(prompt, 500)
            live = eng._pool.live_pages
            res2 = eng.generate(prompt, 500)
            sp = eng.stats()["speculate"]
            return res, live, res2, sp
        finally:
            eng.close()

    res_p, live_p, res2_p, _ = run(False)
    res_s, live_s, res2_s, sp = run(True)
    capacity = 4 * PAGE  # (num_pages - 1) usable, page 0 is trash
    assert res_p["finish"] == res_s["finish"] == "cache_full"
    assert len(res_p["tokens"]) == capacity - 10 + 1
    assert res_s["tokens"] == res_p["tokens"]
    assert live_p == live_s == 0
    assert res2_s["tokens"] == res2_p["tokens"] == res_p["tokens"]
    assert sp["drafts"] > 0  # speculation ran before the pool dried


# ---------------------------------------------------------------------------
# opt-out
# ---------------------------------------------------------------------------

def test_spec_per_request_opt_out(dense_ref):
    """speculate=False per request on a speculating engine: zero
    drafts, stream identical to dense."""
    rng = np.random.RandomState(29)
    prompt = _repetitive(rng, PAGE + 2)
    ref = dense_ref.generate(prompt, 7)
    eng = _spec(dense_ref)
    try:
        res = eng.submit(prompt, 7, speculate=False).result(120)
        assert res["tokens"] == ref["tokens"]
        sp = eng.stats()["speculate"]
        assert sp["drafts"] == 0 and sp["tokens_proposed"] == 0
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# HTTP e2e
# ---------------------------------------------------------------------------

def _post(url, doc):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.status, json.loads(r.read())


def _tiny_predictor():
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        out = layers.fc(x, 2, name="spec_http_fc")
    scope = pt.Scope()
    pt.Executor().run(startup, scope=scope)
    from paddle_tpu.inference import Predictor
    return Predictor(main, ["x"], [out], scope=scope)


def test_http_generate_speculate(dense_ref):
    """POST /generate carries the per-request ``"speculate"`` field
    end-to-end, /statusz exposes the speculate stats block (the
    loadgen acceptance-rate embed reads it), and a non-bool value is a
    400, not a crash."""
    gen = _spec(dense_ref)
    eng = ServingEngine(_tiny_predictor(), workers=1, max_batch=2,
                        max_delay_ms=1.0, deadline_ms=60000)
    eng.attach_generator(gen)
    srv = serve(eng)
    try:
        rng = np.random.RandomState(31)
        prompt = _repetitive(rng, PAGE + 1)
        ref = dense_ref.generate(prompt, 6)

        code, doc = _post(srv.url + "/generate",
                          {"prompt": prompt, "max_new_tokens": 6})
        assert code == 200 and doc["tokens"] == ref["tokens"]
        code, doc = _post(srv.url + "/generate",
                          {"prompt": prompt, "max_new_tokens": 6,
                           "speculate": False})
        assert code == 200 and doc["tokens"] == ref["tokens"]

        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(srv.url + "/generate",
                  {"prompt": prompt, "speculate": "yes"})
        assert ei.value.code == 400

        with urllib.request.urlopen(srv.url + "/statusz",
                                    timeout=30) as r:
            sz = json.loads(r.read())
        spec = sz["engine"]["generator"]["stats"]["speculate"]
        assert spec["drafts"] >= 1
        assert 0.0 <= spec["acceptance_rate"] <= 1.0
    finally:
        eng.generator = None
        srv.close()
        eng.close()
        gen.close()
