"""Observability tests: profiler trace capture, flags registry,
check_nan_inf op naming, print op.

Reference analogs: tests/unittests/test_profiler.py, test_flags_*.py,
test_nan_inf.py, test_print_op.py.
"""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, optimizer
from paddle_tpu.flags import flag_value


def test_flags_set_get_and_env_defaults():
    got = pt.get_flags("FLAGS_check_nan_inf")
    assert got == {"FLAGS_check_nan_inf": False}
    pt.set_flags({"FLAGS_check_nan_inf": True})
    try:
        assert flag_value("FLAGS_check_nan_inf") is True
        multi = pt.get_flags(["FLAGS_check_nan_inf", "FLAGS_benchmark"])
        assert multi["FLAGS_check_nan_inf"] is True
        assert multi["FLAGS_benchmark"] is False
    finally:
        pt.set_flags({"FLAGS_check_nan_inf": False})
    with pytest.raises(ValueError, match="unknown flag"):
        pt.set_flags({"FLAGS_bogus": 1})


def test_profiler_trace_saved_and_loadable(tmp_path):
    from paddle_tpu.profiler import (RecordEvent, load_trace, profiler,
                                     summarize_trace)

    x = layers.data("x", [4])
    h = layers.fc(x, 8, act="relu")
    loss = layers.mean(h)
    optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = np.random.rand(8, 4).astype("float32")
    exe.run(feed={"x": xv}, fetch_list=[loss])  # compile outside trace

    d = str(tmp_path / "trace")
    with profiler(trace_dir=d):
        with RecordEvent("bench_step"):
            for _ in range(3):
                exe.run(feed={"x": xv}, fetch_list=[loss])

    trace = load_trace(d)
    names = {e.get("name") for e in trace["traceEvents"]}
    assert "bench_step" in names  # our annotation is on the timeline
    table = summarize_trace(d, "total")
    assert "bench_step" in table and "Total(ms)" in table


def test_stop_profiler_failure_does_not_wedge(monkeypatch, tmp_path):
    """A failed jax.profiler.stop_trace must still clear the session:
    the old code left _active_dir set, permanently wedging
    start_profiler with 'profiler already running'."""
    import jax

    from paddle_tpu import profiler as prof

    started = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: started.append(d))

    def boom():
        raise RuntimeError("trace flush failed")

    monkeypatch.setattr(jax.profiler, "stop_trace", boom)
    prof.start_profiler(trace_dir=str(tmp_path / "a"))
    with pytest.raises(RuntimeError, match="trace flush failed"):
        prof.stop_profiler()
    # not wedged: the next session starts and stops cleanly
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    prof.start_profiler(trace_dir=str(tmp_path / "b"))
    assert prof.stop_profiler() == str(tmp_path / "b")
    assert started == [str(tmp_path / "a"), str(tmp_path / "b")]


def test_check_nan_inf_names_the_op():
    """Inject a NaN-producing op (log of a negative number) and assert
    the failure names it."""
    x = layers.data("x", [3])
    h = layers.fc(x, 4, name="ok_fc")
    # shift h far negative so log() yields NaN for ANY initializer draw
    # (h alone straddles zero — whether it happens to be negative depends
    # on the rng backend's xavier draw, which changed across jax versions)
    bad = layers.log(layers.scale(h, scale=1.0, bias=-1000.0))
    loss = layers.mean(bad)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = -np.ones((2, 3), "float32")
    pt.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with pytest.raises(FloatingPointError, match="op 'log'"):
            exe.run(feed={"x": xv}, fetch_list=[loss])
    finally:
        pt.set_flags({"FLAGS_check_nan_inf": False})


def test_check_nan_inf_clean_run_matches_jit():
    x = layers.data("x", [3])
    loss = layers.mean(layers.fc(x, 4, act="sigmoid"))
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = np.random.RandomState(0).rand(2, 3).astype("float32")
    ref = float(exe.run(feed={"x": xv}, fetch_list=[loss])[0])
    pt.set_flags({"FLAGS_check_nan_inf": True})
    try:
        got = float(np.asarray(
            exe.run(feed={"x": xv}, fetch_list=[loss])[0]))
    finally:
        pt.set_flags({"FLAGS_check_nan_inf": False})
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_print_op_passthrough_and_grad(capfd):
    import jax

    x = layers.data("x", [3])
    h = layers.fc(x, 4, name="pfc")
    p = layers.Print(h, message="h_values")
    loss = layers.mean(p)
    optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = np.ones((2, 3), "float32")
    l1 = float(exe.run(feed={"x": xv}, fetch_list=[loss])[0])
    l2 = float(exe.run(feed={"x": xv}, fetch_list=[loss])[0])
    assert np.isfinite(l1) and l2 < l1  # pass-through + identity grad
    jax.effects_barrier()
    out = capfd.readouterr()
    assert "h_values" in out.out or "h_values" in out.err


# ---------------------------------------------------------------------------
# monitor / StatRegistry + graphviz dumps (r3 §5 observability partial)
# ---------------------------------------------------------------------------

def test_stat_registry_counts_executor_steps():
    from paddle_tpu.monitor import monitor, stat_add, stat_get

    base = stat_get("executor_run_steps")
    main_p, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main_p, startup):
        x = layers.data("x", [2, 2], append_batch_size=False)
        y = layers.scale(x, scale=2.0)
    exe = pt.Executor()
    exe.run(startup)
    for _ in range(3):
        exe.run(main_p, feed={"x": np.ones((2, 2), "float32")},
                fetch_list=[y])
    assert stat_get("executor_run_steps") >= base + 3
    stat_add("custom_stat", 5)
    snap = dict(monitor.publish())
    assert snap["custom_stat"] == 5
    assert dict(monitor.publish(reset=True))["custom_stat"] == 5
    assert stat_get("custom_stat") == 0


def test_program_dot_dump(tmp_path):
    from paddle_tpu.monitor import program_to_dot
    from paddle_tpu.framework.ir import PassRegistry

    main_p, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main_p, startup):
        x = layers.data("x", [4, 4], append_batch_size=False)
        h = layers.fc(x, size=3, act="relu")
        layers.reduce_mean(h)
    dot = program_to_dot(main_p)
    assert dot.startswith("digraph G {") and dot.endswith("}")
    assert '"op_0"' in dot and "mul" in dot and "relu" in dot
    assert "lightgrey" in dot     # parameter shading
    # via the registered pass (reference graph_viz_pass attachment)
    p = str(tmp_path / "prog.dot")
    PassRegistry.get("graph_viz", graph_viz_path=p).apply(main_p)
    content = open(p).read()
    assert "digraph G {" in content and "reduce_mean" in content
