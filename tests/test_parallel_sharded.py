"""GSPMD sharded-step tests on the virtual 8-device CPU mesh.

Reference analog: ParallelExecutor tests compare single- vs multi-device
losses on the same net (tests/unittests/parallel_executor_test_base.py);
here we compare the unsharded Executor step vs the dp- and dp+mp-sharded
jitted step.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, optimizer
from paddle_tpu.parallel import (MeshConfig, make_mesh, dp_mesh,
                                 megatron_rules, build_sharded_step)
from paddle_tpu.parallel.sharded import shard_batch


def _build_mlp():
    x = layers.data("x", [8, 16], append_batch_size=False)
    y = layers.data("y", [8, 1], dtype="int64", append_batch_size=False)
    h = layers.fc(x, size=32, act="relu")
    logits = layers.fc(h, size=4)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
    return loss


def _init(scope):
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), scope=scope)
    return exe


def _feed():
    rng = np.random.RandomState(0)
    return {"x": rng.rand(8, 16).astype("float32"),
            "y": rng.randint(0, 4, (8, 1)).astype("int64")}


@pytest.mark.parametrize("cfg", [dict(), dict(mp=2), dict(mp=4)])
def test_sharded_step_matches_single_device(cfg):
    main, startup = pt.default_main_program(), pt.default_startup_program()
    with pt.program_guard(main, startup):
        loss = _build_mlp()
        optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)

    # single-device run
    scope1 = pt.Scope()
    exe = _init(scope1)
    feed = _feed()
    ref_losses = [float(exe.run(main, feed=feed, fetch_list=[loss],
                                scope=scope1)[0]) for _ in range(3)]

    # sharded run from identical init
    scope2 = pt.Scope()
    _init(scope2)
    mesh = make_mesh(MeshConfig(**cfg).resolve(8))
    fn, mut_in, const_in, _ = build_sharded_step(
        main, ["x", "y"], [loss.name], mesh, rules=megatron_rules(mesh))
    feed_vals = tuple(shard_batch(mesh, [feed["x"], feed["y"]]))
    mut = tuple(scope2.find_var(n) for n in mut_in)
    const = tuple(scope2.find_var(n) for n in const_in)
    got = []
    for i in range(3):
        fetches, mut, _ = fn(feed_vals, mut, const, np.int32(i + 1))
        got.append(float(np.asarray(fetches[0])))

    np.testing.assert_allclose(got, ref_losses, rtol=2e-5)


def test_megatron_rules_shard_2d_weights():
    from jax.sharding import PartitionSpec as P
    mesh = make_mesh({"dp": 4, "mp": 2})
    rules = megatron_rules(mesh)
    assert rules.spec("fc_0.w_0", (16, 32)) == P(None, "mp")
    assert rules.spec("fc_0.b_0", (32,)) == P()  # 1-D: replicated
    assert rules.spec("odd.w", (16, 33)) == P()  # indivisible: replicated


def test_dp_gradient_equivalence_vs_single_device():
    """dp over 8 devices on batch 8 == single device batch 8 (same math):
    per-step losses must match, which fails if the implicit gradient psum
    or the loss scaling were wrong."""
    main, startup = pt.default_main_program(), pt.default_startup_program()
    with pt.program_guard(main, startup):
        loss = _build_mlp()
        optimizer.MomentumOptimizer(0.1, 0.9).minimize(loss)

    scope1 = pt.Scope()
    exe = _init(scope1)
    feed = _feed()
    ref = [float(exe.run(main, feed=feed, fetch_list=[loss],
                         scope=scope1)[0]) for _ in range(4)]

    scope = pt.Scope()
    _init(scope)
    mesh = dp_mesh(8)
    fn, mut_in, const_in, _ = build_sharded_step(
        main, ["x", "y"], [loss.name], mesh)
    feed_vals = tuple(shard_batch(mesh, [feed["x"], feed["y"]]))
    mut = tuple(scope.find_var(n) for n in mut_in)
    const = tuple(scope.find_var(n) for n in const_in)
    losses = []
    for i in range(4):
        fetches, mut, _ = fn(feed_vals, mut, const, np.int32(i + 1))
        losses.append(float(np.asarray(fetches[0])))
    np.testing.assert_allclose(losses, ref, rtol=2e-5)
    assert losses[-1] < losses[0]
