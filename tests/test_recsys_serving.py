"""Recommender serving tier: ep-sharded embedding lookups, hot-row
caching, Wide&Deep small-feed inference, and capability routing.

Contract under test (serving/embedding.py + the front-end wiring):

* sharded ``lookup`` is BIT-EXACT (tolerance 0) vs the unsharded
  ``values[ids]`` gather — both placements, duplicate ids, 2-D id
  batches, cold cache, warm cache, and cache disabled;
* the hot-row cache pins rows for the duration of a lookup (a pinned
  row is never evicted), counts hits/misses/evictions, and raises on
  refcount underflow;
* a dead shard DEGRADES instead of failing: cached rows stay exact,
  uncached rows come back as the default row, the degraded counters
  book it, and ``revive_shard`` restores bit-exactness;
* the engine advertises the ``embedding`` capability through
  ``health()``/``/healthz`` and the router steers sparse-feed bodies
  to embedding-capable replicas (and dense bodies away from them).
"""
import importlib.util
import json
import os
import threading
import urllib.request

import numpy as np
import pytest

from paddle_tpu import fault
from paddle_tpu.serving import (HotRowCache, Router, RouterServer,
                                RowSharding, ServingEngine,
                                ShardedEmbeddingTable, batcher,
                                build_recsys_predictor, serve)
from paddle_tpu.serving.embedding import PLACEMENTS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_loadgen():
    spec = importlib.util.spec_from_file_location(
        "serving_loadgen_recsys_tests",
        os.path.join(REPO, "tools", "serving_loadgen.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


lg = _load_loadgen()


def _values(vocab=97, dim=5, seed=7):
    return np.random.RandomState(seed).standard_normal(
        (vocab, dim)).astype(np.float32)


# ---------------------------------------------------------------------------
# row sharding
# ---------------------------------------------------------------------------

def test_row_sharding_bijection_both_placements():
    vocab, shards = 97, 3
    for placement in PLACEMENTS:
        sh = RowSharding(vocab, shards, placement)
        seen = {}
        for s in range(shards):
            rows = sh.rows_of(s)
            assert len(rows) > 0
            for local, gid in enumerate(rows):
                assert gid not in seen, "row owned by two shards"
                seen[int(gid)] = (s, local)
        assert len(seen) == vocab, "every row owned exactly once"
        ids = np.arange(vocab)
        np.testing.assert_array_equal(
            sh.shard_of(ids), [seen[i][0] for i in range(vocab)])
        np.testing.assert_array_equal(
            sh.local_of(ids), [seen[i][1] for i in range(vocab)])


def test_row_sharding_validation():
    with pytest.raises(ValueError):
        RowSharding(10, 0)
    with pytest.raises(ValueError):
        RowSharding(10, 11)
    with pytest.raises(ValueError):
        RowSharding(10, 2, "hash-ring")


# ---------------------------------------------------------------------------
# bit-exact lookup
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("placement", ["mod", "range"])
def test_lookup_bit_exact_vs_unsharded(placement):
    vals = _values()
    table = ShardedEmbeddingTable(vals, shards=3, placement=placement,
                                  cache_rows=32)
    rng = np.random.RandomState(0)
    # duplicates + full coverage + a 2-D batch shape
    ids = rng.randint(0, 97, size=(4, 11)).astype(np.int64)
    ids[0, :3] = [5, 5, 5]
    out = table.lookup(ids)
    assert out.shape == (4, 11, 5)
    assert np.array_equal(out, vals[ids]), "cold lookup not bit-exact"
    # warm pass: now served (partly) from the hot-row cache — still
    # bit-exact, and the cache must have measured hits
    out2 = table.lookup(ids)
    assert np.array_equal(out2, vals[ids]), "warm lookup not bit-exact"
    assert table.cache.stats()["hits"] > 0
    assert table.cache.stats()["pinned"] == 0, "lookup leaked a pin"


def test_lookup_bit_exact_cache_disabled():
    vals = _values(vocab=41, dim=3)
    table = ShardedEmbeddingTable(vals, shards=4, cache_rows=0)
    ids = np.arange(41, dtype=np.int64)
    assert np.array_equal(table.lookup(ids), vals)
    assert len(table.cache) == 0
    hot = table.stats()["hot_rows"]
    assert hot["hits"] == 0 and hot["rows"] == 0


def test_lookup_oob_ids_default_row_and_counter():
    vals = _values(vocab=20, dim=4)
    table = ShardedEmbeddingTable(vals, shards=2, cache_rows=0)
    out = table.lookup(np.array([1, 20, 19], dtype=np.int64))
    assert np.array_equal(out[0], vals[1])
    assert np.array_equal(out[2], vals[19])
    assert np.array_equal(out[1], np.zeros(4, np.float32))
    assert table.stats()["counters"]["oob_rows"] == 1


# ---------------------------------------------------------------------------
# hot-row cache units
# ---------------------------------------------------------------------------

def test_hot_row_cache_lru_and_pinning():
    cache = HotRowCache(2, row_nbytes=12)
    row = np.ones(3, np.float32)
    assert cache.put(1, row) and cache.put(2, row)
    # pin 1 (a hit), then insert 3: the unpinned LRU victim is 2
    assert cache.get_pinned(1) is not None
    assert cache.put(3, row)
    assert cache.get_pinned(2) is None, "pinned row was evicted"
    st = cache.stats()
    assert st["evictions"] == 1 and st["rows"] == 2
    assert st["pinned"] == 1 and st["bytes"] == 24
    cache.unpin(1)
    assert cache.stats()["pinned"] == 0
    # all pinned -> an insert is skipped, never an eviction
    assert cache.get_pinned(1) is not None
    assert cache.get_pinned(3) is not None
    assert not cache.put(4, row)
    assert cache.stats()["insert_skips"] == 1
    # flush drops only unpinned rows
    cache.unpin(3)
    cache.flush()
    assert cache.get_pinned(3) is None
    assert cache.get_pinned(1) is not None
    cache.unpin(1)
    cache.unpin(1)  # back to refs=0 from the probe above


def test_hot_row_cache_unpin_underflow_raises():
    cache = HotRowCache(2, row_nbytes=4)
    cache.put(1, np.zeros(1, np.float32))
    with pytest.raises(AssertionError):
        cache.unpin(1)


def test_hot_row_cache_capacity_zero_disabled():
    cache = HotRowCache(0, row_nbytes=4)
    assert not cache.put(1, np.zeros(1, np.float32))
    assert cache.get_pinned(1) is None
    assert len(cache) == 0


# ---------------------------------------------------------------------------
# degradation contract
# ---------------------------------------------------------------------------

def test_dead_shard_degrades_and_revives():
    vals = _values(vocab=60, dim=4)
    table = ShardedEmbeddingTable(vals, shards=3, placement="mod",
                                  cache_rows=16)
    # warm id 3 (shard 0) into the hot-row cache
    table.lookup(np.array([3], dtype=np.int64))
    table.kill_shard(0)
    assert table.dead_shards == [0]
    assert table.placement()["missing_shards"] == [0]
    out = table.lookup(np.array([3, 6, 4], dtype=np.int64))
    # cached row of the dead shard: still exact; uncached row of the
    # dead shard: default row; live shard untouched
    assert np.array_equal(out[0], vals[3])
    assert np.array_equal(out[1], np.zeros(4, np.float32))
    assert np.array_equal(out[2], vals[4])
    n = table.stats()["counters"]
    assert n["degraded"] >= 1 and n["degraded_rows"] >= 1
    table.revive_shard(0)
    out = table.lookup(np.array([6], dtype=np.int64))
    assert np.array_equal(out[0], vals[6])
    assert table.placement()["missing_shards"] == []


def test_gather_fault_degrades_never_raises():
    vals = _values(vocab=30, dim=3)
    table = ShardedEmbeddingTable(vals, shards=2, cache_rows=0)
    fault.configure("embedding_gather:fail@1+")
    try:
        out = table.lookup(np.arange(30, dtype=np.int64))
    finally:
        fault.reset()
    assert out.shape == (30, 3)
    n = table.stats()["counters"]
    assert n["degraded"] >= 1, "injected gather fault never degraded"
    # every degraded row is the default row, every other row exact
    for i in range(30):
        assert (np.array_equal(out[i], vals[i])
                or np.array_equal(out[i], np.zeros(3, np.float32)))


# ---------------------------------------------------------------------------
# predictor + engine integration
# ---------------------------------------------------------------------------

def _tiny_predictor(**kw):
    cfg = dict(num_sparse=4, num_dense=3, vocab=50, embed_dim=4,
               hidden=(8,), shards=2, cache_rows=16)
    cfg.update(kw)
    return build_recsys_predictor(**cfg)


def _feed(i=0):
    rng = np.random.RandomState(100 + i)
    return {"sparse_ids": rng.randint(0, 50, size=(1, 4)).astype(
                np.int64),
            "dense_x": rng.rand(1, 3).astype(np.float32)}


def test_engine_predict_matches_direct_run():
    pred, shapes = _tiny_predictor()
    direct = [pred.run(_feed(i))[0] for i in range(6)]
    engine = ServingEngine(pred.clone(), workers=1, max_batch=4,
                           max_delay_ms=1.0, deadline_ms=60000.0,
                           buckets=batcher.fanin_bucket_sizes(4),
                           warmup_shapes=shapes)
    try:
        for i in range(6):
            got = engine.predict(_feed(i))[0]
            assert np.array_equal(got, direct[i]), \
                "batched serving path not bit-exact vs direct run"
        health = engine.health()
    finally:
        engine.close()
    assert health["capabilities"] == ["embedding"]
    emb = health["embedding"]
    assert emb["counters"]["lookups"] > 0
    assert "hit_rate" in emb and "hot_rows" in emb


def test_degraded_shard_reported_not_fatal_through_engine():
    pred, shapes = _tiny_predictor()
    engine = ServingEngine(pred, workers=1, max_batch=2,
                           max_delay_ms=1.0, deadline_ms=60000.0,
                           warmup_shapes=shapes)
    try:
        engine.predict(_feed(0))
        pred.table.kill_shard(1)
        out = engine.predict(_feed(1))  # still serves, degraded
        assert out[0].shape[0] == 1
        health = engine.health()
        assert health["embedding"]["dead_shards"] == [1]
        assert pred.placement()["missing_shards"] == [1]
    finally:
        engine.close()


def test_fanin_bucket_sizes():
    assert batcher.fanin_bucket_sizes(256) == (1, 2, 4, 8, 32, 128,
                                               256)
    assert batcher.fanin_bucket_sizes(64) == (1, 2, 4, 8, 32, 64)
    assert batcher.fanin_bucket_sizes(6) == (1, 2, 4, 6)
    assert batcher.fanin_bucket_sizes(1) == (1,)


# ---------------------------------------------------------------------------
# loadgen knobs
# ---------------------------------------------------------------------------

def test_zipf_ids_deterministic_bounded_and_skewed():
    a = lg.zipf_ids(np.random.RandomState(3), 1000, 4096, 1.2)
    b = lg.zipf_ids(np.random.RandomState(3), 1000, 4096, 1.2)
    np.testing.assert_array_equal(a, b)
    assert a.dtype == np.int64
    assert a.min() >= 0 and a.max() < 1000
    flat = lg.zipf_ids(np.random.RandomState(3), 1000, 4096, 0.2)
    # heavier skew concentrates mass on the low (hot) ids
    assert np.median(a) < np.median(flat)


def test_check_slo_hit_rate_floor():
    rep = {"mode": "closed", "requests": 8, "ok": 8, "shed": 0,
           "failed": 0, "wall_s": 1.0, "qps": 8.0,
           "latency_ms": {"count": 8, "p99": 5.0}, "hit_rate": 0.7}
    assert lg.check_slo(rep, hit_rate=0.5)["ok"]
    out = lg.check_slo(rep, hit_rate=0.9)
    assert not out["ok"] and out["hit_rate_limit"] == 0.9
    # a bound against a report that never measured the hit rate is a
    # violation, not a vacuous pass
    unmeasured = dict(rep)
    unmeasured.pop("hit_rate")
    out = lg.check_slo(unmeasured, hit_rate=0.5)
    assert not out["ok"]
    assert any("hit rate" in v for v in out["violations"])


# ---------------------------------------------------------------------------
# HTTP e2e: capability routing
# ---------------------------------------------------------------------------

def _post(url, route, body):
    req = urllib.request.Request(
        url + route, data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30.0) as r:
        return r.status, json.loads(r.read())


def test_http_capability_routing_end_to_end():
    pred, shapes = _tiny_predictor()
    emb_eng = ServingEngine(pred, workers=1, max_batch=4,
                            max_delay_ms=1.0, deadline_ms=60000.0,
                            warmup_shapes=shapes)
    den_pred, den_shapes = lg.build_synthetic(feat=4, hidden=8,
                                              depth=1)
    den_eng = ServingEngine(den_pred, workers=1, max_batch=2,
                            max_delay_ms=1.0, deadline_ms=60000.0,
                            warmup_shapes=den_shapes)
    emb_srv = den_srv = rsrv = None
    try:
        emb_srv = serve(emb_eng, port=0)
        den_srv = serve(den_eng, port=0)
        router = Router([emb_srv.url, den_srv.url], autostart=False)
        router.poll_once()
        assert router.embedding_active()
        rsrv = RouterServer(router).start()
        hz = json.loads(urllib.request.urlopen(
            rsrv.url + "/healthz", timeout=10.0).read())
        assert hz["embedding"] is True
        assert hz["capabilities"] == {"embedding": 1}

        sparse = json.dumps({"inputs": {
            "sparse_ids": [[1, 2, 3, 4]],
            "dense_x": [[0.1, 0.2, 0.3]]}}).encode()
        dense = json.dumps({"inputs": {
            "x": [[0.1, 0.2, 0.3, 0.4]]}}).encode()
        lookups0 = pred.embedding_stats()["counters"]["lookups"]
        for _ in range(3):
            status, _ = _post(rsrv.url, "/predict", sparse)
            assert status == 200
            status, _ = _post(rsrv.url, "/predict", dense)
            assert status == 200
        # sparse bodies landed on the embedding replica...
        assert pred.embedding_stats()["counters"]["lookups"] \
            == lookups0 + 3
        # ...and dense bodies were steered OFF it (a 26-slot feed on
        # the dense replica would have 400'd; symmetric steering means
        # the embedding replica never saw an {"x"} body either)
        assert den_eng.stats()["counters"]["requests"] >= 3
    finally:
        for srv in (rsrv, emb_srv, den_srv):
            if srv is not None:
                srv.close()
        emb_eng.close()
        den_eng.close()
