"""Flash / ring / Ulysses attention tests (new TPU capability;
reference had no fused-training attention or sequence parallelism)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.ops.pallas import blockwise_attention, flash_attention
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.ring import ring_attention, ulysses_attention

B, H, S, D = 2, 4, 128, 32


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(B, H, S, D).astype("float32")),
            jnp.asarray(rng.randn(B, H, S, D).astype("float32")),
            jnp.asarray(rng.randn(B, H, S, D).astype("float32")))


def _naive(q, k, v, causal=False):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_naive(causal):
    q, k, v = _qkv()
    out, _ = blockwise_attention(q, k, v, causal=causal, block_k=32)
    np.testing.assert_allclose(out, _naive(q, k, v, causal), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_kernel_matches_naive(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal, None, 64, 32, True)  # interpret
    np.testing.assert_allclose(out, _naive(q, k, v, causal), atol=2e-5)


def test_flash_gradients_match_naive():
    q, k, v = _qkv()
    g1 = jax.grad(lambda q: (flash_attention(
        q, k, v, True, None, 64, 64, True) ** 2).sum())(q)
    g2 = jax.grad(lambda q: (_naive(q, k, v, True) ** 2).sum())(q)
    np.testing.assert_allclose(g1, g2, atol=1e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    """Sequence sharded over sp=8: ring result == full attention."""
    from jax.sharding import PartitionSpec as P
    q, k, v = _qkv()
    mesh = make_mesh({"sp": 8})

    ring = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=causal),
        mesh=mesh, in_specs=(P(None, None, "sp"),) * 3,
        out_specs=P(None, None, "sp")))
    out = ring(q, k, v)
    np.testing.assert_allclose(out, _naive(q, k, v, causal), atol=2e-5)


def test_ring_attention_gradients():
    from jax.sharding import PartitionSpec as P
    q, k, v = _qkv()
    mesh = make_mesh({"sp": 8})

    def ring_loss(q, k, v):
        f = jax.shard_map(
            lambda a, b, c: ring_attention(a, b, c, "sp", causal=True),
            mesh=mesh, in_specs=(P(None, None, "sp"),) * 3,
            out_specs=P(None, None, "sp"))
        return (f(q, k, v) ** 2).sum()

    g1 = jax.jit(jax.grad(ring_loss))(q, k, v)
    g2 = jax.grad(lambda q: (_naive(q, k, v, True) ** 2).sum())(q)
    np.testing.assert_allclose(g1, g2, atol=1e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(causal):
    from jax.sharding import PartitionSpec as P
    q, k, v = _qkv()
    mesh = make_mesh({"sp": 4})  # H=4 heads divisible by 4

    uly = jax.jit(jax.shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "sp", causal=causal),
        mesh=mesh, in_specs=(P(None, None, "sp"),) * 3,
        out_specs=P(None, None, "sp")))
    out = uly(q, k, v)
    np.testing.assert_allclose(out, _naive(q, k, v, causal), atol=2e-5)


def test_flash_attention_op_and_layer():
    """Static-graph flash_attention op: forward + grads flow."""
    rng = np.random.RandomState(0)
    main, startup = pt.default_main_program(), pt.default_startup_program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [B, H, S, D], append_batch_size=False)
        q = layers.fc(x, D, num_flatten_dims=3)
        out = layers.flash_attention(q, x, x, causal=True)
        loss = layers.mean(out)
        from paddle_tpu import optimizer
        optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(startup)
    xv = rng.randn(B, H, S, D).astype("float32")
    l0 = float(exe.run(main, feed={"x": xv}, fetch_list=[loss])[0])
    for _ in range(3):
        l1 = float(exe.run(main, feed={"x": xv}, fetch_list=[loss])[0])
    assert np.isfinite(l1) and l1 != l0
