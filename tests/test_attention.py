"""Flash / ring / Ulysses attention tests (new TPU capability;
reference had no fused-training attention or sequence parallelism)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.ops.pallas import blockwise_attention, flash_attention
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.mesh import shard_map_compat
from paddle_tpu.parallel.ring import ring_attention, ulysses_attention

B, H, S, D = 2, 4, 128, 32


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(B, H, S, D).astype("float32")),
            jnp.asarray(rng.randn(B, H, S, D).astype("float32")),
            jnp.asarray(rng.randn(B, H, S, D).astype("float32")))


def _naive(q, k, v, causal=False):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_naive(causal):
    q, k, v = _qkv()
    out, _ = blockwise_attention(q, k, v, causal=causal, block_k=32)
    np.testing.assert_allclose(out, _naive(q, k, v, causal), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_kernel_matches_naive(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal, None, 64, 32, True)  # interpret
    np.testing.assert_allclose(out, _naive(q, k, v, causal), atol=2e-5)


def test_flash_gradients_match_naive():
    q, k, v = _qkv()
    g1 = jax.grad(lambda q: (flash_attention(
        q, k, v, True, None, 64, 64, True) ** 2).sum())(q)
    g2 = jax.grad(lambda q: (_naive(q, k, v, True) ** 2).sum())(q)
    np.testing.assert_allclose(g1, g2, atol=1e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    """Sequence sharded over sp=8: ring result == full attention."""
    from jax.sharding import PartitionSpec as P
    q, k, v = _qkv()
    mesh = make_mesh({"sp": 8})

    ring = jax.jit(shard_map_compat(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=causal),
        mesh, in_specs=(P(None, None, "sp"),) * 3,
        out_specs=P(None, None, "sp")))
    out = ring(q, k, v)
    np.testing.assert_allclose(out, _naive(q, k, v, causal), atol=2e-5)


def test_ring_attention_gradients():
    from jax.sharding import PartitionSpec as P
    q, k, v = _qkv()
    mesh = make_mesh({"sp": 8})

    def ring_loss(q, k, v):
        f = shard_map_compat(
            lambda a, b, c: ring_attention(a, b, c, "sp", causal=True),
            mesh, in_specs=(P(None, None, "sp"),) * 3,
            out_specs=P(None, None, "sp"))
        return (f(q, k, v) ** 2).sum()

    g1 = jax.jit(jax.grad(ring_loss))(q, k, v)
    g2 = jax.grad(lambda q: (_naive(q, k, v, True) ** 2).sum())(q)
    np.testing.assert_allclose(g1, g2, atol=1e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(causal):
    from jax.sharding import PartitionSpec as P
    q, k, v = _qkv()
    mesh = make_mesh({"sp": 4})  # H=4 heads divisible by 4

    uly = jax.jit(shard_map_compat(
        lambda q, k, v: ulysses_attention(q, k, v, "sp", causal=causal),
        mesh, in_specs=(P(None, None, "sp"),) * 3,
        out_specs=P(None, None, "sp")))
    out = uly(q, k, v)
    np.testing.assert_allclose(out, _naive(q, k, v, causal), atol=2e-5)


def test_flash_attention_op_and_layer():
    """Static-graph flash_attention op: forward + grads flow."""
    rng = np.random.RandomState(0)
    main, startup = pt.default_main_program(), pt.default_startup_program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [B, H, S, D], append_batch_size=False)
        q = layers.fc(x, D, num_flatten_dims=3)
        out = layers.flash_attention(q, x, x, causal=True)
        loss = layers.mean(out)
        from paddle_tpu import optimizer
        optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(startup)
    xv = rng.randn(B, H, S, D).astype("float32")
    l0 = float(exe.run(main, feed={"x": xv}, fetch_list=[loss])[0])
    for _ in range(3):
        l1 = float(exe.run(main, feed={"x": xv}, fetch_list=[loss])[0])
    assert np.isfinite(l1) and l1 != l0


def _naive_bias(q, k, v, bias_rows):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    s = s + bias_rows[:, None, None, :]
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)


def _pad_bias(seed=3):
    rng = np.random.RandomState(seed)
    mask = (rng.rand(B, S) < 0.8).astype("float32")
    mask[:, :4] = 1.0  # at least a few attended positions
    return jnp.asarray((mask - 1.0) * 10000.0)


def test_blockwise_bias_matches_naive():
    q, k, v = _qkv()
    bias = _pad_bias()
    out, _ = blockwise_attention(q, k, v, block_k=32, bias=bias)
    np.testing.assert_allclose(out, _naive_bias(q, k, v, bias), atol=2e-5)


def test_pallas_bias_kernel_matches_naive():
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_bias
    q, k, v = _qkv()
    bias = _pad_bias()
    out = flash_attention_bias(q, k, v, bias, False, None, 64, 32, True)
    np.testing.assert_allclose(out, _naive_bias(q, k, v, bias), atol=2e-5)


def test_flash_bias_gradients_match_naive():
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_bias
    q, k, v = _qkv()
    bias = _pad_bias()
    g1 = jax.grad(lambda q: (flash_attention_bias(
        q, k, v, bias, False, None, 64, 64, True) ** 2).sum())(q)
    g2 = jax.grad(lambda q: (_naive_bias(q, k, v, bias) ** 2).sum())(q)
    np.testing.assert_allclose(g1, g2, atol=1e-3)


def test_bert_flash_matches_unfused():
    """BERT encoder loss parity: flash path vs unfused reference math
    (dropout off so the graphs are numerically comparable)."""
    from paddle_tpu.models import build_bert_pretrain

    losses = []
    ref_params = None
    for use_flash in (False, True):
        main, startup = pt.Program(), pt.Program()
        startup._is_startup = True
        with pt.program_guard(main, startup):
            feeds, outs = build_bert_pretrain(
                batch_size=2, seq_len=32, vocab_size=128, hidden=32,
                num_layers=2, num_heads=2, intermediate=64, dropout=0.0,
                use_flash=use_flash)
        scope = pt.Scope()
        exe = pt.Executor()
        main.random_seed = startup.random_seed = 7
        exe.run(startup, scope=scope)
        # same weights for both graphs: params are created in the same
        # order, so copy run-1's initialized values positionally
        pnames = [p.name for p in main.global_block().all_parameters()]
        if ref_params is None:
            ref_params = [np.asarray(scope.find_var(n)) for n in pnames]
        else:
            assert len(pnames) == len(ref_params)
            for n, val in zip(pnames, ref_params):
                assert np.asarray(scope.find_var(n)).shape == val.shape
                scope.set_var(n, val)
        rng = np.random.RandomState(0)
        feed = {
            "input_ids": rng.randint(0, 128, (2, 32)).astype("int64"),
            "token_type_ids": np.zeros((2, 32), "int64"),
            "attn_mask": (rng.rand(2, 32) < 0.9).astype("float32"),
            "mlm_mask": (rng.rand(2, 32) < 0.15).astype("float32"),
            "mlm_labels": rng.randint(0, 128, (2, 32)).astype("int64"),
        }
        loss, = exe.run(main, feed=feed, fetch_list=[outs["loss"]],
                        scope=scope)
        losses.append(float(np.asarray(loss)))
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-4)


def test_einsum_impl_matches_unfused_both_layouts():
    """impl='xla' einsum attention == the reference matmul chain, in both
    bhsd and the transpose-free bshd layout, incl. bias and causal."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    B, H, S, D = 2, 3, 16, 8
    q = rng.randn(B, H, S, D).astype("float32")
    k = rng.randn(B, H, S, D).astype("float32")
    v = rng.randn(B, H, S, D).astype("float32")
    bias = np.where(rng.rand(B, S) < 0.2, -1e4, 0.0).astype("float32")

    def ref(q, k, v, bias, causal):
        s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        s = s + bias[:, None, None, :]
        if causal:
            s = np.where(np.tril(np.ones((S, S), bool))[None, None],
                         s, -1e30)
        e = np.exp(s - s.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        return np.einsum("bhqk,bhkd->bhqd", p, v)

    import paddle_tpu as pt
    from paddle_tpu import layers

    for causal in (False, True):
        for layout in ("bhsd", "bshd"):
            main, startup = pt.Program(), pt.Program()
            startup._is_startup = True
            with pt.program_guard(main, startup):
                shp = [B, H, S, D] if layout == "bhsd" else [B, S, H, D]
                qv = layers.data("q", shp, append_batch_size=False)
                kv = layers.data("k", shp, append_batch_size=False)
                vv = layers.data("v", shp, append_batch_size=False)
                bv = layers.data("bias", [B, S], append_batch_size=False)
                out = layers.flash_attention(qv, kv, vv, bias=bv,
                                             causal=causal, impl="xla",
                                             layout=layout, is_test=True)
            exe = pt.Executor()
            exe.run(startup)
            feed_q = q if layout == "bhsd" else q.transpose(0, 2, 1, 3)
            feed_k = k if layout == "bhsd" else k.transpose(0, 2, 1, 3)
            feed_v = v if layout == "bhsd" else v.transpose(0, 2, 1, 3)
            got, = exe.run(main, feed={"q": feed_q, "k": feed_k,
                                       "v": feed_v, "bias": bias},
                           fetch_list=[out])
            got = np.asarray(got)
            if layout == "bshd":
                got = got.transpose(0, 2, 1, 3)
            np.testing.assert_allclose(got, ref(q, k, v, bias, causal),
                                       rtol=2e-4, atol=2e-5,
                                       err_msg=f"{layout} causal={causal}")


def test_einsum_impl_dropout_statistics():
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu import layers

    B, H, S, D = 2, 2, 32, 8
    qv = layers.data("q", [B, H, S, D], append_batch_size=False)
    out = layers.flash_attention(qv, qv, qv, impl="xla",
                                 dropout_prob=0.5)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    x = np.random.RandomState(1).rand(B, H, S, D).astype("float32")
    o1, = exe.run(feed={"q": x}, fetch_list=[out])
    o2, = exe.run(feed={"q": x}, fetch_list=[out])
    # dropout active: stochastic across steps, but finite and same shape
    assert not np.allclose(np.asarray(o1), np.asarray(o2))
    assert np.isfinite(np.asarray(o1)).all()


# ---------------------------------------------------------------------------
# packed-QKV kernels (transpose-free [B, S, 3H] path)
# ---------------------------------------------------------------------------

PB, PS, PH, PNH = 2, 128, 256, 4  # head_dim 64, two heads per lane chunk


def _packed_ref(qkv, bias=None, causal=False, nh=PNH):
    b, s, three_h = qkv.shape
    h = three_h // 3
    d = h // nh
    x = qkv.reshape(b, s, 3, nh, d)
    q, k, v = x[:, :, 0], x[:, :, 1], x[:, :, 2]
    sc = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if bias is not None:
        sc = sc + bias[:, None, None, :]
    if causal:
        sc = jnp.where(jnp.tril(jnp.ones((s, s), bool))[None, None],
                       sc, -1e30)
    p = jax.nn.softmax(sc, -1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(b, s, h)


@pytest.mark.parametrize("causal", [False, True])
def test_packed_flash_matches_naive(causal):
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_packed

    rng = np.random.RandomState(0)
    qkv = jnp.asarray(rng.randn(PB, PS, 3 * PH).astype("float32"))
    out = flash_attention_packed(qkv, PNH, causal, None, 64, 32, True)
    ref = _packed_ref(qkv, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_packed_flash_grads_match_naive(causal):
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_packed

    rng = np.random.RandomState(1)
    qkv = jnp.asarray(rng.randn(PB, PS, 3 * PH).astype("float32"))
    g1 = jax.grad(lambda x: (flash_attention_packed(
        x, PNH, causal, None, 64, 32, True) ** 2).sum())(qkv)
    g2 = jax.grad(lambda x: (_packed_ref(x, causal=causal) ** 2).sum())(qkv)
    scale = float(jnp.abs(g2).max())
    np.testing.assert_allclose(np.asarray(g1) / scale,
                               np.asarray(g2) / scale, atol=2e-2)


def test_packed_flash_bias_and_grads():
    from paddle_tpu.ops.pallas.flash_attention import (
        flash_attention_packed_bias)

    rng = np.random.RandomState(2)
    qkv = jnp.asarray(rng.randn(PB, PS, 3 * PH).astype("float32"))
    bias = jnp.asarray(
        np.where(rng.rand(PB, PS) > 0.2, 0.0, -1e4).astype("float32"))
    out = flash_attention_packed_bias(qkv, bias, PNH, False, None, 64, 32,
                                      True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_packed_ref(qkv, bias)),
                               atol=2e-2, rtol=2e-2)
    g1 = jax.grad(lambda x, b: (flash_attention_packed_bias(
        x, b, PNH, False, None, 64, 32, True) ** 2).sum(), (0, 1))(qkv, bias)
    g2 = jax.grad(lambda x, b: (_packed_ref(x, b) ** 2).sum(), (0, 1))(
        qkv, bias)
    for a, b_ in zip(g1, g2):
        scale = float(jnp.abs(b_).max())
        np.testing.assert_allclose(np.asarray(a) / scale,
                                   np.asarray(b_) / scale, atol=2e-2)


def test_packed_flash_head_dim_128():
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_packed

    rng = np.random.RandomState(3)
    qkv = jnp.asarray(rng.randn(PB, PS, 3 * 256).astype("float32"))
    out = flash_attention_packed(qkv, 2, False, None, 64, 32, True)
    ref = _packed_ref(qkv, nh=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)


def test_flash_attention_qkv_op_and_layer():
    """Static-graph flash_attention_qkv op: forward + grads flow, and the
    fallback (CPU/mesh) path matches the packed-kernel math."""
    main_p, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main_p, startup):
        x = layers.data("x", [PB, PS, 3 * PH], append_batch_size=False)
        x.stop_gradient = False
        bias = layers.data("bias", [PB, PS], append_batch_size=False)
        out = layers.flash_attention_qkv(x, PNH, bias=bias)
        loss = layers.reduce_mean(out)
        pt.append_backward(loss)
    exe = pt.Executor()
    exe.run(startup)
    rng = np.random.RandomState(4)
    xv = rng.randn(PB, PS, 3 * PH).astype("float32")
    bv = np.where(rng.rand(PB, PS) > 0.2, 0.0, -1e4).astype("float32")
    outs = exe.run(main_p, feed={"x": xv, "bias": bv},
                   fetch_list=[out.name, "x@GRAD"])
    ref = _packed_ref(jnp.asarray(xv), jnp.asarray(bv))
    np.testing.assert_allclose(outs[0], np.asarray(ref), atol=2e-2,
                               rtol=2e-2)
    assert np.abs(outs[1]).max() > 0
