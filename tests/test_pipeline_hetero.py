"""Heterogeneous-stage pipeline tests: embedding front stage, uneven
splits, multi-var boundary (skip connection), GPipe vs 1F1B parity.

Reference semantics target: framework/section_worker.cc:44-119 runs
arbitrary per-stage sections — the stacked fast path could not
(VERDICT r2 weak #4); build_hetero_pp_step does.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, optimizer
from paddle_tpu.framework.core import device_guard, reset_unique_name
from paddle_tpu.ops.registry import reset_op_seed
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.pipeline_hetero import (FLAT_NAME,
                                                 build_hetero_pp_step)

VOCAB, EMB, HID, NCLS = 16, 8, 12, 4


def _build(opt_cls=optimizer.SGDOptimizer, lr=0.1):
    """2 uneven stages: embedding+fc front, 2xfc+loss tail, with a skip
    connection crossing the boundary (multi-var transport)."""
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    reset_unique_name()
    reset_op_seed()
    with pt.program_guard(main, startup):
        ids = layers.data("ids", [6], dtype="int64")          # [b, 6]
        label = layers.data("label", [1], dtype="int64")
        with device_guard("gpu:0"):
            emb = layers.embedding(ids, [VOCAB, EMB], param_attr="emb_w")
            flat = layers.flatten(emb, axis=1)                # [b, 48]
            h0 = layers.fc(flat, HID, act="tanh", name="s0fc")
        with device_guard("gpu:1"):
            h1 = layers.fc(h0, HID, act="tanh", name="s1fc_a")
            h1b = layers.elementwise_add(h1, h0)              # skip: h0
            h2 = layers.fc(h1b, HID, act="tanh", name="s1fc_b")
            logits = layers.fc(h2, NCLS, name="s1head")
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        opt_cls(learning_rate=lr).minimize(loss)
    return main, startup, loss


def _feed(batch, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, VOCAB, (batch, 6)).astype("int64")
    label = (ids.sum(1) % NCLS).astype("int64")[:, None]
    return {"ids": ids, "label": label}


def _run_plain(steps, feed, opt_cls=optimizer.SGDOptimizer):
    main, startup, loss = _build(opt_cls)
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    init = {p.name: np.asarray(scope.find_var(p.name))
            for p in main.global_block().all_parameters()}
    losses = [float(np.asarray(exe.run(main, feed=feed,
                                       fetch_list=[loss],
                                       scope=scope)[0]).reshape(-1)[0])
              for _ in range(steps)]
    params = {p.name: np.asarray(scope.find_var(p.name))
              for p in main.global_block().all_parameters()}
    return init, losses, params


def _run_pp(steps, feed, mesh, microbatches, init, schedule,
            opt_cls=optimizer.SGDOptimizer):
    main, startup, loss = _build(opt_cls)
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    for (n, v) in init.items():
        scope.set_var(n, v)

    fn, mut_in, const_in, _ = build_hetero_pp_step(
        main, ["ids", "label"], [loss.name], microbatches, mesh,
        schedule=schedule)
    fn.prepare_scope(scope)

    flat = scope.find_var(FLAT_NAME)
    # placement assertion: each device holds only its stage's flat shard
    assert flat.sharding.spec[0] == "pp"

    feed_vals = tuple(feed[n] for n in ["ids", "label"])
    mut = tuple(scope.find_var(n) for n in mut_in)
    const = tuple(scope.find_var(n) for n in const_in)
    losses = []
    for i in range(steps):
        fetches, mut, _x = fn(feed_vals, mut, const, np.int32(i + 1))
        losses.append(float(np.asarray(fetches[0]).reshape(-1)[0]))
    for n, v in zip(mut_in, mut):
        scope.set_var(n, v)
    fn.sync_scope(scope, mut)
    params = {p.name: np.asarray(scope.find_var(p.name))
              for p in main.global_block().all_parameters()}
    return losses, params


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_hetero_pp2_matches_plain(schedule):
    feed = _feed(8)
    init, ref_losses, ref_params = _run_plain(4, feed)
    mesh = make_mesh({"pp": 2})
    losses, params = _run_pp(4, feed, mesh, microbatches=4, init=init,
                             schedule=schedule)
    np.testing.assert_allclose(losses, ref_losses, rtol=5e-5, atol=1e-6)
    for n in ref_params:
        np.testing.assert_allclose(params[n], ref_params[n], rtol=5e-4,
                                   atol=1e-5, err_msg=n)


def test_hetero_pp2_dp2_adam():
    """pp2 x dp2, Adam, embedding front stage — the VERDICT 'done'
    config."""
    feed = _feed(8)
    init, ref_losses, ref_params = _run_plain(
        4, feed, opt_cls=optimizer.AdamOptimizer)
    mesh = make_mesh({"pp": 2, "dp": 2})
    losses, params = _run_pp(4, feed, mesh, microbatches=2, init=init,
                             schedule="gpipe",
                             opt_cls=optimizer.AdamOptimizer)
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4, atol=1e-6)
    for n in ref_params:
        np.testing.assert_allclose(params[n], ref_params[n], rtol=1e-3,
                                   atol=2e-5, err_msg=n)


def test_1f1b_matches_gpipe_exactly():
    feed = _feed(8)
    init, _, _ = _run_plain(1, feed)
    mesh = make_mesh({"pp": 2})
    l_g, p_g = _run_pp(3, feed, mesh, 4, init, "gpipe")
    l_1, p_1 = _run_pp(3, feed, mesh, 4, init, "1f1b")
    np.testing.assert_allclose(l_1, l_g, rtol=1e-5, atol=1e-7)
    for n in p_g:
        np.testing.assert_allclose(p_1[n], p_g[n], rtol=1e-5, atol=1e-7,
                                   err_msg=n)


def test_hetero_four_uneven_stages():
    """4 stages of different widths/op counts train and converge."""
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    reset_unique_name()
    reset_op_seed()
    with pt.program_guard(main, startup):
        x = layers.data("x", [10], dtype="float32")
        label = layers.data("label", [1], dtype="float32")
        widths = [16, 24, 8, 4]
        h = x
        for s, w in enumerate(widths):
            with device_guard(f"gpu:{s}"):
                h = layers.fc(h, w, act="tanh", name=f"u{s}")
                if s == 1:  # extra depth on stage 1 (uneven op count)
                    h = layers.fc(h, w, act="tanh", name=f"u{s}b")
        with device_guard("gpu:3"):
            pred = layers.fc(h, 1, name="head")
        loss = layers.mean(pt.layers.square_error_cost(pred, label))
        optimizer.SGDOptimizer(0.2).minimize(loss)

    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    mesh = make_mesh({"pp": 4, "dp": 2})
    fn, mut_in, const_in, _ = build_hetero_pp_step(
        main, ["x", "label"], [loss.name], 4, mesh, schedule="1f1b")
    fn.prepare_scope(scope)
    rng = np.random.RandomState(0)
    xv = rng.rand(16, 10).astype("float32")
    yv = (xv.sum(1, keepdims=True) * 0.3).astype("float32")
    mut = tuple(scope.find_var(n) for n in mut_in)
    const = tuple(scope.find_var(n) for n in const_in)
    losses = []
    for i in range(30):
        fetches, mut, _x = fn((xv, yv), mut, const, np.int32(i + 1))
        losses.append(float(np.asarray(fetches[0]).reshape(-1)[0]))
    assert losses[-1] < 0.1 * losses[0], (losses[0], losses[-1])
