"""OpTests for the round-5 misc batch (ops/misc2_ops.py).

Reference unittests: test_space_to_depth_op.py, test_crop_op.py,
test_pad_constant_like.py, test_expand_as_op.py, test_frobenius_norm_op
.py, test_cross_entropy2_op.py, test_where_index.py, test_sigmoid_focal
_loss_op.py, test_shuffle_batch_op.py, test_sample_logits.py,
test_positive_negative_pair_op.py, test_hash_op.py,
test_coalesce_tensor_op.py, test_inplace_abn_op.py.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from op_test import OpCase, run_case

R = np.random.RandomState


def test_space_to_depth():
    x = R(0).randn(2, 8, 4, 6).astype("float32")
    bs = 2
    b, c, h, w = x.shape
    c2 = c // (bs * bs)
    # literal reference functor loop (space_to_depth_op.h:39)
    out = np.zeros(b * c * h * w, "float32")
    xf = x.reshape(-1)
    for idx in range(b * c * h * w):
        bb = idx // (c * h * w)
        k = (idx % (c * h * w)) // (h * w)
        j = ((idx % (c * h * w)) % (h * w)) // w
        i = ((idx % (c * h * w)) % (h * w)) % w
        cc = k % c2
        off = k // c2
        w2 = i * bs + off % bs
        h2 = j * bs + off // bs
        out[w2 + w * bs * (h2 + h * bs * (cc + c2 * bb))] = xf[idx]
    ref = out.reshape(b, c * bs * bs, h // bs, w // bs)
    run_case(OpCase("space_to_depth", {"X": x}, attrs={"blocksize": 2},
                    ref=lambda X, **a: ref, grad=["X"]))


def test_crop_and_crop_tensor():
    x = R(1).randn(4, 6, 5).astype("float32")
    for op in ("crop", "crop_tensor"):
        run_case(OpCase(
            op, {"X": x},
            attrs={"offsets": [1, 2, 0], "shape": [2, 3, 4]},
            ref=lambda X, **a: X[1:3, 2:5, 0:4], grad=["X"]))


def test_pad_constant_like():
    x = np.zeros((4, 5), "float32")
    y = R(2).randn(2, 3).astype("float32")
    ref = np.full((4, 5), 1.5, "float32")
    ref[:2, :3] = y
    run_case(OpCase(
        "pad_constant_like", {"X": x, "Y": y},
        attrs={"pad_value": 1.5},
        ref=lambda X, Y, **a: ref, grad=["Y"]))


def test_expand_as():
    x = R(3).randn(2, 1, 3).astype("float32")
    run_case(OpCase(
        "expand_as", {"X": x, "Y": np.zeros((4, 1, 3), "float32")},
        ref=lambda X, Y: np.tile(X, (2, 1, 1)), grad=["X"]))
    # v2 = numpy broadcasting rules (1-dims expand, others must match)
    run_case(OpCase(
        "expand_as_v2", {"X": x, "Y": np.zeros((2, 5, 3), "float32")},
        ref=lambda X, Y: np.broadcast_to(X, (2, 5, 3)), grad=["X"]))


def test_frobenius_norm():
    x = R(4).randn(3, 4, 5).astype("float32")
    run_case(OpCase(
        "frobenius_norm", {"X": x}, attrs={"dim": [1, 2],
                                           "keep_dim": False},
        ref=lambda X, **a: np.sqrt((X ** 2).sum((1, 2))),
        grad=["X"], rtol=1e-4, atol=1e-5))
    run_case(OpCase(
        "frobenius_norm", {"X": x}, attrs={"reduce_all": True},
        ref=lambda X, **a: np.sqrt((X ** 2).sum()),
        grad=["X"], rtol=1e-4, atol=1e-5))


def test_cross_entropy2():
    x = R(5).uniform(0.05, 1.0, (4, 7)).astype("float32")
    x /= x.sum(-1, keepdims=True)
    lab = np.array([[1], [3], [0], [6]], "int64")
    match = np.take_along_axis(x, lab, 1)
    run_case(OpCase(
        "cross_entropy2", {"X": x, "Label": lab},
        outputs={"Y": 1, "MatchX": 1, "XShape": 1},
        ref=lambda X, Label: {"Y": -np.log(match), "MatchX": match},
        grad=["X"], rtol=1e-4, atol=1e-5))


def test_cross_entropy2_ignore_index():
    x = R(6).uniform(0.05, 1.0, (3, 4)).astype("float32")
    lab = np.array([[2], [-100], [1]], "int64")
    safe = np.where(lab == -100, 0, lab)
    match = np.take_along_axis(x, safe, 1)
    y = -np.log(match)
    y[1] = 0.0
    run_case(OpCase(
        "cross_entropy2", {"X": x, "Label": lab},
        outputs={"Y": 1, "MatchX": 1, "XShape": 1},
        attrs={"ignore_index": -100},
        ref=lambda X, Label, **a: {"Y": y, "MatchX": match},
        rtol=1e-4, atol=1e-5))


def test_where_index():
    cond = np.array([[True, False, True], [False, False, True]])
    ref = np.array([[0, 0], [0, 2], [1, 2],
                    [-1, -1], [-1, -1], [-1, -1]], "int64")
    run_case(OpCase("where_index", {"Condition": cond},
                    ref=lambda Condition: ref, check_dtype=True))


def test_sigmoid_focal_loss():
    n, c = 6, 5
    x = R(7).randn(n, c).astype("float32")
    label = np.array([[1], [0], [3], [-1], [5], [2]], "int64")
    fg = np.array([3], "int32")
    gamma, alpha = 2.0, 0.25
    # loop reference (sigmoid_focal_loss_op.cu:41)
    ref = np.zeros((n, c), "float32")
    for i in range(n):
        for d in range(c):
            xx = x[i, d]
            g = label[i, 0]
            c_pos = float(g == d + 1)
            c_neg = float((g != -1) and (g != d + 1))
            fgn = max(fg[0], 1)
            s_pos, s_neg = alpha / fgn, (1 - alpha) / fgn
            p = 1 / (1 + np.exp(-xx))
            term_pos = (1 - p) ** gamma * np.log(max(p, 1e-38))
            term_neg = p ** gamma * (
                -xx * (xx >= 0) - np.log(1 + np.exp(xx - 2 * xx * (xx >= 0))))
            ref[i, d] = -c_pos * term_pos * s_pos - c_neg * term_neg * s_neg
    run_case(OpCase(
        "sigmoid_focal_loss", {"X": x, "Label": label, "FgNum": fg},
        attrs={"gamma": gamma, "alpha": alpha},
        ref=lambda X, Label, FgNum, **a: ref,
        grad=["X"], rtol=1e-4, atol=1e-5))


def test_shuffle_batch():
    """Out must be a permutation of rows and ShuffleIdx must describe it."""
    x = np.arange(20, dtype="float32").reshape(5, 4)
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        xv = pt.layers.data(name="x", shape=[4], dtype="float32")
        block = main.global_block()
        out = block.create_var(name="sb_out", shape=[5, 4],
                               dtype="float32")
        idx = block.create_var(name="sb_idx", shape=[5], dtype="int64")
        block.append_op("shuffle_batch", inputs={"X": [xv.name]},
                        outputs={"Out": [out.name],
                                 "ShuffleIdx": [idx.name]}, attrs={})
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    o, i = exe.run(main, feed={"x": x}, fetch_list=["sb_out", "sb_idx"],
                   scope=scope)
    o, i = np.asarray(o), np.asarray(i)
    assert sorted(i.tolist()) == list(range(5))
    np.testing.assert_allclose(o, x[i])


def test_sample_logits():
    n, vocab, nt, s = 3, 50, 1, 8
    logits = R(8).randn(n, vocab).astype("float32")
    labels = np.array([[5], [0], [49]], "int64")
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        block = main.global_block()
        lg = block.create_var(name="lg", shape=[n, vocab],
                              dtype="float32", is_data=True)
        lb = block.create_var(name="lb", shape=[n, nt], dtype="int64",
                              is_data=True)
        outs = {}
        for slot, shp, dt in [("Samples", [n, nt + s], "int64"),
                              ("Probabilities", [n, nt + s], "float32"),
                              ("SampledLogits", [n, nt + s], "float32"),
                              ("SampledLabels", [n, nt], "int64")]:
            outs[slot] = [block.create_var(name=f"sl_{slot}", shape=shp,
                                           dtype=dt).name]
        block.append_op("sample_logits",
                        inputs={"Logits": ["lg"], "Labels": ["lb"]},
                        outputs=outs,
                        attrs={"num_samples": s,
                               "remove_accidental_hits": False})
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    sm, pr, sl, slab = (np.asarray(v) for v in exe.run(
        main, feed={"lg": logits, "lb": labels},
        fetch_list=["sl_Samples", "sl_Probabilities",
                    "sl_SampledLogits", "sl_SampledLabels"],
        scope=scope))
    # first nt columns are the true labels
    np.testing.assert_array_equal(sm[:, :nt], labels)
    assert (sm >= 0).all() and (sm < vocab).all()
    # probabilities follow the log-uniform marginal
    expect_p = np.log((sm + 2.0) / (sm + 1.0)) / np.log(vocab + 1.0)
    np.testing.assert_allclose(pr, expect_p, rtol=1e-5)
    # sampled logits = gathered logit - log(q)
    gathered = np.take_along_axis(logits, sm, 1)
    np.testing.assert_allclose(sl, gathered - np.log(expect_p),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(slab, np.zeros((n, nt), "int64"))


def test_positive_negative_pair():
    score = np.array([[0.9], [0.4], [0.6], [0.2], [0.8]], "float32")
    label = np.array([[1], [0], [1], [0], [1]], "float32")
    qid = np.array([[0], [0], [0], [1], [1]], "int64")
    # q0: pairs (0,1): lab 1>0, s .9>.4 pos; (1,2): lab 0<1, s... hi=2:
    #     .6>.4 pos; (0,2) same label skip. q1: (3,4): hi=4 .8>.2 pos
    run_case(OpCase(
        "positive_negative_pair",
        {"Score": score, "Label": label, "QueryID": qid},
        outputs={"PositivePair": 1, "NegativePair": 1, "NeutralPair": 1},
        attrs={"column": -1},
        ref=lambda Score, Label, QueryID, **a: {
            "PositivePair": np.array([3.0], "float32"),
            "NegativePair": np.array([0.0], "float32"),
            "NeutralPair": np.array([0.0], "float32")},
        check_dtype=False))


def test_hash_op():
    x = np.array([[1, 2], [3, 4], [1, 2]], "int64")
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        block = main.global_block()
        xv = block.create_var(name="hx", shape=[3, 2], dtype="int64",
                              is_data=True)
        out = block.create_var(name="hout", shape=[3, 4, 1],
                               dtype="int64")
        block.append_op("hash", inputs={"X": ["hx"]},
                        outputs={"Out": ["hout"]},
                        attrs={"num_hash": 4, "mod_by": 10000})
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    o, = exe.run(main, feed={"hx": x}, fetch_list=["hout"], scope=scope)
    o = np.asarray(o)
    assert o.shape == (3, 4, 1)
    assert (o >= 0).all() and (o < 10000).all()
    np.testing.assert_array_equal(o[0], o[2])  # deterministic
    assert len({tuple(o[0, :, 0]), tuple(o[1, :, 0])}) == 2


def test_coalesce_tensor():
    a = R(9).randn(2, 3).astype("float32")
    b = R(10).randn(4).astype("float32")
    run_case(OpCase(
        "coalesce_tensor", {"Input": [a, b]},
        outputs={"Output": 2, "FusedOutput": 1},
        attrs={"copy_data": True},
        ref=lambda Input, **at: {
            "Output": [a, b],
            "FusedOutput": np.concatenate([a.reshape(-1), b])},
    ))


def test_inplace_abn_matches_bn_relu():
    x = R(11).randn(4, 3, 5, 5).astype("float32")
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        xv = pt.layers.data(name="ax", shape=[3, 5, 5], dtype="float32")
        bn = pt.layers.batch_norm(xv, act="relu")
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    want, = exe.run(main, feed={"ax": x}, fetch_list=[bn.name],
                    scope=scope)

    main2, startup2 = pt.Program(), pt.Program()
    startup2._is_startup = True
    with pt.program_guard(main2, startup2):
        xv = pt.layers.data(name="ax", shape=[3, 5, 5], dtype="float32")
        block = main2.global_block()
        c = 3
        params = {}
        for nm, init in [("scale", 1.0), ("bias", 0.0), ("mean", 0.0),
                         ("var", 1.0)]:
            v = block.create_var(name=f"abn_{nm}", shape=[c],
                                 dtype="float32", persistable=True)
            startup2.global_block().create_var(
                name=f"abn_{nm}", shape=[c], dtype="float32",
                persistable=True)
            startup2.global_block().append_op(
                "fill_constant", inputs={},
                outputs={"Out": [f"abn_{nm}"]},
                attrs={"shape": [c], "value": init, "dtype": "float32"})
            params[nm] = v
        outs = {s: [block.create_var(name=f"abn_{s}", shape=[c],
                                     dtype="float32").name]
                for s in ("MeanOut", "VarianceOut", "SavedMean",
                          "SavedVariance")}
        y = block.create_var(name="abn_y", shape=[4, 3, 5, 5],
                             dtype="float32")
        outs["Y"] = [y.name]
        block.append_op(
            "inplace_abn",
            inputs={"X": [xv.name], "Scale": ["abn_scale"],
                    "Bias": ["abn_bias"], "Mean": ["abn_mean"],
                    "Variance": ["abn_var"]},
            outputs=outs, attrs={"activation": "relu"})
    exe.run(startup2, scope=scope)
    got, = exe.run(main2, feed={"ax": x}, fetch_list=["abn_y"],
                   scope=scope)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
