"""OpTests for the round-5 catalog batches (catalog_seq_ops,
catalog_ctr_ops, quant/optimizer/dgc/attention additions).

Reference unittests: test_sequence_reshape.py, test_sequence_scatter_op
.py, test_lod_reset_op.py, test_split_merge_lod_tensor_op.py,
test_shrink_rnn_memory.py, test_merge_selected_rows_op.py,
test_split_ids_op.py / test_merge_ids_op.py, test_select_input_output
_op.py, test_batch_fc_op.py, test_rank_attention_op.py,
test_tree_conv_op.py, test_var_conv_2d.py, test_pyramid_hash_op.py,
test_filter_by_instag_op.py, test_prroi_pool_op.py, test_correlation
.py, test_chunk_eval_op.py, test_quantize_op.py, test_proximal_adagrad
_op.py, test_dgc_op.py, test_fused_multihead_matmul_op.py,
test_skip_layernorm_fuse_pass.py, test_fused_emb_seq_pool_op.py.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from op_test import OpCase, run_case

R = np.random.RandomState


def _run_program(op_type, inputs, outputs, attrs, feed_extra=None):
    """Build a one-op program, run it, return fetched outputs (dict)."""
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    feed = {}
    with pt.program_guard(main, startup):
        block = main.global_block()
        in_slots = {}
        for slot, arrs in inputs.items():
            names = []
            arrs_l = arrs if isinstance(arrs, list) else [arrs]
            for j, a in enumerate(arrs_l):
                n = f"i_{slot}_{j}"
                block.create_var(name=n, shape=a.shape,
                                 dtype=str(a.dtype), is_data=True)
                feed[n] = a
                names.append(n)
            in_slots[slot] = names
        out_slots = {s: [f"o_{s}_{j}" for j in range(c)]
                     for s, c in outputs.items()}
        block.append_op(op_type, inputs=in_slots, outputs=out_slots,
                        attrs=attrs)
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    names = [n for ns in out_slots.values() for n in ns]
    vals = exe.run(main, feed=feed, fetch_list=names, scope=scope)
    return dict(zip(names, [np.asarray(v) for v in vals]))


# ---------------------------------------------------------------------------
# sequence / LoD
# ---------------------------------------------------------------------------
def test_sequence_reshape():
    x = R(0).randn(2, 4, 6).astype("float32")
    lens = np.array([4, 2], "int64")
    out = _run_program(
        "sequence_reshape", {"X": x, "Lengths": lens},
        {"Out": 1, "LengthsOut": 1}, {"new_dim": 3})
    np.testing.assert_allclose(out["o_Out_0"], x.reshape(2, 8, 3))
    np.testing.assert_array_equal(out["o_LengthsOut_0"], [8, 4])


def test_sequence_scatter():
    x = R(1).randn(2, 6).astype("float32")
    ids = np.array([[0, 2, 3], [1, 1, 4]], "int64")
    upd = R(2).randn(2, 3).astype("float32")
    lens = np.array([3, 2], "int64")
    ref = x.copy()
    for b in range(2):
        for t in range(int(lens[b])):
            ref[b, ids[b, t]] += upd[b, t]
    run_case(OpCase(
        "sequence_scatter",
        {"X": x, "Ids": ids, "Updates": upd, "Lengths": lens},
        ref=lambda **kw: ref, grad=["X", "Updates"]))


def test_lod_reset():
    x = R(3).randn(3, 4).astype("float32")
    y = np.array([2, 1, 4], "int64")
    out = _run_program("lod_reset", {"X": x, "Y": y},
                       {"Out": 1, "LengthsOut": 1}, {})
    np.testing.assert_allclose(out["o_Out_0"], x)
    np.testing.assert_array_equal(out["o_LengthsOut_0"], y)


def test_tensor_array_bridges():
    x = R(4).randn(2, 3, 5).astype("float32")
    out = _run_program("lod_tensor_to_array", {"X": x}, {"Out": 1}, {})
    np.testing.assert_allclose(out["o_Out_0"], x.swapaxes(0, 1))
    back = _run_program("array_to_lod_tensor",
                        {"X": x.swapaxes(0, 1)}, {"Out": 1}, {})
    np.testing.assert_allclose(back["o_Out_0"], x)


def test_split_merge_lod_tensor():
    x = R(5).randn(4, 3).astype("float32")
    mask = np.array([[1], [0], [1], [0]], "int32")
    out = _run_program("split_lod_tensor", {"X": x, "Mask": mask},
                       {"OutTrue": 1, "OutFalse": 1}, {})
    np.testing.assert_allclose(out["o_OutTrue_0"],
                               np.where(mask.astype(bool), x, 0))
    np.testing.assert_allclose(out["o_OutFalse_0"],
                               np.where(mask.astype(bool), 0, x))
    merged = _run_program(
        "merge_lod_tensor",
        {"InTrue": out["o_OutTrue_0"], "InFalse": out["o_OutFalse_0"],
         "Mask": mask}, {"Out": 1}, {})
    np.testing.assert_allclose(merged["o_Out_0"], x)


def test_shrink_rnn_memory():
    x = R(6).randn(3, 4).astype("float32")
    lens = np.array([5, 2, 3], "int64")
    i = np.array([2], "int64")
    out = _run_program("shrink_rnn_memory",
                       {"X": x, "I": i, "Lengths": lens}, {"Out": 1}, {})
    ref = x.copy()
    ref[1] = 0  # length 2 <= step 2 -> dead
    np.testing.assert_allclose(out["o_Out_0"], ref)


def test_select_input_output():
    a, b = (R(7).randn(2, 3).astype("float32") for _ in range(2))
    mask = np.array([1], "int32")
    out = _run_program("select_input", {"X": [a, b], "Mask": mask},
                       {"Out": 1}, {})
    np.testing.assert_allclose(out["o_Out_0"], b)
    out = _run_program("select_output", {"X": a, "Mask": mask},
                       {"Out": 2}, {})
    np.testing.assert_allclose(out["o_Out_0"], np.zeros_like(a))
    np.testing.assert_allclose(out["o_Out_1"], a)


def test_split_merge_ids():
    ids = np.array([[3], [4], [7], [10]], "int64")
    out = _run_program("split_ids", {"Ids": ids}, {"Out": 2}, {})
    np.testing.assert_array_equal(out["o_Out_0"].reshape(-1),
                                  [-1, 4, -1, 10])
    np.testing.assert_array_equal(out["o_Out_1"].reshape(-1),
                                  [3, -1, 7, -1])
    # merge: two shards' lookup results back in query order
    rows0 = np.array([4, 10], "int64")
    rows1 = np.array([3, 7], "int64")
    emb0 = R(8).randn(2, 5).astype("float32")
    emb1 = R(9).randn(2, 5).astype("float32")
    merged = _run_program(
        "merge_ids",
        {"Ids": ids, "Rows": [rows0, rows1], "X": [emb0, emb1]},
        {"Out": 1}, {})
    want = np.stack([emb1[0], emb0[0], emb1[1], emb0[1]])
    np.testing.assert_allclose(merged["o_Out_0"], want)


# ---------------------------------------------------------------------------
# CTR / text / detection
# ---------------------------------------------------------------------------
def test_batch_fc():
    x = R(10).randn(3, 4, 5).astype("float32")
    w = R(11).randn(3, 5, 6).astype("float32")
    b = R(12).randn(3, 1, 6).astype("float32")
    run_case(OpCase(
        "batch_fc", {"Input": x, "W": w, "Bias": b},
        ref=lambda Input, W, Bias: np.einsum("sid,sdo->sio", Input, W)
        + Bias,
        grad=["Input", "W"], rtol=1e-4, atol=1e-5))


def test_rank_attention():
    n, d, R_, pcol = 4, 3, 2, 5
    x = R(13).randn(n, d).astype("float32")
    param = R(14).randn(R_ * R_ * d, pcol).astype("float32")
    # rows: [own_rank, faster_1, index_1, faster_2, index_2]
    ro = np.array([
        [1, 1, 0, 2, 1],
        [2, 1, 0, 2, 1],
        [1, 2, 3, 0, 0],    # second slot invalid (faster=0)
        [0, 1, 0, 1, 1],    # own rank invalid -> all zero
    ], "int32")
    ref = np.zeros((n, pcol), "float32")
    pr = param.reshape(R_ * R_, d, pcol)
    for i in range(n):
        lower = ro[i, 0] - 1
        if lower < 0:
            continue
        for k in range(R_):
            faster = ro[i, 2 * k + 1] - 1
            if faster < 0:
                continue
            idx = ro[i, 2 * k + 2]
            ref[i] += x[idx] @ pr[lower * R_ + faster]
    run_case(OpCase(
        "rank_attention",
        {"X": x, "RankOffset": ro, "RankParam": param},
        attrs={"MaxRank": R_},
        ref=lambda **kw: ref, grad=["X", "RankParam"],
        rtol=1e-4, atol=1e-5))


def test_tree_conv():
    # tree: 1 -> (2, 3); 2 -> (4,)   (1-based, one batch)
    edges = np.array([[[1, 2], [1, 3], [2, 4], [0, 0]]], "int32")
    N, F, G, M, D = 5, 3, 2, 2, 2
    x = R(15).randn(1, N, F).astype("float32")
    w = R(16).randn(F, 3, G, M).astype("float32")
    # loop reference per tree2col.cc construct_patch + tree2col.h etas
    children = {1: [2, 3], 2: [4], 3: [], 4: [], 5: []}
    parent_meta = {2: (1, 2), 3: (2, 2), 4: (1, 1)}  # node->(idx,pclen)

    def patch(root):
        # DFS limited to depth < D
        items = [(root, 1, 1, 0)]
        stack = [(root, 0)]
        while stack:
            u, dep = stack.pop()
            if dep + 1 < D:
                for i, v in enumerate(children[u]):
                    idx, pclen = i + 1, len(children[u])
                    items.append((v, idx, pclen, dep + 1))
                    stack.append((v, dep + 1))
        return items

    ref = np.zeros((1, N, G, M), "float32")
    for u in range(1, N + 1):
        acc = np.zeros((F, 3), "float32")
        for (v, idx, pclen, dep) in patch(u):
            eta_t = (D - dep) / D
            temp = 0.5 if pclen == 1 else (idx - 1.0) / (pclen - 1.0)
            eta_l = (1 - eta_t) * temp
            eta_r = (1 - eta_t) * (1 - eta_l)
            acc[:, 0] += eta_l * x[0, v - 1]
            acc[:, 1] += eta_r * x[0, v - 1]
            acc[:, 2] += eta_t * x[0, v - 1]
        ref[0, u - 1] = np.einsum("fr,frgm->gm", acc, w)
    run_case(OpCase(
        "tree_conv", {"NodesVector": x, "EdgeSet": edges, "Filter": w},
        attrs={"max_depth": D},
        ref=lambda **kw: ref, grad=["NodesVector", "Filter"],
        rtol=1e-4, atol=1e-5))


def test_var_conv_2d():
    x = R(17).randn(2, 1, 6, 6).astype("float32")
    out_ch, kh, kw = 2, 3, 3
    w = R(18).randn(out_ch, 1 * kh * kw).astype("float32")
    rows = np.array([6, 4], "int64")
    cols = np.array([6, 3], "int64")
    out = _run_program(
        "var_conv_2d",
        {"X": x, "W": w, "RowLengths": rows, "ColLengths": cols},
        {"Out": 1},
        {"OutputChannel": out_ch, "KernelH": kh, "KernelW": kw,
         "StrideH": 1, "StrideW": 1})["o_Out_0"]
    assert out.shape == (2, 2, 6, 6)
    # masked region zero
    assert np.all(out[1, :, 4:, :] == 0) and np.all(out[1, :, :, 3:] == 0)
    # interior of full-extent row matches a manual correlation loop
    ref = np.zeros((6, 6), "float32")
    for i in range(6):
        for j in range(6):
            acc = 0.0
            for di in range(3):
                for dj in range(3):
                    ii, jj = i + di - 1, j + dj - 1
                    if 0 <= ii < 6 and 0 <= jj < 6:
                        acc += x[0, 0, ii, jj] * w[0, di * 3 + dj]
            ref[i, j] = acc
    np.testing.assert_allclose(out[0, 0], ref, rtol=1e-4, atol=1e-4)


def test_pyramid_hash():
    ids = np.array([[3, 7, 9, 0], [5, 2, 0, 0]], "int64")
    lens = np.array([3, 2], "int64")
    W = R(19).randn(64, 4).astype("float32")
    out = _run_program(
        "pyramid_hash",
        {"X": ids, "W": W, "Lengths": lens}, {"Out": 1},
        {"num_emb": 8, "rand_len": 4, "pyramid_layer": 2,
         "space_len": 64})["o_Out_0"]
    assert out.shape == (2, 4, 8)
    # n-grams beyond the row's length contribute nothing
    assert np.all(out[1, 2:] == 0)
    assert np.any(out[0, 0] != 0)
    # determinism: same ids -> same embedding
    out2 = _run_program(
        "pyramid_hash",
        {"X": ids, "W": W, "Lengths": lens}, {"Out": 1},
        {"num_emb": 8, "rand_len": 4, "pyramid_layer": 2,
         "space_len": 64})["o_Out_0"]
    np.testing.assert_allclose(out, out2)


def test_filter_by_instag():
    ins = R(20).randn(4, 3).astype("float32")
    tags = np.array([[1, -1], [2, 3], [4, -1], [3, 1]], "int64")
    want = np.array([1, 3], "int64")
    out = _run_program(
        "filter_by_instag",
        {"Ins": ins, "Ins_tag": tags, "Filter_tag": want},
        {"Out": 1, "LossWeight": 1, "IndexMap": 1}, {})
    keep = np.array([True, True, False, True])
    np.testing.assert_allclose(out["o_Out_0"],
                               np.where(keep[:, None], ins, 0))
    np.testing.assert_allclose(out["o_LossWeight_0"].reshape(-1),
                               keep.astype("float32"))


def test_prroi_pool_exact_average():
    """A ROI aligned to pixel centers spanning whole pixels: the
    integral average equals the plain mean of those pixels."""
    x = R(21).randn(1, 2, 8, 8).astype("float32")
    # roi [x1,y1,x2,y2] covering pixel centers 2..5 in both axes
    rois = np.array([[2.0, 2.0, 4.0, 4.0]], "float32")
    out = _run_program(
        "prroi_pool", {"X": x, "ROIs": rois}, {"Out": 1},
        {"pooled_height": 1, "pooled_width": 1,
         "spatial_scale": 1.0})["o_Out_0"]
    # bilinear interpolant integrated over [2,4]^2: trapezoid weights
    w = np.zeros(8)
    w[2], w[3], w[4] = 0.5, 1.0, 0.5
    ref = np.einsum("h,w,chw->c", w, w, x[0]) / 4.0
    np.testing.assert_allclose(out[0, :, 0, 0], ref, rtol=1e-4,
                               atol=1e-5)


def test_prroi_pool_grad():
    x = R(22).randn(1, 1, 6, 6).astype("float32")
    rois = np.array([[0.5, 0.5, 4.5, 4.5]], "float32")
    run_case(OpCase(
        "prroi_pool", {"X": x, "ROIs": rois},
        attrs={"pooled_height": 2, "pooled_width": 2,
               "spatial_scale": 1.0},
        ref=None, grad=["X", "ROIs"], grad_rtol=8e-2, grad_atol=8e-3))


def test_correlation():
    x1 = R(23).randn(1, 3, 5, 5).astype("float32")
    x2 = R(24).randn(1, 3, 5, 5).astype("float32")
    d = 1
    ref = np.zeros((1, 9, 5, 5), "float32")
    x2p = np.pad(x2, ((0, 0), (0, 0), (1, 1), (1, 1)))
    i = 0
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            ref[:, i] = (x1 * x2p[:, :, 1 + dy:6 + dy,
                                  1 + dx:6 + dx]).mean(1)
            i += 1
    run_case(OpCase(
        "correlation", {"Input1": x1, "Input2": x2},
        attrs={"max_displacement": d, "stride2": 1},
        ref=lambda **kw: ref, grad=["Input1", "Input2"],
        rtol=1e-4, atol=1e-5))


def test_chunk_eval_iob():
    # types: PER, LOC; IOB tags: B-PER=0 I-PER=1 B-LOC=2 I-LOC=3 O=4
    inference = np.array([[0, 1, 4, 2, 4],
                          [2, 3, 3, 4, 0]], "int64")
    label = np.array([[0, 1, 4, 2, 4],
                      [2, 3, 4, 4, 0]], "int64")
    lens = np.array([5, 5], "int64")
    out = _run_program(
        "chunk_eval",
        {"Inference": inference[..., None], "Label": label[..., None],
         "Lengths": lens},
        {"Precision": 1, "Recall": 1, "F1-Score": 1,
         "NumInferChunks": 1, "NumLabelChunks": 1,
         "NumCorrectChunks": 1},
        {"num_chunk_types": 2, "chunk_scheme": "IOB"})
    # row0: chunks inf {(0,PER,0-1),(3,LOC)} lab same -> 2 correct
    # row1: inf {(0-2,LOC),(4,PER)}, lab {(0-1,LOC),(4,PER)} -> 1
    assert out["o_NumInferChunks_0"][0] == 4
    assert out["o_NumLabelChunks_0"][0] == 4
    assert out["o_NumCorrectChunks_0"][0] == 3
    np.testing.assert_allclose(out["o_Precision_0"][0], 0.75)
    np.testing.assert_allclose(out["o_Recall_0"][0], 0.75)


def test_chunk_eval_iobes_plain():
    # IOBES, 1 type: B=0 I=1 E=2 S=3, O=4
    inf = np.array([[0, 1, 2, 3, 4]], "int64")
    lab = np.array([[0, 1, 2, 4, 3]], "int64")
    lens = np.array([5], "int64")
    out = _run_program(
        "chunk_eval",
        {"Inference": inf[..., None], "Label": lab[..., None],
         "Lengths": lens},
        {"Precision": 1, "Recall": 1, "F1-Score": 1,
         "NumInferChunks": 1, "NumLabelChunks": 1,
         "NumCorrectChunks": 1},
        {"num_chunk_types": 1, "chunk_scheme": "IOBES"})
    assert out["o_NumInferChunks_0"][0] == 2
    assert out["o_NumLabelChunks_0"][0] == 2
    assert out["o_NumCorrectChunks_0"][0] == 1  # the B-I-E chunk
    # plain scheme: each maximal same-type run is a chunk
    inf_p = np.array([[0, 0, 1, 2, 2]], "int64")
    out = _run_program(
        "chunk_eval",
        {"Inference": inf_p[..., None], "Label": inf_p[..., None],
         "Lengths": lens},
        {"Precision": 1, "Recall": 1, "F1-Score": 1,
         "NumInferChunks": 1, "NumLabelChunks": 1,
         "NumCorrectChunks": 1},
        {"num_chunk_types": 3, "chunk_scheme": "plain"})
    assert out["o_NumInferChunks_0"][0] == 3
    assert out["o_NumCorrectChunks_0"][0] == 3


# ---------------------------------------------------------------------------
# quant / optimizer / dgc / fused
# ---------------------------------------------------------------------------
def test_quantize_dequantize_requantize():
    x = R(25).randn(3, 4).astype("float32")
    q = _run_program("quantize", {"Input": x}, {"Output": 1},
                     {"Scale": 32.0})["o_Output_0"]
    assert q.dtype == np.int8
    np.testing.assert_array_equal(
        q, np.clip(np.round(x * 32.0), -128, 127).astype("int8"))
    dq = _run_program("dequantize", {"Input": q}, {"Output": 1},
                      {"Scale": 32.0})["o_Output_0"]
    np.testing.assert_allclose(dq, x, atol=1.0 / 32.0 + 1e-6)
    rq = _run_program("requantize", {"Input": q}, {"Output": 1},
                      {"Scale_in": 32.0, "Scale_out": 16.0}
                      )["o_Output_0"]
    np.testing.assert_array_equal(
        rq, np.clip(np.round(q.astype("float32") / 2.0), -128,
                    127).astype("int8"))


def test_proximal_adagrad():
    p = R(26).randn(4).astype("float32")
    g = R(27).randn(4).astype("float32")
    m = np.abs(R(28).randn(4)).astype("float32")
    lr = np.array([0.1], "float32")
    l1, l2 = 0.05, 0.02
    m_new = m + g * g
    lr_eff = lr / np.sqrt(m_new)
    prox = p - lr_eff * g
    want = (np.sign(prox) * np.maximum(np.abs(prox) - lr_eff * l1, 0)
            / (1 + lr_eff * l2))
    out = _run_program(
        "proximal_adagrad",
        {"Param": p, "Grad": g, "Moment": m, "LearningRate": lr},
        {"ParamOut": 1, "MomentOut": 1}, {"l1": l1, "l2": l2})
    np.testing.assert_allclose(out["o_ParamOut_0"], want, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(out["o_MomentOut_0"], m_new, rtol=1e-5)


def test_dgc_op():
    g = R(29).randn(32).astype("float32")
    u = np.zeros(32, "float32")
    v = np.zeros(32, "float32")
    step = np.array([10.0], "float32")
    out = _run_program(
        "dgc", {"Grad": g, "U": u, "V": v, "current_step": step},
        {"U_out": 1, "V_out": 1, "EncodeGrad": 1, "Grad_out": 1},
        {"m": 0.9, "sparsity": [0.75], "rampup_begin_step": 0.0,
         "rampup_step": 1.0})
    enc = out["o_EncodeGrad_0"]
    # top-25% kept: 8 of 32 entries
    assert (enc != 0).sum() == 8
    kept = np.abs(g)[enc != 0].min()
    dropped = np.abs(g)[enc == 0].max()
    assert kept >= dropped
    # error feedback: residual + encoded == accumulated grad
    np.testing.assert_allclose(enc + out["o_V_out_0"], g, rtol=1e-5,
                               atol=1e-6)


def test_dgc_clip_by_norm():
    x = (R(30).randn(16) * 10).astype("float32")
    norm = np.linalg.norm(x)
    step = np.array([5.0], "float32")
    out = _run_program(
        "dgc_clip_by_norm", {"X": x, "current_step": step}, {"Out": 1},
        {"max_norm": 1.0, "rampup_begin_step": 10.0})["o_Out_0"]
    np.testing.assert_allclose(out, x)  # before rampup: no clipping
    out = _run_program(
        "dgc_clip_by_norm", {"X": x, "current_step": step}, {"Out": 1},
        {"max_norm": 1.0, "rampup_begin_step": 0.0})["o_Out_0"]
    np.testing.assert_allclose(out, x / norm, rtol=1e-4)


def test_multihead_matmul():
    B, S, N, H = 2, 4, 2, 3
    D = N * H
    x = R(31).randn(B, S, D).astype("float32")
    w = R(32).randn(D, 3, N, H).astype("float32")
    b = R(33).randn(3, N, H).astype("float32")
    qkv = np.einsum("bsd,dknh->kbnsh", x, w) + b.reshape(3, 1, N, 1, H)
    q, k, v = qkv
    logits = np.einsum("bnsh,bnth->bnst", q, k) / np.sqrt(H)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    ref = np.einsum("bnst,bnth->bsnh", probs, v).reshape(B, S, D)
    run_case(OpCase(
        "multihead_matmul",
        {"Input": x, "W": w.reshape(D, -1), "Bias": b},
        attrs={"head_number": N, "alpha": 1.0 / np.sqrt(H)},
        ref=lambda **kw: ref, grad=["Input"], rtol=1e-4, atol=1e-5))


def test_skip_layernorm():
    x = R(34).randn(2, 3, 6).astype("float32")
    y = R(35).randn(2, 3, 6).astype("float32")
    scale = R(36).randn(6).astype("float32")
    bias = R(37).randn(6).astype("float32")
    s = x + y
    mu = s.mean(-1, keepdims=True)
    var = s.var(-1, keepdims=True)
    ref = (s - mu) / np.sqrt(var + 1e-5) * scale + bias
    run_case(OpCase(
        "skip_layernorm", {"X": x, "Y": y, "Scale": scale,
                           "Bias": bias},
        ref=lambda **kw: ref, grad=["X", "Y"], rtol=1e-4, atol=1e-5))


def test_fused_embedding_eltwise_layernorm():
    V, Dm = 11, 6
    ids1 = np.array([[1, 2], [3, 4]], "int64")[..., None]
    ids2 = np.array([[5, 6], [7, 8]], "int64")[..., None]
    e1 = R(38).randn(V, Dm).astype("float32")
    e2 = R(39).randn(V, Dm).astype("float32")
    scale = R(40).randn(Dm).astype("float32")
    bias = R(41).randn(Dm).astype("float32")
    s = e1[ids1[..., 0]] + e2[ids2[..., 0]]
    mu = s.mean(-1, keepdims=True)
    var = s.var(-1, keepdims=True)
    ref = (s - mu) / np.sqrt(var + 1e-5) * scale + bias
    out = _run_program(
        "fused_embedding_eltwise_layernorm",
        {"Ids": [ids1, ids2], "Embs": [e1, e2], "Scale": scale,
         "Bias": bias}, {"Out": 1}, {})["o_Out_0"]
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_merge_selected_rows_dense_passthrough():
    x = R(42).randn(3, 4).astype("float32")
    out = _run_program("merge_selected_rows", {"X": x}, {"Out": 1}, {})
    np.testing.assert_allclose(out["o_Out_0"], x)
    out = _run_program("get_tensor_from_selected_rows", {"X": x},
                       {"Out": 1}, {})
    np.testing.assert_allclose(out["o_Out_0"], x)


def test_attention_lstm():
    """Loop reference of attention_lstm_op.cc:350 (padded form)."""
    B, T, M, D = 2, 4, 3, 2
    r = R(50)
    x = r.randn(B, T, M).astype("float32") * 0.5
    c0 = r.randn(B, D).astype("float32") * 0.3
    h0 = r.randn(B, D).astype("float32") * 0.3
    aw = r.randn(M + D, 1).astype("float32") * 0.5
    ab = r.randn(1).astype("float32") * 0.1
    lw = r.randn(D + M, 4 * D).astype("float32") * 0.4
    lb = r.randn(4 * D).astype("float32") * 0.1
    lens = np.array([4, 2], "int64")

    def sig(v):
        return 1 / (1 + np.exp(-v))

    hs = np.zeros((B, T, D), "float32")
    cs = np.zeros((B, T, D), "float32")
    for b in range(B):
        h, c = h0[b].copy(), c0[b].copy()
        L = int(lens[b])
        atted = x[b, :L] @ aw[:M, 0] + ab[0]
        for t in range(L):
            logit = np.maximum(atted + c @ aw[M:, 0], 0.0)
            e = np.exp(logit - logit.max())
            probs = e / e.sum()
            ctx_vec = probs @ x[b, :L]
            gates = h @ lw[:D] + ctx_vec @ lw[D:] + lb
            f, i, o = (sig(gates[:D]), sig(gates[D:2*D]),
                       sig(gates[2*D:3*D]))
            cand = np.tanh(gates[3*D:])
            c = f * c + i * cand
            h = np.tanh(c) * o
            hs[b, t], cs[b, t] = h, c
    run_case(OpCase(
        "attention_lstm",
        {"X": x, "C0": c0, "H0": h0, "AttentionWeight": aw,
         "AttentionBias": ab, "LSTMWeight": lw, "LSTMBias": lb,
         "Lengths": lens},
        outputs={"Hidden": 1, "Cell": 1},
        ref=lambda **kw: {"Hidden": hs, "Cell": cs},
        grad=["X", "LSTMWeight", "AttentionWeight"],
        rtol=1e-4, atol=1e-5))


def test_depthwise_conv2d_transpose():
    """Grouped transpose conv (groups == channels) vs a per-channel
    numpy scatter reference."""
    C, H, W, K, S = 3, 4, 4, 3, 2
    x = R(60).randn(1, C, H, W).astype("float32")
    w = R(61).randn(C, 1, K, K).astype("float32")  # IOHW, out/groups=1
    OH = (H - 1) * S + K
    OW = (W - 1) * S + K
    ref = np.zeros((1, C, OH, OW), "float32")
    for c in range(C):
        for i in range(H):
            for j in range(W):
                ref[0, c, i*S:i*S+K, j*S:j*S+K] += x[0, c, i, j] * w[c, 0]
    run_case(OpCase(
        "depthwise_conv2d_transpose", {"Input": x, "Filter": w},
        outputs={"Output": 1},
        attrs={"strides": [S, S], "paddings": [0, 0], "groups": C},
        ref=lambda **kw: ref, grad=["Input", "Filter"],
        rtol=1e-4, atol=1e-5))


def test_conv2d_transpose_stride2_shape_and_values():
    """Round-5 regression: stride-2 transpose conv with explicit pad 0
    must produce the (H-1)*s+k output the infer promises (the old
    lowering passed forward pads literally and shrank it)."""
    H, K, S = 4, 3, 2
    x = R(62).randn(1, 2, H, H).astype("float32")
    w = R(63).randn(2, 3, K, K).astype("float32")
    OH = (H - 1) * S + K
    ref = np.zeros((1, 3, OH, OH), "float32")
    for ci in range(2):
        for co in range(3):
            for i in range(H):
                for j in range(H):
                    ref[0, co, i*S:i*S+K, j*S:j*S+K] += \
                        x[0, ci, i, j] * w[ci, co]
    run_case(OpCase(
        "conv2d_transpose", {"Input": x, "Filter": w},
        outputs={"Output": 1},
        attrs={"strides": [S, S], "paddings": [0, 0], "groups": 1},
        ref=lambda **kw: ref, grad=["Input", "Filter"],
        rtol=1e-4, atol=1e-4))


def test_conv2d_transpose_output_size_attr():
    """output_size extends the default with stride slack padding."""
    H, K, S = 3, 3, 2
    x = np.ones((1, 1, H, H), "float32")
    w = np.ones((1, 1, K, K), "float32")
    out = _run_program(
        "conv2d_transpose", {"Input": x, "Filter": w}, {"Output": 1},
        {"strides": [S, S], "paddings": [0, 0], "groups": 1,
         "output_size": [8, 8]})["o_Output_0"]
    assert out.shape == (1, 1, 8, 8)
    # the extra row/col is pure zero padding at the high end
    assert np.all(out[0, 0, 7, :] == 0) and np.all(out[0, 0, :, 7] == 0)


def test_conv3d_transpose_grouped():
    """Grouped 3-D transpose conv (previously NotImplementedError) vs a
    scatter-loop reference."""
    C, Dp, K, S = 2, 3, 2, 2
    x = R(66).randn(1, C, Dp, Dp, Dp).astype("float32")
    w = R(67).randn(C, 1, K, K, K).astype("float32")  # groups=C
    OD = (Dp - 1) * S + K
    ref = np.zeros((1, C, OD, OD, OD), "float32")
    for c in range(C):
        for a in range(Dp):
            for b in range(Dp):
                for d in range(Dp):
                    ref[0, c, a*S:a*S+K, b*S:b*S+K, d*S:d*S+K] += \
                        x[0, c, a, b, d] * w[c, 0]
    run_case(OpCase(
        "conv3d_transpose", {"Input": x, "Filter": w},
        outputs={"Output": 1},
        attrs={"strides": [S]*3, "paddings": [0]*3, "groups": C},
        ref=lambda **kw: ref, grad=["Input", "Filter"],
        rtol=1e-4, atol=1e-4))


def test_bilateral_slice():
    """Loop reference of bilateral_slice_op.cu:60 (with offset)."""
    B, Cin, H, W = 1, 2, 4, 4
    D, Hg, Wg, Cout = 3, 2, 2, 2
    cs = Cin + 1
    x = R(70).rand(B, Cin, H, W).astype("float32")
    grid = R(71).randn(B, Cout * cs, D, Hg, Wg).astype("float32")
    guide = R(72).rand(B, H, W).astype("float32")
    ref = np.zeros((B, Cout, H, W), "float32")
    for b in range(B):
        for oc in range(Cout):
            for yp in range(H):
                for xp in range(W):
                    gx = (xp + 0.5) * Wg / W
                    gy = (yp + 0.5) * Hg / H
                    gz = guide[b, yp, xp] * D
                    fx = int(np.floor(gx - 0.5))
                    fy = int(np.floor(gy - 0.5))
                    fz = int(np.floor(gz - 0.5))
                    val = 0.0
                    for ic in range(cs):
                        cf = 0.0
                        for xx in range(fx, fx + 2):
                            x_ = min(max(xx, 0), Wg - 1)
                            wx = max(1 - abs(xx + 0.5 - gx), 0)
                            for yy in range(fy, fy + 2):
                                y_ = min(max(yy, 0), Hg - 1)
                                wy = max(1 - abs(yy + 0.5 - gy), 0)
                                for zz in range(fz, fz + 2):
                                    z_ = min(max(zz, 0), D - 1)
                                    dfz = zz + 0.5 - gz
                                    wz = max(1 - np.sqrt(
                                        dfz*dfz + 1e-8), 0)
                                    cf += grid[b, cs*oc+ic, z_, y_,
                                               x_] * wx * wy * wz
                        if ic < Cin:
                            val += cf * x[b, ic, yp, xp]
                        else:
                            val += cf
                    ref[b, oc, yp, xp] = val
    run_case(OpCase(
        "bilateral_slice", {"X": x, "Grid": grid, "Guide": guide},
        attrs={"has_offset": True},
        ref=lambda **kw: ref, grad=["X", "Grid"],
        rtol=1e-4, atol=1e-5))
