"""Inference predictor + export tests.

Reference analogs: inference/tests/api/analyzer_*_tester.cc (save, load
in a fresh predictor, compare against train-time outputs, Clone), and
the frozen-program export path.
"""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, optimizer
from paddle_tpu.inference import (Config, Predictor, create_predictor,
                                  load_portable)


def _train_and_save(tmpdir, steps=8):
    x = layers.data("x", [6])
    y = layers.data("y", [1])
    h = layers.fc(x, 12, act="relu", name="fc1")
    pred = layers.fc(h, 1, name="fc2")
    loss = layers.mean(pt.layers.square_error_cost(pred, y))
    optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    xs = rng.rand(16, 6).astype("float32")
    ys = xs.sum(1, keepdims=True).astype("float32") * 0.5
    for _ in range(steps):
        exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])
    pt.io.save_inference_model(tmpdir, ["x"], [pred], exe)
    # train-process reference output (test-mode clone; unpruned, so it
    # still wants the label feed)
    test_prog = pt.default_main_program().clone(for_test=True)
    ref = exe.run(test_prog, feed={"x": xs, "y": ys},
                  fetch_list=[pred.name])[0]
    return xs, np.asarray(ref)


def test_predictor_matches_train_eval(tmp_path):
    d = str(tmp_path / "model")
    xs, ref = _train_and_save(d)
    p = Predictor(d)
    assert p.get_input_names() == ["x"]
    out = p.run({"x": xs})
    np.testing.assert_allclose(out[0], ref, rtol=1e-5, atol=1e-6)
    # positional feed + repeat call hits the AOT cache
    out2 = p.run([xs])
    np.testing.assert_allclose(out2[0], ref, rtol=1e-5, atol=1e-6)
    assert len(p._cache) == 1
    # new shape -> new compile, still correct
    out3 = p.run({"x": xs[:4]})
    np.testing.assert_allclose(out3[0], ref[:4], rtol=1e-5, atol=1e-6)
    assert len(p._cache) == 2


def test_predictor_clone_shares_weights(tmp_path):
    d = str(tmp_path / "model")
    xs, ref = _train_and_save(d)
    p = Predictor(d)
    q = p.clone()
    assert q.scope is p.scope  # zero-copy shared weights
    np.testing.assert_allclose(q.run({"x": xs})[0], ref, rtol=1e-5,
                               atol=1e-6)


def test_create_predictor_config_api(tmp_path):
    d = str(tmp_path / "model")
    xs, ref = _train_and_save(d)
    cfg = Config(model_dir=d)
    cfg.disable_gpu()
    cfg.switch_ir_optim(True)
    p = create_predictor(cfg)
    np.testing.assert_allclose(p.run({"x": xs})[0], ref, rtol=1e-5,
                               atol=1e-6)


def test_stablehlo_export(tmp_path):
    d = str(tmp_path / "model")
    xs, _ref = _train_and_save(d)
    p = Predictor(d)
    mlir = p.export_stablehlo(str(tmp_path / "model.stablehlo.mlir"),
                              {"x": (16, 6)})
    assert "stablehlo" in mlir and "module" in mlir
    assert os.path.getsize(str(tmp_path / "model.stablehlo.mlir")) > 0


def test_serve_in_fresh_process(tmp_path):
    """Save here; a clean subprocess loads both the model dir (Predictor)
    and the portable artifact (load_portable) and must reproduce the
    train-process outputs."""
    d = str(tmp_path / "model")
    xs, ref = _train_and_save(d)
    p = Predictor(d)
    portable = str(tmp_path / "model.jaxport")
    p.export_portable(portable, {"x": (16, 6)})
    np.save(str(tmp_path / "x.npy"), xs)

    child = textwrap.dedent(f"""
        import os
        os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \\
            " --xla_force_host_platform_device_count=8"
        import jax; jax.config.update("jax_platforms", "cpu")
        import numpy as np
        from paddle_tpu.inference import Predictor, load_portable
        xs = np.load({str(tmp_path / 'x.npy')!r})
        out1 = Predictor({d!r}).run({{"x": xs}})[0]
        out2 = load_portable({portable!r}).run({{"x": xs}})[0]
        np.save({str(tmp_path / 'out1.npy')!r}, out1)
        np.save({str(tmp_path / 'out2.npy')!r}, out2)
        print("SERVED")
    """)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", child], env=env,
                       capture_output=True, text=True, timeout=240)
    assert "SERVED" in r.stdout, (r.stdout, r.stderr)
    np.testing.assert_allclose(np.load(str(tmp_path / "out1.npy")), ref,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.load(str(tmp_path / "out2.npy")), ref,
                               rtol=1e-5, atol=1e-6)
