"""Sharded-serving tests: mesh-partitioned ShardedPredictor +
ReplicaGroupEngine under the batching/tracing front end.

The contract is the serving bit-exactness matrix extended over
topology: a caller must not be able to tell whether their request ran
on one chip, on an mp-weight-sharded group, or on any of dp
independent replica groups — ``np.array_equal`` against a
single-device ``Predictor.run``, at every bucket boundary, on dp-only
/ mp-only / dp×mp meshes.  Per-shard health (``worker_health``,
``/healthz``/``/statusz`` ``groups`` blocks), the degradation
contract (a failing group turns ``degraded`` but neither sinks its
requests silently nor stops its siblings), missing-shard reporting,
SIGTERM drain with in-flight sharded batches, and the mesh-aware
``clone()``/``warmup()`` fix ride along.
"""
import importlib.util
import os
import signal
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import fault, layers
from paddle_tpu.inference import Predictor
from paddle_tpu.parallel import make_mesh, parse_mesh_spec
from paddle_tpu.parallel.mesh import axis_size
from paddle_tpu.serving import (OverloadedError, ReplicaGroupEngine,
                                RequestFailed, ServingEngine,
                                ShardedPredictor)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

jax = pytest.importorskip("jax")
pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="sharded serving tests need the 8-device sim (conftest "
           "forces --xla_force_host_platform_device_count=8)")


@pytest.fixture(autouse=True)
def _reset_faults():
    fault.reset()
    yield
    fault.reset()
    pt.set_flags({"FLAGS_fault_inject": "",
                  "FLAGS_serving_group_degraded_after": 3,
                  "FLAGS_serving_mesh": ""})


def _build_mlp(feat=6, hidden=16, classes=4, depth=2, seed=0):
    """Fresh in-process MLP predictor (own program + scope).  Every
    weight's last dim is mp=2-divisible — the megatron divisibility
    rule the bit-exact contract assumes (an indivisible weight
    replicates, and contracting a still-sharded activation against it
    lets GSPMD partial-sum across devices, drifting low-order bits)."""
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    startup.random_seed = main.random_seed = seed
    with pt.program_guard(main, startup):
        x = layers.data("x", [feat])
        h = x
        for i in range(depth):
            h = layers.fc(h, hidden, act="relu", name=f"sh_fc{i}_{seed}")
        out = layers.fc(h, classes, name=f"sh_head_{seed}")
    scope = pt.Scope()
    pt.Executor().run(startup, scope=scope)
    return Predictor(main, ["x"], [out], scope=scope)


@pytest.fixture(scope="module")
def small_model():
    p = _build_mlp()
    rng = np.random.RandomState(0)
    xs = rng.rand(64, 6).astype("float32")
    return p, xs


# ---------------------------------------------------------------------------
# mesh-spec parsing (the FLAGS_serving_mesh / --mesh surface)
# ---------------------------------------------------------------------------

def test_parse_mesh_spec_forms():
    assert parse_mesh_spec("dp=4,mp=2") == {"dp": 4, "mp": 2}
    assert parse_mesh_spec("dp4,mp2") == {"dp": 4, "mp": 2}
    assert parse_mesh_spec(" dp=2 , ep=4 ") == {"dp": 2, "ep": 4}
    assert parse_mesh_spec("") == {}
    with pytest.raises(ValueError, match="unknown mesh axis"):
        parse_mesh_spec("xx=2")
    with pytest.raises(ValueError, match=">= 1"):
        parse_mesh_spec("dp=0")
    with pytest.raises(ValueError, match="bad mesh spec"):
        parse_mesh_spec("dp")


def test_axis_size():
    mesh = make_mesh({"dp": 2, "mp": 2}, devices=jax.devices()[:4])
    assert axis_size(mesh, "dp") == 2
    assert axis_size(mesh, "dp", "mp") == 4
    assert axis_size(mesh, "ep") == 1


# ---------------------------------------------------------------------------
# bit-exactness: dp-only / mp-only / dp x mp, at every bucket boundary
# ---------------------------------------------------------------------------

TOPOLOGIES = [
    pytest.param(dict(groups=4, mp=1), id="dp-only"),
    pytest.param(dict(groups=1, mp=2), id="mp-only"),
    pytest.param(dict(groups=2, mp=2), id="dpxmp"),
]


@pytest.mark.parametrize("topo", TOPOLOGIES)
def test_replica_groups_bit_exact_across_buckets(small_model, topo):
    """Engine outputs np.array_equal to single-device Predictor.run at
    sizes 1 / b-1 / b / b+1 (b+1 exercises the chunked oversize path
    riding the sharded pool)."""
    p, xs = small_model
    b = 4
    with ReplicaGroupEngine(p, max_batch=b, max_delay_ms=1.0,
                            deadline_ms=60000, **topo) as eng:
        for size in (1, b - 1, b, b + 1):
            ref = p.run({"x": xs[:size]})[0]
            got = eng.predict({"x": xs[:size]})[0]
            assert np.array_equal(ref, got), \
                f"{topo}: size {size} not bit-exact"


@pytest.mark.parametrize("topo", TOPOLOGIES)
def test_concurrent_single_rows_bit_exact(small_model, topo):
    """Concurrent 1-row submitters get batched across replica groups;
    every caller still reads exactly the single-device answer."""
    p, xs = small_model
    ref = p.run({"x": xs[:16]})[0]
    with ReplicaGroupEngine(p, max_batch=4, max_delay_ms=2.0,
                            deadline_ms=60000, **topo) as eng:
        futs = [eng.submit({"x": xs[i:i + 1]}) for i in range(16)]
        for i, f in enumerate(futs):
            assert np.array_equal(f.result(60)[0], ref[i:i + 1])


def test_sharded_predictor_run_matches_plain(small_model):
    """ShardedPredictor.run (no engine) is bit-exact vs the plain
    Predictor for every bucket size, including the GEMM-padded 1-row
    path on a weight-sharded mesh."""
    p, xs = small_model
    sp = ShardedPredictor(p.program, p.feed_names, p.fetch_names,
                          scope=p.scope,
                          mesh=make_mesh({"mp": 2},
                                         devices=jax.devices()[:2]))
    for size in (1, 3, 4, 8):
        ref = p.run({"x": xs[:size]})[0]
        assert np.array_equal(ref, sp.run({"x": xs[:size]})[0])


# ---------------------------------------------------------------------------
# predictor contract: clone / warmup / cache_info / placement
# ---------------------------------------------------------------------------

def test_mesh_aware_clone_shares_executables(small_model):
    p, xs = small_model
    sp = ShardedPredictor(p.program, p.feed_names, p.fetch_names,
                          scope=p.scope,
                          mesh=make_mesh({"mp": 2},
                                         devices=jax.devices()[:2]))
    sp.run({"x": xs[:4]})
    c = sp.clone()
    assert type(c) is ShardedPredictor
    assert c.mesh is sp.mesh
    assert c._cache is sp._cache          # shared sharded executables
    assert c.scope is sp.scope            # shared placed weight shards
    assert np.array_equal(c.run({"x": xs[:4]})[0],
                          p.run({"x": xs[:4]})[0])


def test_mesh_aware_warmup_primes_executed_buckets(small_model):
    """warmup() on a weight-sharded mesh must prime the executable
    1-row requests actually hit (the GEMM-padded 2-row form), so the
    first real request compiles nothing."""
    p, xs = small_model
    sp = ShardedPredictor(p.program, p.feed_names, p.fetch_names,
                          scope=p.scope,
                          mesh=make_mesh({"mp": 2},
                                         devices=jax.devices()[:2]))
    compiled = sp.warmup([{"x": (1, 6)}, {"x": (4, 6)}])
    assert compiled == 2
    n_before = len(sp.cache_info()["signatures"])
    sp.run({"x": xs[:1]})
    sp.run({"x": xs[:4]})
    assert len(sp.cache_info()["signatures"]) == n_before


def test_cache_info_names_the_mesh(small_model):
    p, xs = small_model
    sp = ShardedPredictor(p.program, p.feed_names, p.fetch_names,
                          scope=p.scope,
                          mesh=make_mesh({"mp": 2},
                                         devices=jax.devices()[:2]))
    sp.run({"x": xs[:2]})
    info = sp.cache_info()
    assert info["mesh"] == "mp=2"
    assert info["devices"] == [0, 1]
    assert info["signatures"]  # XLA manifests still attached


def test_placement_reports_missing_shards(small_model):
    p, xs = small_model
    sp = ShardedPredictor(p.program, p.feed_names, p.fetch_names,
                          scope=p.scope,
                          mesh=make_mesh({"mp": 2},
                                         devices=jax.devices()[:2]))
    assert sp.placement()["missing_shards"] == []
    assert sp.placement(live_ids={0})["missing_shards"] == [1]


def test_plain_predictor_clone_still_plain(small_model):
    """The mesh-aware clone() must not change the base contract: a
    plain Predictor's clone is a plain Predictor sharing scope."""
    p, xs = small_model
    c = p.clone()
    assert type(c) is Predictor
    assert c.scope is p.scope
    assert np.array_equal(c.run({"x": xs[:2]})[0],
                          p.run({"x": xs[:2]})[0])


# ---------------------------------------------------------------------------
# per-shard health: worker_health / healthz / statusz
# ---------------------------------------------------------------------------

def test_per_shard_health_fields(small_model):
    p, xs = small_model
    with ReplicaGroupEngine(p, groups=2, mp=2, max_batch=4,
                            max_delay_ms=1.0,
                            deadline_ms=60000) as eng:
        for i in range(8):
            eng.predict({"x": xs[i:i + 1]})
        health = eng.worker_health()
        assert len(health) == 2
        for g in health:
            for field in ("worker", "batches", "failures",
                          "consecutive_failures", "degraded",
                          "in_flight_rows", "rows_total", "last_batch",
                          "predict_ms", "avg_batch_rows", "mesh",
                          "devices", "missing_shards", "status"):
                assert field in g, f"worker_health missing {field!r}"
            assert g["status"] == "ok"
            assert g["mesh"] == "mp=2"
            assert len(g["devices"]) == 2
        assert health[0]["devices"] != health[1]["devices"]  # disjoint
        # at least one group served something, and the totals add up
        assert sum(g["batches"] for g in health) >= 1
        assert sum(g["rows_total"] for g in health) == 8
        # /healthz and /statusz carry the same per-group block
        hz = eng.health()
        assert hz["status"] == "ok"
        assert [g["status"] for g in hz["groups"]] == ["ok", "ok"]
        sz = eng.introspect()
        assert len(sz["groups"]) == 2
        assert sz["replica_groups"] == {"groups": 2,
                                        "group_axes": {"mp": 2, "ep": 1},
                                        "devices_per_group": 2}
        # executables inventory names which shard set each runs on
        assert all("mesh" in e for e in sz["executables"])


def test_missing_shards_flips_group_and_healthz(small_model):
    """A group whose mesh devices vanish from the live set reports
    missing_shards; /healthz degrades while siblings stay ok."""
    p, xs = small_model
    with ReplicaGroupEngine(p, groups=2, mp=1, max_batch=4,
                            max_delay_ms=1.0,
                            deadline_ms=60000) as eng:
        eng.predict({"x": xs[:2]})
        victim = eng._pool[1]
        orig = victim.placement
        victim.placement = lambda live_ids=None: orig(
            live_ids={d for d in range(8) if d not in
                      victim.device_ids()})
        try:
            health = eng.worker_health()
            assert health[0]["status"] == "ok"
            assert health[1]["status"] == "missing_shards"
            assert health[1]["missing_shards"] == victim.device_ids()
            assert eng.health()["status"] == "degraded"
        finally:
            victim.placement = orig
        assert eng.health()["status"] == "ok"


# ---------------------------------------------------------------------------
# degradation contract: one poisoned group, siblings keep serving
# ---------------------------------------------------------------------------

def test_serve_batch_fail_isolated_to_one_group(small_model):
    """serve_batch:fail@1 with degraded_after=1: the one group that
    picked the poisoned batch turns degraded (visible in /healthz),
    its requests get a real error, every other group keeps serving
    bit-exact answers — and one later success clears the streak."""
    from paddle_tpu.monitor import stat_get

    p, xs = small_model
    pt.set_flags({"FLAGS_serving_group_degraded_after": 1})
    fault.configure("serve_batch:fail@1")
    fails_before = stat_get("serving_batch_failures")
    ref = p.run({"x": xs[:4]})[0]
    with ReplicaGroupEngine(p, groups=4, mp=1, max_batch=4,
                            max_delay_ms=1.0,
                            deadline_ms=60000) as eng:
        first = eng.submit({"x": xs[:4]})
        with pytest.raises(RequestFailed, match="injected"):
            first.result(60)
        health = eng.worker_health()
        degraded = [g for g in health if g["status"] == "degraded"]
        assert len(degraded) == 1, \
            "exactly the group that ran the poisoned batch degrades"
        assert degraded[0]["consecutive_failures"] == 1
        assert eng.health()["status"] == "degraded"
        assert eng.stats()["groups_degraded"] == 1
        # the other three groups never saw a failure
        assert all(g["failures"] == 0 for g in health
                   if g["worker"] != degraded[0]["worker"])
        # siblings (and, eventually, the degraded group itself) keep
        # serving: every follow-up request completes bit-exact
        futs = [eng.submit({"x": xs[:4]}) for _ in range(8)]
        for f in futs:
            assert np.array_equal(f.result(60)[0], ref)
        # success on the degraded group resets its streak; drive
        # traffic until every group served at least one ok batch
        deadline = time.monotonic() + 30
        while eng.stats()["groups_degraded"]:
            assert time.monotonic() < deadline, \
                "degraded flag never cleared"
            eng.predict({"x": xs[:4]})
    assert stat_get("serving_batch_failures") == fails_before + 1


# ---------------------------------------------------------------------------
# SIGTERM drain with in-flight sharded batches
# ---------------------------------------------------------------------------

def test_sigterm_drains_sharded_batches_then_rejects(small_model):
    p, xs = small_model
    eng = ReplicaGroupEngine(p, groups=2, mp=2, max_batch=4,
                             max_delay_ms=2.0, deadline_ms=60000)
    eng.install_sigterm()
    try:
        futs = [eng.submit({"x": xs[i:i + 1]}) for i in range(12)]
        os.kill(os.getpid(), signal.SIGTERM)
        ref = p.run({"x": xs[:12]})[0]
        # every in-flight sharded batch completes with a real answer
        for i, f in enumerate(futs):
            assert np.array_equal(f.result(60)[0], ref[i:i + 1])
        deadline = time.monotonic() + 30
        while any(t.is_alive() for t in eng._threads):
            assert time.monotonic() < deadline, "drain did not finish"
            time.sleep(0.01)
        with pytest.raises(OverloadedError, match="draining"):
            eng.submit({"x": xs[:1]})
    finally:
        eng.close()
        signal.signal(signal.SIGTERM, signal.SIG_DFL)


# ---------------------------------------------------------------------------
# topology resolution (flags / spec / kwargs) + guardrails
# ---------------------------------------------------------------------------

def test_topology_from_flag_and_spec(small_model):
    p, xs = small_model
    pt.set_flags({"FLAGS_serving_mesh": "dp=2,mp=2"})
    with ReplicaGroupEngine(p, max_batch=4, max_delay_ms=1.0,
                            deadline_ms=60000) as eng:
        assert eng.replica_groups == 2
        assert eng.group_axes == {"mp": 2, "ep": 1}
    # an explicit mesh_spec wins over the flag
    with ReplicaGroupEngine(p, mesh_spec="dp=4", max_batch=4,
                            max_delay_ms=1.0, deadline_ms=60000) as eng:
        assert eng.replica_groups == 4
        assert eng.group_axes == {"mp": 1, "ep": 1}


def test_topology_guardrails(small_model):
    p, _ = small_model
    with pytest.raises(ValueError, match="needs"):
        ReplicaGroupEngine(p, groups=8, mp=2)   # 16 devices on an 8-sim
    # a training topology string must not silently serve on a
    # fraction of the devices
    with pytest.raises(ValueError, match="does not serve over"):
        ReplicaGroupEngine(p, mesh_spec="dp=2,pp=4")
    # a malformed flag must not break a fully-kwarg'd constructor
    pt.set_flags({"FLAGS_serving_mesh": "dp=garbage"})
    with ReplicaGroupEngine(p, groups=2, mp=1, ep=1, max_batch=4,
                            max_delay_ms=1.0, deadline_ms=60000) as eng:
        assert eng.replica_groups == 2
    pt.set_flags({"FLAGS_serving_mesh": ""})
    sp = ShardedPredictor(p.program, p.feed_names, p.fetch_names,
                          scope=p.scope,
                          mesh=make_mesh({"mp": 2},
                                         devices=jax.devices()[:2]))
    with pytest.raises(ValueError, match="unplaced"):
        ReplicaGroupEngine(sp, groups=2)
    with pytest.raises(ValueError):
        ShardedPredictor(p.program, p.feed_names, p.fetch_names,
                         scope=p.scope)         # no mesh


# ---------------------------------------------------------------------------
# mesh-partitioned generation (Llama decode over mp kv-heads)
# ---------------------------------------------------------------------------

def test_generation_mesh_partitioned_bit_exact():
    """A GenerationEngine on an mp=2 mesh (weights sharded, per-slot
    KV caches sharded over kv-heads) emits the SAME token streams as
    the single-device engine with the same seed."""
    from paddle_tpu.serving import GenerationEngine

    model = dict(vocab_size=64, hidden=32, num_layers=2, num_heads=4,
                 num_kv_heads=4, intermediate=64)
    prompts = [np.arange(3, 9, dtype="int64"),
               np.arange(5, 9, dtype="int64")]

    def run(mesh, scope=None):
        eng = GenerationEngine(model, num_slots=2, max_seq_len=32,
                               max_new_tokens=8, seed=7, mesh=mesh,
                               scope=scope, deadline_ms=60000)
        try:
            return ([eng.generate(q, 8)["tokens"] for q in prompts],
                    eng.stats(), eng.scope)
        finally:
            eng.close()

    # the meshed engine SHARES the reference engine's scope (the
    # documented zero-copy handoff): same weights, so any token
    # divergence is the mesh partitioning — not the global op-seed
    # advancing between two in-process builds
    ref_tokens, _, scope = run(None)
    mesh = make_mesh({"mp": 2}, devices=jax.devices()[:2])
    got_tokens, stats, _ = run(mesh, scope=scope)
    assert got_tokens == ref_tokens
    assert stats["mesh"] == "mp=2"
    assert stats["kv_shard_axis"] == "mp"


# ---------------------------------------------------------------------------
# loadgen --sharded SLO contract
# ---------------------------------------------------------------------------

def _load_loadgen():
    path = os.path.join(REPO, "tools", "serving_loadgen.py")
    spec = importlib.util.spec_from_file_location("serving_loadgen",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_loadgen_slo_fails_on_degraded_group():
    lg = _load_loadgen()
    rep = {"mode": "closed", "latency_ms": {"p99": 5.0},
           "shed_rate": 0.0,
           "groups": [{"worker": 0, "status": "ok"},
                      {"worker": 1, "status": "degraded",
                       "mesh": "mp=2", "devices": [2, 3]}]}
    slo = lg.check_slo(rep, fail_degraded=True)
    assert not slo["ok"]
    assert any("degraded" in v for v in slo["violations"])
    # same contract against an embedded live-server /statusz block
    # (the real endpoint nests the groups under "engine")
    rep2 = {"mode": "closed", "latency_ms": {"p99": 5.0},
            "statusz": {"engine": {"groups": [
                {"worker": 0, "status": "missing_shards"}]}}}
    slo2 = lg.check_slo(rep2, fail_degraded=True)
    assert not slo2["ok"]
    # and a healthy report passes
    assert lg.check_slo(rep2 | {"statusz": {"engine": {"groups": [
        {"worker": 0, "status": "ok"}]}}}, fail_degraded=True)["ok"]
