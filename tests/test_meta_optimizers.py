"""Meta-optimizer tests.

Reference analogs: test_fleet_{amp,dgc,lamb,lars,localsgd,gradient_merge,
recompute,sharding}_meta_optimizer.py — single-process: build strategy,
minimize, assert on the rewritten program — plus numeric checks our
compiled-execution model makes cheap.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, optimizer
from paddle_tpu.distributed import fleet


def _net(n_in=8, n_hidden=16, n_out=4, batch=16):
    x = layers.data("x", [batch, n_in], append_batch_size=False)
    y = layers.data("y", [batch, 1], dtype="int64", append_batch_size=False)
    h = layers.fc(x, n_hidden, act="relu")
    logits = layers.fc(h, n_out)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
    return loss, h


def _minimize_with(strategy, opt):
    fleet.init(is_collective=True)
    main, startup = pt.default_main_program(), pt.default_startup_program()
    with pt.program_guard(main, startup):
        loss, h = _net()
        strategy_obj = strategy(h) if callable(strategy) else strategy
        fopt = fleet.distributed_optimizer(opt, strategy_obj)
        fopt.minimize(loss)
    return main, startup, loss


def _optypes(program):
    types = []

    def walk(blk):
        for op in blk.ops:
            types.append(op.type)
            for k in ("sub_block", "true_block", "false_block"):
                idx = op.attr(k, None)
                if idx is not None:
                    walk(program.block(idx))
    walk(program.global_block())
    return types


def test_amp_meta_optimizer():
    s = fleet.DistributedStrategy()
    s.amp = True
    main, _, _ = _minimize_with(s, optimizer.AdamOptimizer(1e-3))
    assert main._amp_lowering is not None
    assert main._amp_lowering["dtype"] == "bfloat16"
    assert "AMPOptimizer" in fleet.fleet_instance()._applied_meta_optimizers


def test_recompute_meta_optimizer():
    s_fn_calls = {}

    def strat(h):
        s = fleet.DistributedStrategy()
        s.recompute = True
        s.recompute_configs = {"checkpoints": [h.name]}
        return s
    main, _, _ = _minimize_with(strat, optimizer.AdamOptimizer(1e-3))
    types = _optypes(main)
    # recomputed forward ops appear again in backward region
    assert types.count("mul") >= 3  # 2 forward + >=1 recomputed
    assert "RecomputeOptimizer" in \
        fleet.fleet_instance()._applied_meta_optimizers


def test_gradient_merge_meta_optimizer():
    s = fleet.DistributedStrategy()
    s.gradient_merge = True
    s.gradient_merge_configs = {"k_steps": 4, "avg": True}
    main, _, _ = _minimize_with(s, optimizer.SGDOptimizer(0.1))
    types = _optypes(main)
    assert "conditional_block" in types
    assert "sgd" in types  # inside the conditional block


def test_localsgd_meta_optimizer():
    s = fleet.DistributedStrategy()
    s.localsgd = True
    s.localsgd_configs = {"k_steps": 2}
    main, _, _ = _minimize_with(s, optimizer.SGDOptimizer(0.1))
    types = _optypes(main)
    assert "conditional_block" in types
    assert "c_allreduce_sum" in types
    # no per-step grad allreduce outside the sync block
    top_types = [op.type for op in main.global_block().ops]
    assert "c_allreduce_sum" not in top_types


def test_dgc_meta_optimizer():
    s = fleet.DistributedStrategy()
    s.dgc = True
    main, _, _ = _minimize_with(s, optimizer.MomentumOptimizer(0.1, 0.9))
    types = _optypes(main)
    assert "dgc_momentum" in types
    assert "momentum" not in types


def test_sharding_meta_optimizer():
    s = fleet.DistributedStrategy()
    s.sharding = True
    main, _, _ = _minimize_with(s, optimizer.AdamOptimizer(1e-3))
    assert getattr(main, "_zero_sharding", None) is not None
    # placement-based: no collective rewrite
    assert "c_allreduce_sum" not in _optypes(main)


def test_fp16_allreduce_meta():
    s = fleet.DistributedStrategy()
    s.fp16_allreduce = True
    main, _, _ = _minimize_with(s, optimizer.SGDOptimizer(0.1))
    types = [op.type for op in main.global_block().ops]
    assert "cast" in types and "c_allreduce_sum" in types


def test_gradient_merge_numeric():
    """k=4 merge: no update for 3 steps, exact averaged update at step 4."""
    from paddle_tpu.framework.initializer import NumpyArrayInitializer
    rng = np.random.RandomState(0)
    xv = rng.rand(8, 4).astype("float32")
    w0 = rng.rand(4, 1).astype("float32")

    main, startup = pt.default_main_program(), pt.default_startup_program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [8, 4], append_batch_size=False)
        pred = layers.fc(x, 1, param_attr=pt.ParamAttr(
            initializer=NumpyArrayInitializer(w0)), bias_attr=False)
        loss = layers.mean(pred)
        opt = optimizer.GradientMergeOptimizer(
            optimizer.SGDOptimizer(0.1), k_steps=4, avg=True)
        opt.minimize(loss)
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    wname = main.global_block().all_parameters()[0].name
    w_before = np.asarray(scope.find_var(wname)).copy()
    for _ in range(3):
        exe.run(main, feed={"x": xv}, fetch_list=[loss], scope=scope)
    np.testing.assert_allclose(np.asarray(scope.find_var(wname)), w_before)
    exe.run(main, feed={"x": xv}, fetch_list=[loss], scope=scope)
    expected = w_before - 0.1 * xv.mean(0, keepdims=True).T
    np.testing.assert_allclose(np.asarray(scope.find_var(wname)), expected,
                               atol=1e-6)


def test_amp_static_trains_bf16():
    from paddle_tpu.contrib.mixed_precision import decorate
    main, startup = pt.default_main_program(), pt.default_startup_program()
    with pt.program_guard(main, startup):
        loss, _ = _net()
        opt = decorate(optimizer.AdamOptimizer(1e-2))
        opt.minimize(loss)
    exe = pt.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(16, 8).astype("float32"),
            "y": rng.randint(0, 4, (16, 1)).astype("int64")}
    l0 = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
    for _ in range(15):
        l = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
    assert l < l0


def test_fp16_loss_scaling_recovers_from_inf():
    """Force an inf gradient via a huge loss scale: step is skipped
    (params unchanged) and the scale halves after decr_every_n=1."""
    from paddle_tpu.contrib.mixed_precision import decorate
    main, startup = pt.default_main_program(), pt.default_startup_program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4, 4], append_batch_size=False)
        pred = layers.fc(x, 1, bias_attr=False)
        loss = layers.mean(pred)
        opt = decorate(optimizer.SGDOptimizer(0.1), dtype="float16",
                       init_loss_scaling=1e38, decr_every_n_nan_or_inf=1,
                       use_dynamic_loss_scaling=True)
        opt.minimize(loss)
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    wname = main.global_block().all_parameters()[0].name
    w0 = np.asarray(scope.find_var(wname)).copy()
    exe.run(main, feed={"x": np.ones((4, 4), "float32")},
            fetch_list=[loss], scope=scope)
    w1 = np.asarray(scope.find_var(wname))
    np.testing.assert_allclose(w0, w1)  # inf step skipped
    scale = float(np.asarray(scope.find_var("loss_scaling_0")))
    assert scale < 1e38


def test_recompute_matches_plain_training():
    from paddle_tpu.ops.registry import reset_op_seed
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(16, 8).astype("float32"),
            "y": rng.randint(0, 4, (16, 1)).astype("int64")}
    results = []
    for use_rc in (False, True):
        reset_op_seed()
        main, startup = pt.Program(), pt.Program()
        startup._is_startup = True
        with pt.program_guard(main, startup):
            loss, h = _net()
            if use_rc:
                opt = optimizer.RecomputeOptimizer(
                    optimizer.AdamOptimizer(1e-2))
                opt._set_checkpoints([h])
            else:
                opt = optimizer.AdamOptimizer(1e-2)
            opt.minimize(loss)
        exe = pt.Executor()
        scope = pt.Scope()
        exe.run(startup, scope=scope)
        results.append([float(exe.run(main, feed=feed, fetch_list=[loss],
                                      scope=scope)[0]) for _ in range(5)])
    np.testing.assert_allclose(results[0], results[1], rtol=1e-5)


def test_dgc_trains():
    main, startup = pt.default_main_program(), pt.default_startup_program()
    with pt.program_guard(main, startup):
        loss, _ = _net()
        opt = optimizer.DGCMomentumOptimizer(
            0.1, 0.9, rampup_begin_step=2, sparsity=[0.9])
        opt.minimize(loss)
    exe = pt.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(16, 8).astype("float32"),
            "y": rng.randint(0, 4, (16, 1)).astype("int64")}
    l0 = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
    for _ in range(20):
        l = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
    assert l < l0


def test_zero_sharding_runs_on_mesh():
    fleet.init(is_collective=True)
    main, startup = pt.default_main_program(), pt.default_startup_program()
    with pt.program_guard(main, startup):
        loss, _ = _net()
        s = fleet.DistributedStrategy()
        s.sharding = True
        fopt = fleet.distributed_optimizer(optimizer.AdamOptimizer(1e-2), s)
        fopt.minimize(loss)
    exe = pt.Executor()
    exe.run(startup)
    compiled = pt.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(16, 8).astype("float32"),
            "y": rng.randint(0, 4, (16, 1)).astype("int64")}
    l0 = exe.run(compiled, feed=feed, fetch_list=[loss])[0]
    for _ in range(8):
        l = exe.run(compiled, feed=feed, fetch_list=[loss])[0]
    assert "gspmd" in compiled._compiled
    assert float(np.mean(l)) < float(np.mean(l0))
