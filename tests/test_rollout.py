"""Safe weight rollout tests: in-place hot-swap discipline at every
layer (Predictor -> ServingEngine -> HTTP /swap -> fleet) plus the
router's canary traffic-shift with burn-rate auto-revert.

The load-bearing contracts:

* **Validated before applied** — structural drift (shape/dtype/missing
  name) raises :class:`SwapMismatch` with NOTHING flipped; the old
  weights keep serving bit-exactly.
* **Atomic or rolled back** — a commit failure mid-swap (the
  ``weight_swap`` fault site) restores every already-flipped array; a
  torn mix of versions is never observable.
* **Zero recompiles** — the compiled executables outlive the weights:
  the predictor's signature cache must not grow across a swap.
* **Version honesty** — every data-plane HTTP reply names the weights
  version that answered it (``X-PaddleTPU-Weights-Version``), bumped
  only on successful swap/revert.
* **Warming replicas shed** — a replica gated on warmup refuses
  data-plane POSTs with an explicit 503 until warmup finishes (an
  early request would race the warmup pass on donated buffers).
* **Canary verdicts** — a NaN-poisoned checkpoint on the canary
  minority burns the short-window SLO judge and auto-reverts; a clean
  checkpoint soaks and promotes fleet-wide.
"""
import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import fault, layers
from paddle_tpu.flags import set_flags
from paddle_tpu.framework.core import reset_unique_name
from paddle_tpu.inference import Predictor, SwapMismatch
from paddle_tpu.serving import GenerationEngine, ServingEngine, serve
from paddle_tpu.serving.replica import build_synthetic_checkpoint
from paddle_tpu.serving.router import Router, RouterServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIMS = dict(feat=8, hidden=16, depth=1, classes=4)
VERSION_HEADER = "X-PaddleTPU-Weights-Version"


def _build_replica_predictor(seed=0):
    """A predictor structurally identical to the synthetic-MLP replica
    (``rep_fc0``/``rep_head`` parameter names), so checkpoints minted
    by :func:`build_synthetic_checkpoint` swap onto it."""
    reset_unique_name()
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    startup.random_seed = main.random_seed = seed
    with pt.program_guard(main, startup):
        x = layers.data("x", [DIMS["feat"]])
        h = layers.fc(x, DIMS["hidden"], act="relu", name="rep_fc0")
        out = layers.fc(h, DIMS["classes"], name="rep_head")
    scope = pt.Scope()
    pt.Executor().run(startup, scope=scope)
    return Predictor(main, ["x"], [out], scope=scope)


def _ckpt(tmp_path, name, seed, poison_nan=False, **overrides):
    d = str(tmp_path / name)
    build_synthetic_checkpoint(d, seed=seed, poison_nan=poison_nan,
                               **{**DIMS, **overrides})
    return d


def _probe():
    return np.linspace(-1.0, 1.0, DIMS["feat"],
                       dtype="float32").reshape(1, DIMS["feat"])


def _mlp_reference(params, x):
    """Numpy forward of the rep MLP from raw checkpoint arrays."""
    h = np.maximum(x @ params["rep_fc0.w_0"] + params["rep_fc0.w_1"],
                   0.0)
    return h @ params["rep_head.w_0"] + params["rep_head.w_1"]


def _post(url, doc, timeout=30.0):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read()), dict(r.headers)


# ---------------------------------------------------------------------------
# Predictor layer: validate -> commit-or-rollback -> revert
# ---------------------------------------------------------------------------

def test_predictor_swap_bit_exact_no_recompile(tmp_path):
    pred = _build_replica_predictor(seed=0)
    x = _probe()
    before = pred.run({"x": x})[0]

    ck = _ckpt(tmp_path, "ck_v2", seed=2)
    from paddle_tpu import io
    params = io._read(os.path.join(ck, "__params__"))
    expected = _mlp_reference(params, x)
    assert not np.array_equal(before, expected), \
        "seed 2 checkpoint must actually change the function"

    cached_sigs = set(pred._cache)
    res = pred.swap_weights(ck)
    assert res["replaced"] == len(params)
    after = pred.run({"x": x})[0]
    np.testing.assert_array_equal(after, expected.astype(after.dtype))
    # the executables outlived the weights: same signature cache, no
    # recompile for the already-warm shape
    assert set(pred._cache) == cached_sigs

    # single-level revert restores the original arrays bit-exactly;
    # a revert is itself a swap, so reverting AGAIN toggles back to
    # the checkpoint (the retained level is always "what I replaced")
    pred.revert_weights()
    np.testing.assert_array_equal(pred.run({"x": x})[0], before)
    pred.revert_weights()
    np.testing.assert_array_equal(pred.run({"x": x})[0], after)


def test_predictor_swap_mismatch_applies_nothing(tmp_path):
    pred = _build_replica_predictor(seed=0)
    x = _probe()
    before = pred.run({"x": x})[0]
    bad = _ckpt(tmp_path, "ck_wide", seed=3, hidden=32)
    with pytest.raises(SwapMismatch) as e:
        pred.swap_weights(bad)
    assert "shape" in str(e.value)
    np.testing.assert_array_equal(pred.run({"x": x})[0], before)
    with pytest.raises(SwapMismatch):
        pred.swap_weights(str(tmp_path / "nonexistent"))


def test_predictor_swap_fault_rolls_back(tmp_path):
    pred = _build_replica_predictor(seed=0)
    x = _probe()
    before = pred.run({"x": x})[0]
    ck = _ckpt(tmp_path, "ck_v2", seed=2)
    fault.configure("weight_swap:fail@2")
    try:
        with pytest.raises(fault.InjectedFault):
            pred.swap_weights(ck)  # dies after flipping one array
    finally:
        fault.configure("")
    # rollback restored the flipped array: still the OLD function,
    # never a torn mix of versions
    np.testing.assert_array_equal(pred.run({"x": x})[0], before)
    with pytest.raises(SwapMismatch):
        pred.revert_weights()  # a failed swap retains nothing


# ---------------------------------------------------------------------------
# Engine + HTTP: /swap taxonomy, version header, warming shed
# ---------------------------------------------------------------------------

def test_http_swap_versions_and_refusals(tmp_path):
    eng = ServingEngine(_build_replica_predictor(seed=0), workers=1,
                        max_batch=2, max_delay_ms=1.0,
                        deadline_ms=60000.0)
    srv = serve(eng, port=0)
    try:
        x = _probe()
        code, doc, hdr = _post(srv.url + "/predict",
                               {"inputs": {"x": x.tolist()}})
        assert code == 200 and hdr[VERSION_HEADER] == "1"
        before = doc["outputs"][0]

        # structural drift -> 409, nothing flipped
        bad = _ckpt(tmp_path, "ck_wide", seed=3, hidden=32)
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url + "/swap", {"dir": bad})
        assert e.value.code == 409
        assert json.loads(e.value.read())["error"] == "swap_mismatch"
        assert eng.weights_version == 1

        # clean swap -> 200, version bump, header flips, bit-exact
        ck = _ckpt(tmp_path, "ck_v2", seed=2)
        code, doc, _ = _post(srv.url + "/swap", {"dir": ck})
        assert code == 200 and doc["weights_version"] == 2
        assert doc["swap_ms"] >= 0
        from paddle_tpu import io
        params = io._read(os.path.join(ck, "__params__"))
        code, doc, hdr = _post(srv.url + "/predict",
                               {"inputs": {"x": x.tolist()}})
        assert code == 200 and hdr[VERSION_HEADER] == "2"
        np.testing.assert_allclose(np.asarray(doc["outputs"][0]),
                                   _mlp_reference(params, x),
                                   rtol=0, atol=0)
        assert doc["outputs"][0] != before

        # /swap revert -> 200, version bumps again (versions are
        # monotonic per replica: a revert is a NEW rollout decision)
        code, doc, _ = _post(srv.url + "/swap", {"revert": True})
        assert code == 200 and doc["weights_version"] == 3
        code, doc, hdr = _post(srv.url + "/predict",
                               {"inputs": {"x": x.tolist()}})
        assert hdr[VERSION_HEADER] == "3"
        assert doc["outputs"][0] == before

        # draining -> 503 overloaded (old weights keep serving)
        with eng._cv:
            eng._draining = True
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(srv.url + "/swap", {"dir": ck})
            assert e.value.code == 503
            assert json.loads(e.value.read())["error"] == "overloaded"
        finally:
            with eng._cv:
                eng._draining = False
        assert eng.weights_version == 3
    finally:
        srv.close()
        eng.close()


def test_engine_swap_under_load_never_torn(tmp_path):
    """Swap while requests stream through: every answer must be
    bit-exact under exactly ONE version — the pre-swap function or the
    post-swap function, never a mix (and the engine must not shed:
    a swap pauses, it never drops)."""
    eng = ServingEngine(_build_replica_predictor(seed=0), workers=2,
                        max_batch=4, max_delay_ms=1.0,
                        deadline_ms=60000.0)
    try:
        ck = _ckpt(tmp_path, "ck_v2", seed=2)
        from paddle_tpu import io
        params = io._read(os.path.join(ck, "__params__"))
        x = _probe()
        old = eng.submit({"x": x}).result(30.0)[0]
        new = _mlp_reference(params, x).astype(np.asarray(old).dtype)

        futs = [eng.submit({"x": x}) for _ in range(16)]
        res = eng.swap_weights(ck, timeout_s=30.0)
        futs += [eng.submit({"x": x}) for _ in range(16)]
        assert res["weights_version"] == 2
        for f in futs:
            got = np.asarray(f.result(30.0)[0])
            assert (np.array_equal(got, old)
                    or np.array_equal(got, new)), \
                "torn or corrupted response across the swap boundary"
        # post-swap requests all serve the new function
        got = np.asarray(eng.submit({"x": x}).result(30.0)[0])
        np.testing.assert_array_equal(got, new)
    finally:
        eng.close()


def test_http_warming_replica_sheds():
    """A replica gated on warmup refuses data-plane POSTs outright:
    admission before warmup would race the warmup pass's direct
    program runs on donated buffers (SIGABRT, not an error reply)."""
    import tools.serving_loadgen as lg
    reset_unique_name()
    predictor, shapes = lg.build_synthetic(feat=8, hidden=16, depth=1,
                                           classes=4)
    eng = ServingEngine(predictor, workers=1, max_batch=2,
                        max_delay_ms=1.0, deadline_ms=60000.0,
                        ready_requires_warmup=True)
    srv = serve(eng, port=0)
    try:
        x = _probe()
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url + "/predict", {"inputs": {"x": x.tolist()}})
        assert e.value.code == 503
        doc = json.loads(e.value.read())
        assert doc["reason"] == "warming"
        assert e.value.headers.get("Retry-After")
        assert e.value.headers.get(VERSION_HEADER) == "1"

        eng.warmup(shapes)
        code, _, _ = _post(srv.url + "/predict",
                           {"inputs": {"x": x.tolist()}})
        assert code == 200
    finally:
        srv.close()
        eng.close()


# ---------------------------------------------------------------------------
# Generation: decode-boundary swap
# ---------------------------------------------------------------------------

MODEL = dict(vocab_size=61, hidden=32, num_layers=2, num_heads=4,
             num_kv_heads=2, intermediate=64)


def _gen_engine(seed):
    return GenerationEngine(MODEL, num_slots=2, max_seq_len=48,
                            max_new_tokens=6, attn_impl="xla",
                            seed=seed, queue_cap=32,
                            deadline_ms=600000.0)


def test_generation_swap_decode_boundary():
    eng_a = _gen_engine(seed=0)
    eng_b = _gen_engine(seed=1)
    try:
        prompt = [3, 14, 15, 9, 2]
        want = eng_b.submit(list(prompt), max_new_tokens=6) \
                    .result(120.0)["tokens"]
        arrays = {n: np.array(eng_b.scope.find_var(n))
                  for n in eng_a._weight_names()}

        # boundary swap with the scheduler live: first run A so its
        # thread + grid are hot, then commit between grid steps
        eng_a.submit(list(prompt), max_new_tokens=6).result(120.0)
        res = eng_a.swap_weights(arrays)
        assert res["weights_version"] == 2
        got = eng_a.submit(list(prompt), max_new_tokens=6) \
                   .result(120.0)["tokens"]
        assert got == want, "post-swap decode must match the donor " \
                            "engine token-for-token"
        # structural drift refused before anything flips
        with pytest.raises(SwapMismatch):
            eng_a.swap_weights({n: v for n, v in list(arrays.items())[1:]})
        assert eng_a.weights_version == 2
    finally:
        eng_a.close()
        eng_b.close()


# ---------------------------------------------------------------------------
# Router canary: NaN burn -> auto-revert; clean soak -> promote
# ---------------------------------------------------------------------------

def _canary_fleet(tmp_path, n=3):
    # the fleet must start bit-identical for the revert/promote
    # checks: swap a common baseline checkpoint onto every engine
    # (fresh-build init is not seed-reproducible across processes
    # either — real fleets converge the same way, by checkpoint)
    base = _ckpt(tmp_path, "ck_base", seed=5)
    engines, servers = [], []
    for _ in range(n):
        eng = ServingEngine(_build_replica_predictor(),
                            workers=1, max_batch=4, max_delay_ms=1.0,
                            deadline_ms=60000.0)
        eng.swap_weights(base)
        engines.append(eng)
        servers.append(serve(eng, port=0))
    return engines, servers


def _pump_until(router, server, deadline_s, stop):
    """Drive traffic through the router + its judge until ``stop()``
    (deterministic: poll_once() runs the canary evaluation inline)."""
    x = _probe().tolist()
    t_end = time.monotonic() + deadline_s
    while time.monotonic() < t_end:
        for _ in range(6):
            try:
                _post(server.url + "/predict", {"inputs": {"x": x}},
                      timeout=10.0)
            except urllib.error.HTTPError:
                pass  # canary-side failures are the evidence
        router.poll_once()
        st = router.canary_status()
        if stop(st):
            return st
        time.sleep(0.05)
    return router.canary_status()


def test_canary_revert_and_promote(tmp_path):
    set_flags({"FLAGS_serving_check_outputs": True})
    engines, servers = _canary_fleet(tmp_path, 3)
    router = Router([s.url for s in servers], autostart=False,
                    poll_interval_ms=100.0, stale_ms=5000.0)
    front = RouterServer(router).start()
    try:
        router.poll_once()
        assert router.healthz()[1]["routable"] == 3

        # --- poisoned canary: burn conviction + fleet-wide revert ---
        ck_bad = _ckpt(tmp_path, "ck_bad", seed=7, poison_nan=True)
        started = router.canary(ck_bad, fraction=0.3, soak_s=30.0)
        assert started["state"] == "soaking"
        assert len(started["urls"]) == 1  # minority: ceil(.3*3)=1
        st = _pump_until(
            router, front, 60.0,
            lambda s: not s["active"]
            and (s["last"] or {}).get("state") in ("reverted",
                                                   "promoted"))
        assert st["last"]["state"] == "reverted", st["last"]
        assert st["last"]["reason"].startswith("burn:")
        assert st["counters"]["canary_reverts"] == 1
        # reverted replicas answer with the ORIGINAL function again
        x = _probe()
        base = engines[-1].submit({"x": x}).result(30.0)[0]
        for eng in engines:
            got = eng.submit({"x": x}).result(30.0)[0]
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(base))

        # --- clean canary: full soak, then fleet-wide promotion ---
        ck_good = _ckpt(tmp_path, "ck_good", seed=2)
        router.canary(ck_good, fraction=0.3, soak_s=1.5)
        st = _pump_until(
            router, front, 60.0,
            lambda s: not s["active"]
            and (s["last"] or {}).get("state") in ("reverted",
                                                   "promoted"))
        assert st["last"]["state"] == "promoted", st["last"]
        assert st["counters"]["canary_promotions"] == 1
        assert st["counters"]["canary_reverts"] == 1  # no false revert
        from paddle_tpu import io
        params = io._read(os.path.join(ck_good, "__params__"))
        want = _mlp_reference(params, x)
        for eng in engines:  # EVERY replica now serves the new version
            got = eng.submit({"x": x}).result(30.0)[0]
            np.testing.assert_array_equal(
                np.asarray(got), want.astype(np.asarray(got).dtype))
            assert eng.weights_version >= 2
    finally:
        set_flags({"FLAGS_serving_check_outputs": False})
        front.close()
        router.close()
        for s in servers:
            s.close()
        for e in engines:
            e.close()


def test_canary_fleet_level_atomicity(tmp_path):
    """A refused canary swap (structural drift) must leave ZERO
    replicas on the new version — already-swapped minority reverted."""
    engines, servers = _canary_fleet(tmp_path, 2)
    router = Router([s.url for s in servers], autostart=False)
    try:
        router.poll_once()
        bad = _ckpt(tmp_path, "ck_wide", seed=3, hidden=32)
        with pytest.raises(RuntimeError, match="refused"):
            router.canary(bad, fraction=0.5, soak_s=5.0)
        assert not router.canary_status()["active"]
        for eng in engines:
            assert eng.weights_version == 2  # baseline swap only
        # and a fleet that cannot split refuses outright
        solo = Router([servers[0].url], autostart=False)
        try:
            solo.poll_once()
            with pytest.raises(RuntimeError, match="split"):
                solo.canary(bad, fraction=0.5, soak_s=5.0)
        finally:
            solo.close()
    finally:
        router.close()
        for s in servers:
            s.close()
        for e in engines:
            e.close()

# ---------------------------------------------------------------------------
# Fleet: one-replica-at-a-time hot swap across real replica processes
# ---------------------------------------------------------------------------

def test_fleet_hot_swap_converges(tmp_path):
    from paddle_tpu.serving.fleet import FleetSupervisor
    argv = ["--feat", "4", "--hidden", "8", "--depth", "1",
            "--classes", "2", "--workers", "1", "--max-batch", "4",
            "--max-delay-ms", "1", "--deadline-ms", "60000"]
    ck = str(tmp_path / "ck_v2")
    build_synthetic_checkpoint(ck, feat=4, hidden=8, depth=1,
                               classes=2, seed=9)
    sup = FleetSupervisor(replicas=2, replica_argv=argv,
                          max_restarts=2, backoff_ms=100.0,
                          workdir=str(tmp_path))
    try:
        urls = sup.wait_ready(timeout_s=240)
        rep = sup.hot_swap(ck)
        assert rep["converged"], rep
        assert [r["weights_version"] for r in rep["replicas"]] == [2, 2]
        assert all(r["swap_status"] == 200 and not r.get("fallback")
                   for r in rep["replicas"])
        # every replica answers under the new version, bit-exactly
        from paddle_tpu import io
        params = io._read(os.path.join(ck, "__params__"))
        x = np.linspace(-1.0, 1.0, 4, dtype="float32").reshape(1, 4)
        for url in urls:
            code, doc, hdr = _post(url + "/predict",
                                   {"inputs": {"x": x.tolist()}})
            assert code == 200 and hdr[VERSION_HEADER] == "2"
            np.testing.assert_allclose(np.asarray(doc["outputs"][0]),
                                       _mlp_reference(params, x),
                                       rtol=0, atol=0)
    finally:
        sup.close()
