"""graftcheck static-analysis tests: every rule has a seeded-violation
fixture it detects AND a clean twin it passes; the real tree scans
clean; the waiver/baseline machinery round-trips; and the runtime
lock-order sanitizer detects a provoked A->B / B->A inversion.

The fixtures are the rules' contract: a rule that silently stopped
firing on its own triggering shape is worse than no rule (the same
argument as perf_gate --smoke).
"""
import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.graftcheck import core  # noqa: E402
from tools.graftcheck.passes import flag_hygiene, stat_catalog  # noqa: E402


def run_on(tmp_path, source: str, rules, baseline: str = None):
    """Write one fixture module, run the selected passes on it, and
    return the violations list."""
    mod = tmp_path / "fixture.py"
    mod.write_text(textwrap.dedent(source))
    bl = None
    if baseline is not None:
        blf = tmp_path / "baseline.txt"
        blf.write_text(textwrap.dedent(baseline))
        bl = str(blf)
    report = core.run(roots=[str(mod)], rule_filter=rules,
                      baseline_path=bl)
    return report


def rules_of(report):
    return sorted({v.rule for v in report.violations})


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

LOCK_BARE = """
    import threading

    class Engine:
        def __init__(self):
            self._lock = threading.Lock()
            self._queue = []
            self._draining = False

        def start(self):
            threading.Thread(target=self.worker).start()

        def worker(self):
            with self._lock:
                self._queue.append(1)
                self._draining = True

        def stats(self):
            return {"depth": len(self._queue),
                    "draining": self._draining}
"""

LOCK_BARE_CLEAN = """
    import threading

    class Engine:
        def __init__(self):
            self._lock = threading.Lock()
            self._queue = []
            self._draining = False

        def start(self):
            threading.Thread(target=self.worker).start()

        def worker(self):
            with self._lock:
                self._queue.append(1)
                self._draining = True

        def stats(self):
            with self._lock:
                return {"depth": len(self._queue),
                        "draining": self._draining}

        def _drain_locked(self):
            # *_locked convention: the caller holds self._lock
            self._queue.clear()
            return self._draining
"""


def test_lock_bare_access_detected_and_clean_twin(tmp_path):
    r = run_on(tmp_path, LOCK_BARE, ["lock-discipline"])
    assert "lock-bare-access" in rules_of(r)
    keys = {v.key for v in r.violations}
    assert any("Engine.stats._queue" in k for k in keys)
    assert any("Engine.stats._draining" in k for k in keys)

    r = run_on(tmp_path, LOCK_BARE_CLEAN, ["lock-discipline"])
    assert r.violations == []


def test_lock_bare_access_wrong_lock_is_not_protection(tmp_path):
    """Holding an UNRELATED lock must not silence the race: lock
    identity matters, not lock count."""
    src = LOCK_BARE.replace(
        'def stats(self):\n'
        '            return {"depth": len(self._queue),\n'
        '                    "draining": self._draining}',
        'def stats(self):\n'
        '            with self._other:\n'
        '                return {"depth": len(self._queue),\n'
        '                        "draining": self._draining}')
    src = src.replace(
        "self._lock = threading.Lock()",
        "self._lock = threading.Lock()\n"
        "            self._other = threading.Lock()")
    r = run_on(tmp_path, src, ["lock-discipline"])
    msgs = [v for v in r.violations if v.rule == "lock-bare-access"]
    assert any("holding only" in v.message and "_other" in v.message
               for v in msgs), [v.message for v in msgs]


def test_lock_bare_access_requires_threaded_class(tmp_path):
    # same shape but no Thread anywhere: single-threaded class, no
    # finding (and no marker opt-in)
    src = LOCK_BARE.replace(
        "threading.Thread(target=self.worker).start()", "self.worker()")
    r = run_on(tmp_path, src, ["lock-discipline"])
    assert r.violations == []


LOCK_ORDER = """
    import threading

    class TwoLocks:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def forward(self):
            with self._a:
                with self._b:
                    pass

        def backward(self):
            with self._b:
                with self._a:
                    pass
"""

LOCK_ORDER_CLEAN = """
    import threading

    class TwoLocks:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def forward(self):
            with self._a:
                with self._b:
                    pass

        def also_forward(self):
            with self._a:
                with self._b:
                    pass
"""


def test_lock_order_cycle_detected_and_clean_twin(tmp_path):
    r = run_on(tmp_path, LOCK_ORDER, ["lock-discipline"])
    assert rules_of(r) == ["lock-order"]
    assert {v.key for v in r.violations} == \
        {"TwoLocks._a->TwoLocks._b", "TwoLocks._b->TwoLocks._a"}

    r = run_on(tmp_path, LOCK_ORDER_CLEAN, ["lock-discipline"])
    assert r.violations == []


def test_lock_order_interprocedural_and_self_nest(tmp_path):
    src = """
    import threading

    class Indirect:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def holder(self):
            with self._a:
                self.helper()

        def helper(self):
            with self._b:
                pass

        def reverse(self):
            with self._b:
                with self._a:
                    pass

    class SelfNest:
        def __init__(self):
            self._lock = threading.Lock()

        def oops(self):
            with self._lock:
                with self._lock:
                    pass
    """
    r = run_on(tmp_path, src, ["lock-discipline"])
    keys = {v.key for v in r.violations}
    # the A->B edge exists only through the helper() call
    assert "Indirect._a->Indirect._b" in keys
    assert "SelfNest._lock->SelfNest._lock" in keys


# ---------------------------------------------------------------------------
# resource-pairing
# ---------------------------------------------------------------------------

def test_pair_span_detected_and_clean_twin(tmp_path):
    bad = """
    from paddle_tpu.telemetry import span_begin, span_end

    def discarded():
        span_begin("serving/x")

    def leaked():
        s = span_begin("serving/y")
        return None
    """
    r = run_on(tmp_path, bad, ["resource-pairing"])
    assert rules_of(r) == ["pair-span"]
    assert len(r.violations) == 2

    good = """
    from paddle_tpu.telemetry import span_begin, span_end

    def paired():
        s = span_begin("serving/x")
        try:
            return 1
        finally:
            span_end(s)

    def handed_off(sink):
        s = span_begin("serving/y")
        sink.adopt(s)     # ownership transfer

    def stored(self_like):
        self_like._span = span_begin("serving/z")  # escape via store
    """
    r = run_on(tmp_path, good, ["resource-pairing"])
    assert r.violations == []


def test_pair_acquire_detected_and_clean_twin(tmp_path):
    bad = """
    def missing(self):
        self._lock.acquire()
        return work()

    def unsafe(self):
        self._lock.acquire()
        work()                  # raises -> lock held forever
        self._lock.release()
    """
    r = run_on(tmp_path, bad, ["resource-pairing"])
    assert rules_of(r) == ["pair-acquire"]
    msgs = " ".join(v.message for v in r.violations)
    assert "no matching" in msgs and "exception path" in msgs

    good = """
    def with_stmt(self):
        with self._lock:
            return work()

    def try_finally(self):
        self._lock.acquire()
        try:
            return work()
        finally:
            self._lock.release()

    def timeout_probe(self):
        if not self._lock.acquire(timeout=0.05):
            return None
        try:
            return work()
        finally:
            self._lock.release()
    """
    r = run_on(tmp_path, good, ["resource-pairing"])
    assert r.violations == []


def test_pair_refcount_detected_and_clean_twin(tmp_path):
    bad = """
    class Leaky:
        def grab(self):
            self._pool.alloc()          # discarded page

        def hold(self, pages):
            self._pool.incref(pages)    # never decref'd, no transfer
    """
    r = run_on(tmp_path, bad, ["resource-pairing"])
    assert rules_of(r) == ["pair-refcount"]
    # discarded alloc + local incref + class-level imbalance
    assert len(r.violations) == 3

    good = """
    class Balanced:
        def grab(self, slot):
            p = self._pool.alloc()
            if p is None:
                return False
            slot.pages.append(p)        # ownership transfer
            return True

        def adopt(self, slot, pages):
            self._pool.incref(pages)
            slot.pages = list(pages)    # ownership transfer

        def release(self, slot):
            self._pool.decref(slot.pages)
            slot.pages = []
    """
    r = run_on(tmp_path, good, ["resource-pairing"])
    assert r.violations == []


def test_pair_draft_detected_and_clean_twin(tmp_path):
    bad = """
    class Speculator:
        def round(self, slot):
            keep = self._acquire_draft_pages(slot, 4)
            return keep                 # no rollback/release path
    """
    r = run_on(tmp_path, bad, ["resource-pairing"])
    assert rules_of(r) == ["pair-draft"]
    assert r.violations[0].key.endswith(":draft-pages")

    good = """
    class Speculator:
        def round(self, slot):
            keep = self._acquire_draft_pages(slot, 4)
            self._rollback_draft_pages(slot, keep)

        def fail_path(self, slot):
            self._acquire_draft_pages(slot, 4)
            self._release_pages(slot)   # whole-slot release also pairs

        def _acquire_draft_pages(self, slot, n):
            # the helper itself is exempt: it rolls back internally
            # on the exhaustion path before re-raising
            return len(slot.pages)
    """
    r = run_on(tmp_path, good, ["resource-pairing"])
    assert r.violations == []


# ---------------------------------------------------------------------------
# donation-safety
# ---------------------------------------------------------------------------

def test_donation_use_after_alias_detected_and_clean_twin(tmp_path):
    bad = """
    from paddle_tpu import layers

    def block(cache_k, k, positions):
        layers.kv_cache_write(cache_k, k, positions)
        return layers.matmul(cache_k, k)   # reads the donated buffer
    """
    r = run_on(tmp_path, bad, ["donation-safety"])
    assert rules_of(r) == ["donation-use-after-alias"]
    assert r.violations[0].key.endswith(":cache_k")

    good = """
    from paddle_tpu import layers

    def block(cache_k, k, positions):
        cache_k = layers.kv_cache_write(cache_k, k, positions)
        return layers.matmul(cache_k, k)   # rebound: the op's output

    def last_use(cache_k, k, positions):
        out = layers.kv_cache_write(cache_k, k, positions)
        return out                          # donated name never read

    def tuple_rebind(cache_k, cache_v, k, v, pos):
        cache_k, cache_v = (layers.kv_cache_write(cache_k, k, pos),
                            layers.kv_cache_write(cache_v, v, pos))
        return layers.matmul(cache_k, cache_v)
    """
    r = run_on(tmp_path, good, ["donation-safety"])
    assert r.violations == []


def test_donation_jit_callable_detected_and_clean_twin(tmp_path):
    bad = """
    import jax

    class Engine:
        def build(self):
            self._adopt_scatter = jax.jit(
                lambda pool, idx, rows: pool.at[idx].set(rows),
                donate_argnums=(0,))

        def adopt(self, pool, idx, rows):
            self._adopt_scatter(pool, idx, rows)
            return pool.sum()           # reads the donated buffer
    """
    r = run_on(tmp_path, bad, ["donation-safety"])
    assert rules_of(r) == ["donation-use-after-alias"]
    assert r.violations[0].key.endswith(":pool")

    good = """
    import jax

    class Engine:
        def build(self, donate_state):
            self._adopt_scatter = jax.jit(
                lambda pool, idx, rows: pool.at[idx].set(rows),
                donate_argnums=(0,) if donate_state else ())

        def adopt(self, pool, idx, rows):
            pool = self._adopt_scatter(pool, idx, rows)
            return pool.sum()           # rebound same statement

        def multiline(self, pool, idx,
                      rows):
            out = self._adopt_scatter(pool,
                                      idx, rows)
            return out                  # donated name never read after

        def plain(self, pool):
            self._undonated(pool)
            return pool.sum()           # not a donating callable
    """
    r = run_on(tmp_path, good, ["donation-safety"])
    assert r.violations == []


# ---------------------------------------------------------------------------
# flag-hygiene
# ---------------------------------------------------------------------------

def test_flag_hygiene_rules(tmp_path, monkeypatch):
    readme = tmp_path / "README.md"
    readme.write_text("docs: `FLAGS_fx_documented` is a knob\n")
    monkeypatch.setattr(flag_hygiene, "README_PATH", str(readme))
    monkeypatch.setattr(flag_hygiene, "READ_EVIDENCE_ROOTS", ())
    bad = """
    from paddle_tpu.flags import register_flag, flag_value

    register_flag("FLAGS_fx_dead", 0, "never read")
    register_flag("FLAGS_fx_documented", 0, "read below")

    def f():
        flag_value("FLAGS_fx_documented")
        return flag_value("FLAGS_fx_typod")     # never registered
    """
    r = run_on(tmp_path, bad, ["flag-hygiene"])
    got = {(v.rule, v.key) for v in r.violations}
    assert ("flag-undefined", "FLAGS_fx_typod") in got
    assert ("flag-unused", "FLAGS_fx_dead") in got
    assert ("flag-undocumented", "FLAGS_fx_dead") in got
    # defined + read + documented -> clean
    assert not any(k == "FLAGS_fx_documented" for _, k in got)

    good = """
    from paddle_tpu.flags import register_flag, flag_value

    register_flag("FLAGS_fx_documented", 0, "read below")

    def f():
        return flag_value("FLAGS_fx_documented")
    """
    r = run_on(tmp_path, good, ["flag-hygiene"])
    assert r.violations == []


# ---------------------------------------------------------------------------
# exception-policy + stat-catalog (absorbed tools)
# ---------------------------------------------------------------------------

def test_bare_except_pass_detected_and_waiver_honored(tmp_path):
    bad = """
    def f():
        try:
            x = 1
        except Exception:
            pass
    """
    r = run_on(tmp_path, bad, ["exception-policy"])
    assert rules_of(r) == ["bare-except-pass"]

    good = """
    def f():
        try:
            x = 1
        except StopIteration:
            pass  # ok: generator drained
        try:
            y = 2
        except Exception:
            log("boom")
            pass
    """
    r = run_on(tmp_path, good, ["exception-policy"])
    assert r.violations == []


def test_stat_undocumented_detected_and_clean_twin(tmp_path, monkeypatch):
    readme = tmp_path / "README.md"
    readme.write_text("**Stat catalog** `fx_known_stat`\n")
    monkeypatch.setattr(stat_catalog, "README_PATH", str(readme))
    bad = """
    from paddle_tpu.monitor import stat_add
    from paddle_tpu import telemetry

    def f():
        stat_add("fx_known_stat")
        stat_add("fx_unknown_stat")
        telemetry.gauge_set("fx_unknown_gauge", 1.0)
        stat_add(f"dynamic_{f.__name__}")   # non-literal: out of scope
    """
    r = run_on(tmp_path, bad, ["stat-catalog"])
    assert {v.key for v in r.violations} == \
        {"fx_unknown_stat", "fx_unknown_gauge"}

    good = bad.replace('"fx_unknown_stat"', '"fx_known_stat"').replace(
        '"fx_unknown_gauge"', '"fx_known_stat"')
    r = run_on(tmp_path, good, ["stat-catalog"])
    assert r.violations == []


# ---------------------------------------------------------------------------
# waivers / baseline machinery
# ---------------------------------------------------------------------------

def test_inline_gc_ok_waiver_suppresses(tmp_path):
    src = LOCK_BARE.replace(
        '"draining": self._draining}',
        '"draining": self._draining}  # gc-ok: lock-bare-access '
        'point-in-time probe')
    r = run_on(tmp_path, src, ["lock-discipline"])
    assert not any(v.key.endswith("_draining") for v in r.violations)
    assert any(v.key.endswith("_draining") and "inline" in reason
               for v, reason in r.waived)


def test_baseline_waives_and_goes_stale(tmp_path):
    mod = tmp_path / "fixture.py"
    mod.write_text(textwrap.dedent(LOCK_ORDER))
    rel = os.path.relpath(str(mod), REPO).replace(os.sep, "/")
    bl = tmp_path / "bl.txt"
    bl.write_text(
        f"lock-order  {rel}  TwoLocks._a->TwoLocks._b  -- fixture\n"
        f"lock-order  {rel}  TwoLocks._b->TwoLocks._a  -- fixture\n"
        f"lock-order  {rel}  TwoLocks.nothing->x  -- stale entry\n"
        f"lock-order {rel} missing-reason\n")
    r = core.run(roots=[str(mod)], rule_filter=["lock-discipline"],
                 baseline_path=str(bl))
    assert len(r.waived) == 2
    got = rules_of(r)
    assert "stale-waiver" in got and "baseline-format" in got
    assert "lock-order" not in got


def test_unknown_rule_rejected():
    with pytest.raises(ValueError, match="unknown rule"):
        core.run(rule_filter=["no-such-rule"], roots=["tools"])


# ---------------------------------------------------------------------------
# the real tree is clean (the acceptance bar: fixes landed, waivers
# carry reasons) and the CLI contract holds
# ---------------------------------------------------------------------------

def test_real_tree_scans_clean():
    r = core.run()
    assert r.violations == [], "\n".join(
        v.render() for v in r.violations)
    # every waiver that applies carries a reason string
    assert all(reason for _, reason in r.waived)


def test_subset_roots_scan_clean():
    """A subset-root run must not manufacture violations: flag reads
    still resolve against the registry file even when it is outside
    the roots, and baseline waivers for out-of-scope files are not
    reported stale."""
    for roots in (["paddle_tpu/serving"], ["tools"]):
        r = core.run(roots=roots)
        assert r.violations == [], (roots, "\n".join(
            v.render() for v in r.violations))


def test_missing_root_is_an_error():
    with pytest.raises(FileNotFoundError, match="root not found"):
        core.run(roots=["no_such_directory_anywhere"])


def test_cli_json_stable_and_sorted(tmp_path):
    out1 = subprocess.run(
        [sys.executable, "-m", "tools.graftcheck", "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    out2 = subprocess.run(
        [sys.executable, "-m", "tools.graftcheck", "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert out1.returncode == 0, out1.stdout + out1.stderr
    assert out1.stdout == out2.stdout  # byte-stable across runs
    payload = json.loads(out1.stdout)
    assert payload["ok"] is True
    assert payload["passes"] == sorted(payload["passes"])
    waived = payload["waived"]
    assert waived == sorted(
        waived, key=lambda v: (v["path"], v["line"], v["rule"],
                               v["key"], v["message"]))


def test_cli_rule_filter_and_exit_code(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    x = 1\nexcept Exception:\n    pass\n")
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftcheck", "--rule",
         "exception-policy", "--baseline", "", str(bad)],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 1
    assert "bare-except-pass" in r.stdout


# ---------------------------------------------------------------------------
# runtime lock-order sanitizer
# ---------------------------------------------------------------------------

def test_locksan_detects_ab_ba_inversion():
    from paddle_tpu import locksan

    locksan.clear_violations()
    locksan.enable(raise_on_violation=True)
    try:
        A = threading.Lock()
        B = threading.Lock()
        boom = []

        def t_forward():
            with A:
                with B:
                    pass

        def t_backward():
            try:
                with B:
                    with A:       # closes the cycle
                        pass
            except locksan.LockOrderError as e:
                boom.append(str(e))

        for fn in (t_forward, t_backward):
            th = threading.Thread(target=fn)
            th.start()
            th.join(10)
        assert len(boom) == 1 and "inversion" in boom[0]
        assert len(locksan.violations()) == 1
        # the failed acquire gave the real lock back: A is free
        assert A.acquire(timeout=1)
        A.release()
    finally:
        locksan.disable()
        locksan.clear_violations()


def test_locksan_record_mode_reports_each_inversion_once():
    """FLAGS_debug_lock_order mode (record, no raise): a hot-path
    inversion hit N times yields ONE violation, not unbounded
    growth in a long-running replica."""
    from paddle_tpu import locksan

    locksan.clear_violations()
    locksan.enable(raise_on_violation=False)
    try:
        A = threading.Lock()
        B = threading.Lock()

        def forward():
            with A:
                with B:
                    pass

        def backward():
            with B:
                with A:
                    pass

        for fn in (forward, backward, backward, backward):
            th = threading.Thread(target=fn)
            th.start()
            th.join(10)
        assert len(locksan.violations()) == 1, locksan.violations()
    finally:
        locksan.disable()
        locksan.clear_violations()


def test_locksan_cross_thread_lock_handoff_is_legal():
    """A plain Lock acquired in one thread and released in another
    (the handoff/token pattern) is legal Python: no violation, and
    the acquirer's held-stack entry is unwound so later nesting in
    that thread records no stale edges."""
    from paddle_tpu import locksan

    locksan.clear_violations()
    locksan.enable(raise_on_violation=True)
    try:
        token = threading.Lock()
        A = threading.Lock()
        token.acquire()          # main thread holds the token

        th = threading.Thread(target=token.release)  # handoff release
        th.start()
        th.join(10)
        # if the stale entry survived, this nesting would record a
        # bogus token->A edge from the main thread
        with A:
            pass
        assert locksan.violations() == [], locksan.violations()
    finally:
        locksan.disable()
        locksan.clear_violations()


def test_locksan_clean_patterns_record_nothing():
    from paddle_tpu import locksan

    locksan.clear_violations()
    locksan.enable(raise_on_violation=True)
    try:
        A = threading.Lock()
        R = threading.RLock()
        cv = threading.Condition()

        with A:
            with R:
                with R:           # reentrant: legal
                    pass
        done = []

        def waiter():
            with cv:
                while not done:
                    cv.wait(1.0)

        th = threading.Thread(target=waiter)
        th.start()
        with cv:                  # Condition round-trip through the
            done.append(1)        # wrapped RLock (wait/notify)
            cv.notify_all()
        th.join(10)
        assert locksan.violations() == []
    finally:
        locksan.disable()
        locksan.clear_violations()
