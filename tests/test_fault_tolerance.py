"""Fault-injection matrix: crash-safe checkpointing, skip-step on
non-finite loss, SIGTERM preemption, deterministic injection.

The acceptance bar (ISSUE 1): with an injected torn write + process kill
at an arbitrary step, a restart resumes from the last *valid* checkpoint
and the final trained params match an uninterrupted run bit-exact; every
injected fault and recovery action is visible via monitor counters.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import checkpoint as ckpt
from paddle_tpu import fault, layers, optimizer
from paddle_tpu.monitor import stat_get
from paddle_tpu.train_guard import TrainGuard, TrainingInterrupted


@pytest.fixture(autouse=True)
def _reset_faults():
    fault.reset()
    yield
    fault.reset()
    pt.set_flags({"FLAGS_fault_inject": ""})


def _net(lr=0.1):
    """-> (loss, weight_param_name); the name is unique-suffixed per
    process, so tests must not hardcode it."""
    x = layers.data("x", [4])
    y = layers.data("y", [1])
    pred = layers.fc(x, 1, name="gfc")
    loss = layers.mean(pt.layers.square_error_cost(pred, y))
    optimizer.SGDOptimizer(lr).minimize(loss)
    w = pt.default_main_program().global_block().all_parameters()[0]
    return loss, w.name


def _feed(seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(8, 4).astype("float32")
    return {"x": x, "y": (x.sum(1, keepdims=True) * 0.5).astype("float32")}


def _startup(scope=None):
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), scope=scope)
    return exe


def _clean_params(loss, feed, n_steps, name):
    """Uninterrupted guarded run of n_steps; returns the trained weight."""
    scope = pt.Scope()
    exe = _startup(scope)
    with pt.scope_guard(scope):
        g = TrainGuard(exe, loss, handle_sigterm=False)
        for _ in range(n_steps):
            g.step(feed, scope=scope)
        g.close()
    w = scope.find_var(name)
    assert w is not None, f"{name} missing from scope"
    return np.asarray(w)


# ---------------------------------------------------------------------------
# injector unit behavior
# ---------------------------------------------------------------------------

def test_injector_occurrence_and_sticky_triggers():
    inj = fault.FaultInjector("s:raise@2,t:torn@3+", seed=0)
    assert [inj.fire("s") for _ in range(4)] == \
        [None, "raise", None, None]
    assert [inj.fire("t") for _ in range(5)] == \
        [None, None, "torn", "torn", "torn"]


def test_injector_probabilistic_is_seeded():
    inj1 = fault.FaultInjector("s:raise~0.5", seed=7)
    inj2 = fault.FaultInjector("s:raise~0.5", seed=7)
    s1 = [inj1.fire("s") for _ in range(64)]
    s2 = [inj2.fire("s") for _ in range(64)]
    assert s1 == s2 and 0 < s1.count("raise") < 64


def test_injector_bad_spec_rejected():
    with pytest.raises(ValueError):
        fault.FaultInjector("ckpt_write-raise")


def test_injector_reads_flags():
    pt.set_flags({"FLAGS_fault_inject": "ckpt_write:raise@1"})
    inj = fault.configure()
    assert inj.fire("ckpt_write") == "raise"
    assert stat_get("fault_ckpt_write_raise") >= 1


# ---------------------------------------------------------------------------
# crash-safe checkpoint writes
# ---------------------------------------------------------------------------

def test_atomic_write_manifest_and_validation(tmp_path):
    d = str(tmp_path)
    loss, _w = _net()
    exe = _startup()
    exe.run(feed=_feed(), fetch_list=[loss])
    before = stat_get("checkpoint_writes")
    path = ckpt.save_checkpoint(d, 5)
    assert stat_get("checkpoint_writes") == before + 1
    mpath = os.path.join(path, ckpt.MANIFEST)
    assert os.path.isfile(mpath)
    manifest = json.load(open(mpath))
    assert manifest["step"] == 5 and manifest["files"]
    for meta in manifest["files"].values():
        assert set(meta) == {"bytes", "sha256"}
    assert ckpt.validate_checkpoint(d, 5)
    assert ckpt.latest_step(d) == 5
    assert not any(n.startswith(".tmp-") for n in os.listdir(d))

    # truncate a payload file: validation must reject, latest must hide it
    files = sorted(manifest["files"])
    victim = os.path.join(path, files[0])
    with open(victim, "r+b") as f:
        f.truncate(max(0, os.path.getsize(victim) // 2))
    assert not ckpt.validate_checkpoint(d, 5)
    assert ckpt.latest_step(d) is None
    assert ckpt.latest_step(d, validate=False) == 5


def test_write_retries_transient_error(tmp_path):
    d = str(tmp_path)
    loss, _w = _net()
    exe = _startup()
    exe.run(feed=_feed(), fetch_list=[loss])
    fault.configure("ckpt_write:raise@1")
    r0, f0 = stat_get("checkpoint_retries"), stat_get("faults_injected")
    ckpt.save_checkpoint(d, 3)
    assert stat_get("checkpoint_retries") == r0 + 1
    assert stat_get("faults_injected") == f0 + 1
    assert ckpt.validate_checkpoint(d, 3)


def test_write_gives_up_past_retry_budget(tmp_path):
    d = str(tmp_path)
    loss, _w = _net()
    exe = _startup()
    exe.run(feed=_feed(), fetch_list=[loss])
    fault.configure("ckpt_write:raise@1+")
    with pytest.raises(OSError):
        ckpt.save_checkpoint(d, 3)
    assert os.listdir(d) == []  # failed attempts leave no debris
    assert ckpt.latest_step(d) is None


def test_retention_gc_keeps_newest_valid(tmp_path):
    d = str(tmp_path)
    loss, _w = _net()
    exe = _startup()
    exe.run(feed=_feed(), fetch_list=[loss])
    for s in (1, 2, 3, 4):
        ckpt.save_checkpoint(d, s)
    g0 = stat_get("checkpoints_gc")
    ckpt.save_checkpoint(d, 5, keep_last_n=2)
    assert ckpt.valid_steps(d) == [4, 5]
    assert stat_get("checkpoints_gc") == g0 + 3


# ---------------------------------------------------------------------------
# the fault matrix: resume is bit-exact from the last VALID checkpoint
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("spec,expected_resume", [
    ("ckpt_write:torn@2", 3),       # 2nd write torn -> fall back to step 3
    ("ckpt_write:partial@2", 3),    # manifest-less -> fall back to step 3
    ("ckpt_write:raise@2+", 3),     # storage down from 2nd write on
])
def test_fault_matrix_resume_bitexact(tmp_path, spec, expected_resume):
    d = str(tmp_path / "ck")
    loss, w_name = _net()
    feed = _feed()

    # life 1: train 7 steps with periodic checkpoints at counter steps 3, 6
    fault.configure(spec)
    skipped0 = stat_get("checkpoint_corrupt_skipped")
    exe = _startup()
    g = TrainGuard(exe, loss, checkpoint_dir=d, interval_steps=3,
                   keep_last_n=5, handle_sigterm=False)
    assert g.resumed_step is None
    for _ in range(7):
        g.step(feed)
    g.close()
    fault.reset()
    assert stat_get("faults_injected") > 0
    assert ckpt.latest_step(d) == expected_resume

    # life 2 ("after the crash"): fresh scope + executor, auto-resume
    s2 = pt.Scope()
    exe2 = _startup(s2)
    with pt.scope_guard(s2):
        g2 = TrainGuard(exe2, loss, checkpoint_dir=d, interval_steps=3,
                        keep_last_n=5, handle_sigterm=False)
        assert g2.resumed_step == expected_resume
        assert exe2._step == expected_resume
        while exe2._step < 8:
            g2.step(feed, scope=s2)
        g2.close()
    if spec != "ckpt_write:raise@2+":
        # the newer corrupt checkpoint was skipped on the way down
        assert stat_get("checkpoint_corrupt_skipped") > skipped0
    w_resumed = s2.find_var(w_name)
    assert w_resumed is not None

    # uninterrupted comparator: same 7 training steps, no faults
    w_clean = _clean_params(loss, feed, 7, w_name)
    np.testing.assert_array_equal(np.asarray(w_resumed), w_clean)


def test_nan_loss_skips_step_and_backs_off_scaler():
    loss, w_name = _net()
    feed = _feed()
    fault.configure("loss:nan@3")
    scaler = pt.amp.GradScaler(enable=True, init_loss_scaling=8.0,
                               decr_every_n_nan_or_inf=1)
    seen = []
    exe = _startup()
    sk0 = stat_get("skipped_nonfinite_steps")
    g = TrainGuard(exe, loss, scaler=scaler, on_nonfinite=seen.append,
                   handle_sigterm=False)
    outs = [g.step(feed, fetch_list=[loss])[0] for _ in range(5)]
    g.close()
    assert stat_get("skipped_nonfinite_steps") == sk0 + 1
    assert stat_get("fault_loss_nan") >= 1
    assert g.skipped_steps == 1 and seen == [4]  # counter: startup was 1
    assert not np.isfinite(outs[2]).all()        # the poisoned fetch
    assert all(np.isfinite(o).all() for i, o in enumerate(outs) if i != 2)
    assert scaler.get_scale() == 4.0             # 8.0 * decr_ratio 0.5
    # params match a clean run with the skipped update left out entirely
    w_guarded = pt.global_scope().find_var(w_name)
    assert w_guarded is not None
    w_clean = _clean_params(loss, feed, 4, w_name)
    np.testing.assert_array_equal(np.asarray(w_guarded), w_clean)


def test_legacy_orbax_checkpoint_still_loads(tmp_path):
    """Pre-manifest checkpoints (orbax payload directly under
    <dir>/<step>, no MANIFEST.json) keep working across the upgrade."""
    d = str(tmp_path)
    loss, w_name = _net()
    exe = _startup()
    exe.run(feed=_feed(), fetch_list=[loss])
    import orbax.checkpoint as ocp
    w_before = np.asarray(pt.global_scope().find_var(w_name)).copy()
    ocp.PyTreeCheckpointer().save(
        os.path.abspath(os.path.join(d, "7")), {w_name: w_before},
        force=True)
    assert ckpt.latest_step(d) == 7
    pt.global_scope().set_var(w_name, np.zeros_like(w_before))
    ckpt.load_checkpoint(d, 7)
    np.testing.assert_array_equal(
        np.asarray(pt.global_scope().find_var(w_name)), w_before)


def test_eval_program_nan_does_not_trigger_skip():
    """An interleaved eval run (program clone carrying the same loss var)
    must not count as a skipped step or back off the loss scale."""
    loss, _w = _net()
    feed = _feed()
    test_prog = pt.default_main_program().clone(for_test=True)
    scaler = pt.amp.GradScaler(enable=True, init_loss_scaling=8.0,
                               decr_every_n_nan_or_inf=1)
    exe = _startup()
    g = TrainGuard(exe, loss, scaler=scaler, handle_sigterm=False)
    g.step(feed, fetch_list=[loss])
    sk0 = stat_get("skipped_nonfinite_steps")
    bad = {k: np.full_like(v, np.nan)
           if np.issubdtype(np.asarray(v).dtype, np.floating) else v
           for k, v in feed.items()}
    out = exe.run(test_prog, feed=bad, fetch_list=[loss.name])
    assert not np.isfinite(out[0]).all()
    assert stat_get("skipped_nonfinite_steps") == sk0
    assert g.skipped_steps == 0 and scaler.get_scale() == 8.0
    g.close()


def test_close_uninstalls_auto_checkpoint(tmp_path):
    d = str(tmp_path / "ck")
    loss, _w = _net()
    feed = _feed()
    exe = _startup()
    g = TrainGuard(exe, loss, checkpoint_dir=d, interval_steps=1,
                   handle_sigterm=False)
    g.step(feed)
    assert ckpt.latest_step(d) is not None
    g.close()
    n = len(ckpt.valid_steps(d))
    exe.run(feed=feed, fetch_list=[loss])  # post-close run: no more writes
    assert len(ckpt.valid_steps(d)) == n
    assert getattr(exe, "_auto_ckpt", None) is None


def test_guard_active_without_fetching_loss():
    """The skip-step guard keys on the program producing the loss, not on
    the caller fetching it — a bare step(feed) is still protected."""
    loss, w_name = _net()
    feed = _feed()
    fault.configure("loss:nan@2")
    exe = _startup()
    g = TrainGuard(exe, loss, handle_sigterm=False)
    for _ in range(3):
        out = g.step(feed)          # no fetch_list at all
        assert out == []            # caller's (empty) fetch_list honored
    g.close()
    assert g.skipped_steps == 1
    w_guarded = pt.global_scope().find_var(w_name)
    assert w_guarded is not None
    w_clean = _clean_params(loss, feed, 2, w_name)
    np.testing.assert_array_equal(np.asarray(w_guarded), w_clean)


def test_sigterm_writes_final_checkpoint_and_resumes_bitexact(tmp_path):
    d = str(tmp_path / "ck")
    loss, w_name = _net()
    feed = _feed()
    fault.configure("step:sigterm@4")
    sig0 = stat_get("sigterm_received")
    fin0 = stat_get("checkpoint_final")

    exe = _startup()
    g = TrainGuard(exe, loss, checkpoint_dir=d, interval_steps=100)
    with pytest.raises(TrainingInterrupted) as ei:
        for _ in range(7):
            g.step(feed)
    g.close()
    fault.reset()
    assert ei.value.code == 0                       # clean exit contract
    assert stat_get("sigterm_received") == sig0 + 1
    assert stat_get("checkpoint_final") == fin0 + 1
    assert stat_get("fault_step_sigterm") >= 1
    # 4 training runs happened (counter 2..5); final checkpoint at 5
    assert ckpt.latest_step(d) == 5
    assert ckpt.validate_checkpoint(d, 5)

    # preempted worker restarts: resume and finish the remaining steps
    s2 = pt.Scope()
    exe2 = _startup(s2)
    with pt.scope_guard(s2):
        g2 = TrainGuard(exe2, loss, checkpoint_dir=d, interval_steps=100,
                        handle_sigterm=False)
        assert g2.resumed_step == 5
        while exe2._step < 8:
            g2.step(feed, scope=s2)
        g2.close()
    w_resumed = s2.find_var(w_name)
    assert w_resumed is not None
    w_clean = _clean_params(loss, feed, 7, w_name)
    np.testing.assert_array_equal(np.asarray(w_resumed), w_clean)


def test_deferred_guard_interval_bitexact_resume(tmp_path):
    """Async-pipeline interaction: `loss:nan@N` with
    FLAGS_guard_resolve_interval=8 and fetch-free async steps — the skip
    verdict resolves in deferred batches (at checkpoints/close, never
    per step), yet `skipped_nonfinite_steps` is exact, the callback gets
    the ORIGINAL step id, and crash+resume stays bit-exact because the
    skip re-selection never left the graph."""
    d = str(tmp_path / "ck")
    loss, w_name = _net()
    feed = _feed()
    pt.set_flags({"FLAGS_guard_resolve_interval": 8})
    try:
        fault.configure("loss:nan@3")
        sk0 = stat_get("skipped_nonfinite_steps")
        seen = []
        exe = _startup()
        g = TrainGuard(exe, loss, checkpoint_dir=d, interval_steps=3,
                       keep_last_n=5, handle_sigterm=False,
                       on_nonfinite=seen.append)
        for _ in range(7):          # counter steps 2..8, nan at 4
            g.step_async(feed)      # fetch-free: nothing resolves inline
        g.close()
        fault.reset()
        assert stat_get("skipped_nonfinite_steps") == sk0 + 1
        assert g.skipped_steps == 1 and seen == [4]
        assert ckpt.latest_step(d) == 6

        # life 2 (after the crash): resume at 6, finish steps 7..8
        s2 = pt.Scope()
        exe2 = _startup(s2)
        with pt.scope_guard(s2):
            g2 = TrainGuard(exe2, loss, checkpoint_dir=d,
                            interval_steps=3, keep_last_n=5,
                            handle_sigterm=False)
            assert g2.resumed_step == 6
            while exe2._step < 8:
                g2.step_async(feed, scope=s2)
            g2.close()
        w_resumed = s2.find_var(w_name)
        assert w_resumed is not None
        # comparator: identical feed every step, so 7 guarded steps with
        # one in-graph skip == 6 clean steps, bit-exact
        w_clean = _clean_params(loss, feed, 6, w_name)
        np.testing.assert_array_equal(np.asarray(w_resumed), w_clean)
    finally:
        pt.set_flags({"FLAGS_guard_resolve_interval": 64})


def test_deferred_guard_scaler_backoff_on_resolution():
    """GradScaler backoff fires at RESOLUTION time (not dispatch) and
    records the original non-finite step id."""
    loss, _w = _net()
    feed = _feed()
    fault.configure("loss:nan@2")
    scaler = pt.amp.GradScaler(enable=True, init_loss_scaling=8.0,
                               decr_every_n_nan_or_inf=1)
    exe = _startup()
    g = TrainGuard(exe, loss, scaler=scaler, handle_sigterm=False)
    pt.set_flags({"FLAGS_guard_resolve_interval": 0})
    try:
        for _ in range(4):          # counter steps 2..5, nan at 3
            g.step_async(feed)
        assert scaler.get_scale() == 8.0       # verdict still on device
        exe.resolve_nonfinite_guard()
        assert scaler.get_scale() == 4.0       # backoff landed
        assert scaler.last_nonfinite_step == 3
        g.close()
    finally:
        pt.set_flags({"FLAGS_guard_resolve_interval": 64})


def test_explicit_corrupt_step_raises_before_scope_mutation(tmp_path):
    d = str(tmp_path)
    loss, w_name = _net()
    exe = _startup()
    exe.run(feed=_feed(), fetch_list=[loss])
    ckpt.save_checkpoint(d, 2)
    w_var = pt.global_scope().find_var(w_name)
    assert w_var is not None
    w_before = np.asarray(w_var).copy()
    os.remove(os.path.join(d, "2", ckpt.MANIFEST))
    pt.global_scope().set_var(w_name, w_before + 1.0)
    with pytest.raises(ckpt.CheckpointCorrupt):
        ckpt.load_checkpoint(d, 2)
    # the half-restore guard: scope untouched by the failed load
    np.testing.assert_array_equal(
        np.asarray(pt.global_scope().find_var(w_name)), w_before + 1.0)
