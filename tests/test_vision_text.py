"""paddle.vision / paddle.text namespaces (reference
python/paddle/vision/, python/paddle/text/): transforms math vs numpy,
dataset parsers against synthetic files in the published formats, model
zoo forward shapes."""
import gzip
import os
import pickle
import struct
import tarfile

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.vision import transforms as T


# ---------------------------------------------------------------------------
# transforms
# ---------------------------------------------------------------------------

def test_resize_shapes_and_short_side():
    img = np.arange(24 * 12 * 3, dtype=np.uint8).reshape(24, 12, 3)
    assert T.resize(img, (6, 8)).shape == (6, 8, 3)
    # int size: short side -> 6, AR kept (24x12 -> 12x6)
    assert T.resize(img, 6).shape == (12, 6, 3)
    assert T.resize(img, 6, "nearest").shape == (12, 6, 3)


def test_resize_bilinear_matches_constant_image():
    img = np.full((10, 10, 3), 7.0, np.float32)
    out = T.resize(img, (4, 4))
    np.testing.assert_allclose(out, 7.0, rtol=1e-6)


def test_center_crop_and_flips():
    img = np.arange(5 * 5, dtype=np.float32).reshape(5, 5, 1)
    c = T.center_crop(img, 3)
    np.testing.assert_allclose(c[..., 0], img[1:4, 1:4, 0])
    np.testing.assert_allclose(T.hflip(img), img[:, ::-1])
    np.testing.assert_allclose(T.vflip(img), img[::-1])


def test_to_tensor_and_normalize():
    img = np.full((4, 4, 3), 255, np.uint8)
    t = T.ToTensor()(img)
    assert t.shape == (3, 4, 4) and t.dtype == np.float32
    np.testing.assert_allclose(t, 1.0)
    # dark uint8 images scale by dtype range, not by value
    dark = np.full((2, 2, 3), 1, np.uint8)
    np.testing.assert_allclose(T.ToTensor()(dark), 1.0 / 255.0)
    # float inputs pass through unscaled
    f = np.full((2, 2, 3), 2.5, np.float32)
    np.testing.assert_allclose(T.ToTensor()(f), 2.5)
    n = T.Normalize(mean=[0.5, 0.5, 0.5], std=[0.5, 0.5, 0.5])(t)
    np.testing.assert_allclose(n, 1.0)


def test_compose_pipeline():
    tr = T.Compose([T.Resize(8), T.CenterCrop(6), T.ToTensor(),
                    T.Normalize([0.0] * 3, [1.0] * 3)])
    out = tr(np.zeros((16, 16, 3), np.uint8))
    assert out.shape == (3, 6, 6)


def test_random_transforms_shapes():
    img = np.zeros((9, 9, 3), np.uint8)
    assert T.RandomCrop(4)(img).shape == (4, 4, 3)
    assert T.RandomResizedCrop(5)(img).shape == (5, 5, 3)
    assert T.RandomHorizontalFlip(1.0)(img).shape == (9, 9, 3)
    assert T.Pad(2)(img).shape == (13, 13, 3)
    assert T.Grayscale(3)(img).shape == (9, 9, 3)
    assert T.BrightnessTransform(0.4)(img).shape == (9, 9, 3)
    assert T.ContrastTransform(0.4)(img).shape == (9, 9, 3)


# ---------------------------------------------------------------------------
# vision datasets (synthetic files in published formats)
# ---------------------------------------------------------------------------

def _write_mnist(tmpdir, n=10, gz=True):
    rng = np.random.RandomState(0)
    images = rng.randint(0, 256, (n, 28, 28), dtype=np.uint8)
    labels = rng.randint(0, 10, n).astype(np.uint8)
    ipath = os.path.join(tmpdir, "images-idx3-ubyte.gz")
    lpath = os.path.join(tmpdir, "labels-idx1-ubyte.gz")
    op = gzip.open if gz else open
    with op(ipath, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(images.tobytes())
    with op(lpath, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())
    return ipath, lpath, images, labels


def test_mnist_dataset(tmp_path):
    ipath, lpath, images, labels = _write_mnist(str(tmp_path))
    ds = pt.vision.datasets.MNIST(image_path=ipath, label_path=lpath)
    assert len(ds) == 10
    img, lab = ds[3]
    assert img.shape == (28, 28, 1)
    np.testing.assert_allclose(img[..., 0], images[3])
    assert lab == labels[3]
    # with transform
    ds2 = pt.vision.datasets.MNIST(image_path=ipath, label_path=lpath,
                                   transform=T.ToTensor())
    assert ds2[0][0].shape == (1, 28, 28)


def test_mnist_bad_magic(tmp_path):
    p = tmp_path / "bad"
    p.write_bytes(struct.pack(">IIII", 1234, 1, 28, 28))
    with pytest.raises(ValueError, match="magic"):
        pt.vision.datasets.MNIST(image_path=str(p), label_path=str(p))


def test_mnist_download_unavailable():
    with pytest.raises(ValueError, match="download"):
        pt.vision.datasets.MNIST()


def _write_cifar10(path, n_per_batch=4):
    rng = np.random.RandomState(1)
    with tarfile.open(path, "w:gz") as tar:
        import io

        for name in [f"cifar-10-batches-py/data_batch_{i}"
                     for i in range(1, 6)] + \
                ["cifar-10-batches-py/test_batch"]:
            d = {b"data": rng.randint(
                    0, 256, (n_per_batch, 3072), dtype=np.uint8),
                 b"labels": rng.randint(0, 10, n_per_batch).tolist()}
            raw = pickle.dumps(d)
            info = tarfile.TarInfo(name)
            info.size = len(raw)
            tar.addfile(info, io.BytesIO(raw))


def test_cifar10_dataset(tmp_path):
    p = str(tmp_path / "cifar-10-python.tar.gz")
    _write_cifar10(p)
    train = pt.vision.datasets.Cifar10(data_file=p, mode="train")
    test = pt.vision.datasets.Cifar10(data_file=p, mode="test")
    assert len(train) == 20 and len(test) == 4
    img, lab = train[0]
    assert img.shape == (32, 32, 3) and img.dtype == np.uint8
    assert 0 <= lab < 10


def test_dataset_folder(tmp_path):
    for cls in ("cat", "dog"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(3):
            np.save(d / f"{i}.npy",
                    np.zeros((8, 8, 3), np.uint8))
    ds = pt.vision.datasets.DatasetFolder(str(tmp_path))
    assert len(ds) == 6
    assert ds.classes == ["cat", "dog"]
    img, lab = ds[5]
    assert img.shape == (8, 8, 3) and lab == 1
    flat = pt.vision.datasets.ImageFolder(str(tmp_path))
    assert len(flat) == 6


# ---------------------------------------------------------------------------
# vision models
# ---------------------------------------------------------------------------

def test_lenet_forward():
    with pt.dygraph.guard():
        m = pt.vision.models.LeNet()
        x = pt.dygraph.VarBase(
            np.zeros((2, 1, 28, 28), np.float32))
        out = m(x)
        assert tuple(np.asarray(out._value).shape) == (2, 10)


def test_resnet18_forward_tiny():
    with pt.dygraph.guard():
        m = pt.vision.models.resnet18(num_classes=7)
        x = pt.dygraph.VarBase(
            np.zeros((1, 3, 32, 32), np.float32))
        out = m(x)
        assert tuple(np.asarray(out._value).shape) == (1, 7)


def test_pretrained_rejected():
    with pytest.raises(ValueError, match="pretrained"):
        pt.vision.models.resnet50(pretrained=True)


# ---------------------------------------------------------------------------
# text datasets
# ---------------------------------------------------------------------------

def test_uci_housing(tmp_path):
    rng = np.random.RandomState(2)
    data = rng.uniform(1, 10, (50, 14)).astype(np.float32)
    p = tmp_path / "housing.data"
    with open(p, "w") as f:
        for row in data:
            f.write(" ".join(f"{v:.4f}" for v in row) + "\n")
    train = pt.text.UCIHousing(data_file=str(p), mode="train")
    test = pt.text.UCIHousing(data_file=str(p), mode="test")
    assert len(train) == 40 and len(test) == 10
    x, y = train[0]
    assert x.shape == (13,) and y.shape == (1,)
    # features normalized: |x| bounded by ~1
    assert np.abs(x).max() <= 1.0 + 1e-5


def _write_imdb(path):
    import io

    docs = {
        "train/pos/0.txt": b"good good movie " * 60,
        "train/neg/0.txt": b"bad bad movie " * 60,
        "test/pos/0.txt": b"good film",
        "test/neg/0.txt": b"bad film",
    }
    with tarfile.open(path, "w:gz") as tar:
        for name, content in docs.items():
            info = tarfile.TarInfo(f"aclImdb/{name}")
            info.size = len(content)
            tar.addfile(info, io.BytesIO(content))


def test_imdb(tmp_path):
    p = str(tmp_path / "aclImdb_v1.tar.gz")
    _write_imdb(p)
    ds = pt.text.Imdb(data_file=p, mode="train", cutoff=50)
    assert len(ds) == 2
    assert "good" in ds.word_idx and "movie" in ds.word_idx
    doc, label = ds[0]
    assert doc.dtype == np.int64
    # reference polarity: pos docs first with label 0, then neg with 1
    assert ds[0][1] == 0 and ds[1][1] == 1
    good = ds.word_idx["good"]
    assert good in ds[0][0]  # first doc is the positive review


def _write_ptb(path):
    import io

    lines = {"train": "the cat sat on the mat\nthe dog sat\n" * 30,
             "test": "the cat ran\n"}
    with tarfile.open(path, "w:gz") as tar:
        for which, text in lines.items():
            content = text.encode()
            info = tarfile.TarInfo(
                f"./simple-examples/data/ptb.{which}.txt")
            info.size = len(content)
            tar.addfile(info, io.BytesIO(content))


def test_imikolov(tmp_path):
    p = str(tmp_path / "simple-examples.tgz")
    _write_ptb(p)
    ds = pt.text.Imikolov(data_file=p, data_type="NGRAM", window_size=3,
                          min_word_freq=5)
    assert len(ds) > 0
    gram = ds[0]
    assert gram.shape == (3,)
    seq = pt.text.Imikolov(data_file=p, data_type="SEQ",
                           min_word_freq=5, mode="test")
    src, trg = seq[0]
    assert len(src) == len(trg)
    np.testing.assert_array_equal(src[1:], trg[:-1])


def test_movielens(tmp_path):
    d = tmp_path / "ml-1m"
    d.mkdir()
    (d / "users.dat").write_text(
        "1::M::25::4::12345\n2::F::35::7::67890\n")
    (d / "movies.dat").write_text(
        "10::Movie A (1990)::Comedy|Drama\n20::Movie B (1995)::Action\n")
    (d / "ratings.dat").write_text(
        "1::10::5::978300760\n2::20::3::978302109\n"
        "1::20::4::978301968\n")
    ds = pt.text.Movielens(data_file=str(d), mode="train",
                           test_ratio=0.0)
    assert len(ds) == 3
    feat, rating = ds[0]
    assert feat.shape == (5,) and feat.dtype == np.int64
    assert rating in (5.0, 3.0, 4.0)
    assert ds.movie_info[10].categories == ["Comedy", "Drama"]
    assert ds.user_info[2].is_male is False


def _tar_add(tar, name, content):
    import io

    data = content.encode() if isinstance(content, str) else content
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tar.addfile(info, io.BytesIO(data))


def test_wmt14(tmp_path):
    p = str(tmp_path / "wmt14.tgz")
    with tarfile.open(p, "w:gz") as tar:
        _tar_add(tar, "wmt14/src.dict",
                 "<s>\n<e>\n<unk>\nhello\nworld\n")
        _tar_add(tar, "wmt14/trg.dict",
                 "<s>\n<e>\n<unk>\nbonjour\nmonde\n")
        _tar_add(tar, "wmt14/train/train",
                 "hello world\tbonjour monde\n"
                 "hello novel\tbonjour inconnu\n")
        _tar_add(tar, "wmt14/test/test", "world\tmonde\n")
    ds = pt.text.WMT14(data_file=p, mode="train")
    assert len(ds) == 2
    src, trg, trg_next = ds[0]
    # <s> hello world <e>
    np.testing.assert_array_equal(src, [0, 3, 4, 1])
    np.testing.assert_array_equal(trg, [0, 3, 4])
    np.testing.assert_array_equal(trg_next, [3, 4, 1])
    # unknown words map to UNK_IDX=2
    assert ds[1][0][2] == 2 and ds[1][1][2] == 2
    assert len(pt.text.WMT14(data_file=p, mode="test")) == 1


def test_wmt16(tmp_path):
    p = str(tmp_path / "wmt16.tar.gz")
    train = "the cat\tdie katze\nthe dog\tder hund\n" * 3
    with tarfile.open(p, "w:gz") as tar:
        _tar_add(tar, "wmt16/train", train)
        _tar_add(tar, "wmt16/val", "the cat\tdie katze\n")
        _tar_add(tar, "wmt16/test", "a bird\tein vogel\n")
    ds = pt.text.WMT16(data_file=p, mode="val", src_dict_size=100,
                       trg_dict_size=100)
    assert len(ds) == 1
    src, trg, trg_next = ds[0]
    sd, td = ds.src_dict, ds.trg_dict
    np.testing.assert_array_equal(
        src, [sd["<s>"], sd["the"], sd["cat"], sd["<e>"]])
    np.testing.assert_array_equal(
        trg_next, [td["die"], td["katze"], sd["<e>"]])
    # unknown words in test -> <unk>
    t = pt.text.WMT16(data_file=p, mode="test")
    assert (np.asarray(t[0][0][1:-1]) == sd["<unk>"]).all()


def test_conll05st(tmp_path):
    import gzip as _gz

    words = "The\ncat\nsat\n\nDogs\nbark\n\n"
    # one predicate column per sentence: verb 'sat' spans (V*) at row 2
    props = ("-  (A0*\n-  *)\nsat  (V*)\n\n"
             "-  (A0*)\nbark  (V*)\n\n")
    p = str(tmp_path / "conll05st-tests.tar.gz")
    with tarfile.open(p, "w:gz") as tar:
        _tar_add(tar, "conll05st-release/test.wsj/words/"
                      "test.wsj.words.gz", _gz.compress(words.encode()))
        _tar_add(tar, "conll05st-release/test.wsj/props/"
                      "test.wsj.props.gz", _gz.compress(props.encode()))
    ds = pt.text.Conll05st(data_file=p)
    assert len(ds) == 2
    word_ids, verb_id, mark, labels = ds[0]
    assert verb_id == ds.predicate_dict["sat"]
    assert len(word_ids) == 3 and len(labels) == 3
    inv = {v: k for k, v in ds.label_dict.items()}
    assert [inv[l] for l in labels] == ["B-A0", "I-A0", "B-V"]
    # +/-2 window around verb index 2 (reference conll05.py:160-184)
    np.testing.assert_array_equal(mark, [1, 1, 1])
    word_ids2, verb_id2, mark2, labels2 = ds[1]
    assert verb_id2 == ds.predicate_dict["bark"]
    assert [inv[l] for l in labels2] == ["B-A0", "B-V"]
    np.testing.assert_array_equal(mark2, [1, 1])
