"""Pipeline-parallel tests (reference: test_pipeline.py +
test_fleet_pipeline_meta_optimizer.py)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, optimizer
from paddle_tpu.distributed import fleet
from paddle_tpu.framework.core import reset_unique_name
from paddle_tpu.ops.registry import reset_op_seed


def _build(pipeline, microbatches=4):
    reset_op_seed()
    reset_unique_name()
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        x = layers.data("x", [16, 8], append_batch_size=False)
        y = layers.data("y", [16, 1], dtype="int64",
                        append_batch_size=False)
        with pt.device_guard("gpu:0"):
            h = layers.fc(x, 32, act="relu")
        with pt.device_guard("gpu:1"):
            logits = layers.fc(h, 4)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, y))
        if pipeline:
            opt = optimizer.PipelineOptimizer(
                optimizer.SGDOptimizer(0.1),
                num_microbatches=microbatches)
        else:
            opt = optimizer.SGDOptimizer(0.1)
        opt.minimize(loss)
    return main, startup, loss


def test_device_guard_tags_stages():
    main, _, _ = _build(pipeline=True)
    stages = {op.attr("__stage__") for op in main.global_block().ops
              if op.attr("__stage__") is not None}
    assert stages == {0, 1}
    assert main._pipeline == {"num_microbatches": 4, "num_stages": 2}


def test_pipeline_matches_plain_param_trajectory():
    """GPipe flush on M equal microbatches == plain full-batch step: the
    parameter trajectories must coincide (reference SectionWorker
    correctness criterion)."""
    rng = np.random.RandomState(0)
    xv = rng.rand(16, 8).astype("float32")
    yv = rng.randint(0, 4, (16, 1)).astype("int64")
    params = []
    for pipe in (False, True):
        main, startup, loss = _build(pipe)
        exe = pt.Executor()
        scope = pt.Scope()
        exe.run(startup, scope=scope)
        for _ in range(5):
            exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss],
                    scope=scope)
        names = sorted(p.name for p in main.global_block().all_parameters())
        params.append([np.asarray(scope.find_var(n)) for n in names])
    for a, b in zip(*params):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_pipeline_batch_not_divisible_raises():
    main, startup, loss = _build(pipeline=True, microbatches=3)
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    try:
        exe.run(main, feed={"x": np.zeros((16, 8), "float32"),
                            "y": np.zeros((16, 1), "int64")},
                fetch_list=[loss], scope=scope)
        raised = False
    except ValueError as e:
        raised = "not divisible" in str(e)
    assert raised


def test_fleet_pipeline_meta_optimizer():
    fleet.init(is_collective=True)
    main, startup = pt.default_main_program(), pt.default_startup_program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [8, 4], append_batch_size=False)
        with pt.device_guard("gpu:0"):
            h = layers.fc(x, 8, act="relu")
        with pt.device_guard("gpu:1"):
            loss = layers.mean(layers.fc(h, 2))
        s = fleet.DistributedStrategy()
        s.pipeline = True
        s.pipeline_configs = {"accumulate_steps": 2}
        fopt = fleet.distributed_optimizer(optimizer.SGDOptimizer(0.1), s)
        fopt.minimize(loss)
    assert main._pipeline["num_microbatches"] == 2
    assert "PipelineOptimizer" in \
        fleet.fleet_instance()._applied_meta_optimizers
