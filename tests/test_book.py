"""The reference's canonical `tests/book/` suite rebuilt end-to-end
(VERDICT r3 #3): each model trains through the PUBLIC API to a loss-drop
assertion. Machine translation lives in tests/test_beam_search.py.

Data is synthetic but dataset-shaped (zero-egress environment): the
point of the book suite is that the components COMPOSE — graph builder,
layers, optimizers, executor — exactly as the reference's book models
do. Reference: python/paddle/fluid/tests/book/*.py.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, optimizer


def _train(main_p, startup, feed_fn, loss, steps, scope=None, lr_opt=None):
    exe = pt.Executor()
    scope = scope or pt.Scope()
    exe.run(startup, scope=scope)
    losses = []
    for i in range(steps):
        l, = exe.run(main_p, feed=feed_fn(i), fetch_list=[loss],
                     scope=scope)
        losses.append(float(np.asarray(l).reshape(-1)[0]))
    return losses, exe, scope


# ---------------------------------------------------------------------------
# 1. fit_a_line (UCIHousing linear regression, book/test_fit_a_line.py)
# ---------------------------------------------------------------------------

def test_book_fit_a_line():
    rng = np.random.RandomState(0)
    true_w = rng.randn(13, 1).astype("float32")
    main_p, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main_p, startup):
        x = layers.data("x", [13])
        y = layers.data("y", [1])
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        optimizer.SGDOptimizer(learning_rate=0.01).minimize(loss)

    def feed(i):
        xv = rng.randn(32, 13).astype("float32")
        return {"x": xv, "y": xv @ true_w + 0.01 *
                rng.randn(32, 1).astype("float32")}

    losses, _, _ = _train(main_p, startup, feed, loss, 80)
    assert losses[-1] < 0.1 * losses[0], (losses[0], losses[-1])


# ---------------------------------------------------------------------------
# 2. recognize_digits (MNIST conv net, book/test_recognize_digits.py)
# ---------------------------------------------------------------------------

def test_book_recognize_digits():
    rng = np.random.RandomState(0)
    B = 32
    yv = rng.randint(0, 10, (B, 1)).astype("int64")
    # separable synthetic digits: class-dependent intensity pattern
    xv = (yv.reshape(B, 1, 1, 1) / 10.0
          + 0.1 * rng.randn(B, 1, 28, 28)).astype("float32")
    main_p, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main_p, startup):
        img = layers.data("img", [1, 28, 28])
        label = layers.data("label", [1], dtype="int64")
        # the reference's conv_pool x2 + fc topology
        c1 = layers.pool2d(layers.conv2d(img, 20, 5, act="relu"),
                           pool_size=2, pool_stride=2)
        c2 = layers.pool2d(layers.conv2d(c1, 50, 5, act="relu"),
                           pool_size=2, pool_stride=2)
        logits = layers.fc(c2, size=10)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        optimizer.AdamOptimizer(1e-3).minimize(loss)
    losses, exe, scope = _train(main_p, startup,
                                lambda i: {"img": xv, "label": yv},
                                loss, 40)
    assert losses[-1] < 0.35 * losses[0], (losses[0], losses[-1])


# ---------------------------------------------------------------------------
# 3. image_classification (CIFAR ResNet, book/test_image_classification.py)
# ---------------------------------------------------------------------------

def test_book_image_classification_resnet():
    from paddle_tpu.models import resnet

    rng = np.random.RandomState(0)
    B = 16
    yv = rng.randint(0, 10, (B, 1)).astype("int64")
    xv = (yv.reshape(B, 1, 1, 1) / 10.0
          + 0.1 * rng.randn(B, 3, 32, 32)).astype("float32")
    main_p, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main_p, startup):
        img = layers.data("img", [3, 32, 32])
        label = layers.data("label", [1], dtype="int64")
        out = resnet(img, label=label, depth=18, class_num=10)
        loss = out["loss"]
        optimizer.AdamOptimizer(1e-3).minimize(loss)
    losses, _, _ = _train(main_p, startup,
                          lambda i: {"img": xv, "label": yv}, loss, 30)
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])


# ---------------------------------------------------------------------------
# 4. understand_sentiment (Imdb stacked LSTM,
#    book/notest_understand_sentiment.py)
# ---------------------------------------------------------------------------

def test_book_understand_sentiment_lstm():
    rng = np.random.RandomState(0)
    V, B, T = 50, 16, 12
    GOOD, BAD = 7, 13
    xv = rng.randint(0, V, (B, T)).astype("int64")
    half = B // 2
    xv[:half, rng.randint(0, T)] = GOOD
    xv[half:, rng.randint(0, T)] = BAD
    xv[:half][xv[:half] == BAD] = 0
    xv[half:][xv[half:] == GOOD] = 0
    yv = np.array([[1]] * half + [[0]] * half, "int64")
    lens = np.full((B,), T, "int64")
    main_p, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main_p, startup):
        words = layers.data("words", [B, T], dtype="int64",
                            append_batch_size=False)
        ln = layers.data("ln", [B], dtype="int64", append_batch_size=False)
        label = layers.data("label", [B, 1], dtype="int64",
                            append_batch_size=False)
        emb = layers.embedding(words, size=[V, 32])
        out1, h1, _ = layers.lstm(emb, 32, lengths=ln)
        out2, h2, _ = layers.lstm(out1, 32, lengths=ln)
        feat = layers.concat([h1, h2], axis=1)
        logits = layers.fc(feat, size=2)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        optimizer.AdamOptimizer(5e-3).minimize(loss)
    losses, _, _ = _train(
        main_p, startup,
        lambda i: {"words": xv, "ln": lens, "label": yv}, loss, 50)
    assert losses[-1] < 0.3 * losses[0], (losses[0], losses[-1])


# ---------------------------------------------------------------------------
# 5. word2vec (Imikolov N-gram, book/test_word2vec.py) — reference CE
#    head plus the hsigmoid/NCE variants (VERDICT r3 #4 models)
# ---------------------------------------------------------------------------

def _word2vec_case(head):
    rng = np.random.RandomState(0)
    V, E, B = 40, 16, 64
    ctx = rng.randint(0, V, (B, 4)).astype("int64")
    nxt = ((ctx.sum(1) * 3 + 1) % V).astype("int64")[:, None]
    main_p, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main_p, startup):
        words = [layers.data(n, [B], dtype="int64",
                             append_batch_size=False)
                 for n in ("firstw", "secondw", "thirdw", "forthw")]
        nextw = layers.data("nextw", [B, 1], dtype="int64",
                            append_batch_size=False)
        embs = [layers.embedding(
            w, size=[V, E], param_attr=pt.ParamAttr(name="shared_emb"))
            for w in words]
        concat = layers.concat(embs, axis=1)
        hidden = layers.fc(concat, size=64, act="sigmoid",
                           num_flatten_dims=1)
        if head == "softmax":
            logits = layers.fc(hidden, size=V)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, nextw))
        elif head == "hsigmoid":
            loss = layers.mean(
                layers.hsigmoid(hidden, nextw, num_classes=V))
        else:
            loss = layers.mean(
                layers.nce(hidden, nextw, num_total_classes=V,
                           num_neg_samples=8, sampler=1))
        optimizer.AdamOptimizer(1e-2).minimize(loss)

    def feed(i):
        return {"firstw": ctx[:, 0], "secondw": ctx[:, 1],
                "thirdw": ctx[:, 2], "forthw": ctx[:, 3], "nextw": nxt}

    return _train(main_p, startup, feed, loss, 80)[0]


@pytest.mark.parametrize("head", ["softmax", "hsigmoid", "nce"])
def test_book_word2vec(head):
    losses = _word2vec_case(head)
    assert losses[-1] < 0.5 * losses[0], (head, losses[0], losses[-1])


# ---------------------------------------------------------------------------
# 6. recommender_system (Movielens two towers + cos_sim,
#    book/test_recommender_system.py)
# ---------------------------------------------------------------------------

def test_book_recommender_system():
    rng = np.random.RandomState(0)
    B, NU, NM = 32, 20, 15
    uid = rng.randint(0, NU, (B,)).astype("int64")
    mid = rng.randint(0, NM, (B,)).astype("int64")
    affinity = np.sin(uid * 0.7) * np.cos(mid * 1.3)
    score = (2.5 + 2.5 * affinity).astype("float32")[:, None]
    main_p, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main_p, startup):
        u = layers.data("uid", [B], dtype="int64", append_batch_size=False)
        m = layers.data("mid", [B], dtype="int64", append_batch_size=False)
        y = layers.data("score", [B, 1], append_batch_size=False)
        usr = layers.fc(layers.fc(layers.embedding(u, size=[NU, 32]),
                                  size=32), size=32, act="tanh",
                        num_flatten_dims=1)
        mov = layers.fc(layers.fc(layers.embedding(m, size=[NM, 32]),
                                  size=32), size=32, act="tanh",
                        num_flatten_dims=1)
        sim = layers.cos_sim(usr, mov)
        pred = layers.scale(sim, scale=5.0)
        loss = layers.mean(layers.square_error_cost(pred, y))
        optimizer.AdamOptimizer(5e-3).minimize(loss)
    losses, _, _ = _train(
        main_p, startup,
        lambda i: {"uid": uid, "mid": mid, "score": score}, loss, 80)
    assert losses[-1] < 0.3 * losses[0], (losses[0], losses[-1])


# ---------------------------------------------------------------------------
# 7. label_semantic_roles (Conll05st BiLSTM-CRF,
#    book/test_label_semantic_roles.py)
# ---------------------------------------------------------------------------

def test_book_label_semantic_roles_crf():
    rng = np.random.RandomState(0)
    V, B, T, NTAG = 30, 8, 10, 5
    xv = rng.randint(0, V, (B, T)).astype("int64")
    # learnable tagging rule: tag = word mod NTAG
    yv = (xv % NTAG).astype("int64")
    lens = np.array([T, T, T - 2, T - 3, T, T - 1, T, 4], "int64")
    main_p, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main_p, startup):
        words = layers.data("words", [B, T], dtype="int64",
                            append_batch_size=False)
        ln = layers.data("ln", [B], dtype="int64", append_batch_size=False)
        tags = layers.data("tags", [B, T], dtype="int64",
                           append_batch_size=False)
        emb = layers.embedding(words, size=[V, 32])
        hidden, _, _ = layers.lstm(emb, 32, lengths=ln)
        emission = layers.fc(hidden, size=NTAG, num_flatten_dims=2)
        nll = layers.linear_chain_crf(
            emission, tags, ln, param_attr=pt.ParamAttr(name="srl_crf"))
        loss = layers.mean(nll)
        optimizer.AdamOptimizer(1e-2).minimize(loss)

    test_p = main_p.clone(for_test=True)

    losses, exe, scope = _train(
        main_p, startup,
        lambda i: {"words": xv, "ln": lens, "tags": yv}, loss, 120)
    assert losses[-1] < 0.25 * losses[0], (losses[0], losses[-1])

    # viterbi decode: fetch emissions from the test clone, then run a
    # decoding-only program whose crf_decoding shares the trained
    # transition by name (already in scope — its startup is never run)
    em_vals, = exe.run(test_p, feed={"words": xv, "ln": lens, "tags": yv},
                       fetch_list=[emission.name], scope=scope)
    dec_p, dec_start = pt.Program(), pt.Program()
    dec_start._is_startup = True
    with pt.program_guard(dec_p, dec_start):
        e = layers.data("e", [B, T, NTAG], append_batch_size=False)
        ln2 = layers.data("ln", [B], dtype="int64",
                          append_batch_size=False)
        path = layers.crf_decoding(
            e, ln2, param_attr=pt.ParamAttr(name="srl_crf"))
    got, = exe.run(dec_p, feed={"e": np.asarray(em_vals), "ln": lens},
                   fetch_list=[path], scope=scope)
    got = np.asarray(got)
    # tag accuracy over valid positions must beat chance by a wide margin
    correct = total = 0
    for b in range(B):
        L = int(lens[b])
        correct += (got[b, :L] == yv[b, :L]).sum()
        total += L
    acc = correct / total
    assert acc > 0.8, f"viterbi tag accuracy {acc:.2f}"
