"""Per-op tests: forward vs numpy reference + grads vs central finite
differences, over the whole op registry.

Reference: the per-op OpTest suites under tests/unittests/test_*_op.py
(driven by op_test.py).  The coverage gate at the bottom guarantees every
registered op is either exercised here or skip-listed with the test file
that covers it.
"""
import math

import numpy as np
import pytest

import paddle_tpu as pt
from op_test import OpCase, check_forward, check_grad, run_case

R = np.random.RandomState


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


# ---------------------------------------------------------------------------
# unary elementwise
# ---------------------------------------------------------------------------
_POS = R(0).uniform(0.3, 2.0, (3, 4)).astype("float32")
_SYM = R(1).uniform(-2.0, 2.0, (3, 4)).astype("float32")
_UNIT = R(2).uniform(-0.9, 0.9, (3, 4)).astype("float32")
# keep points away from kinks (relu at 0, round at .5) for finite diffs
_OFF = (_SYM + np.where(np.abs(_SYM) < 0.15, 0.3, 0.0)).astype("float32")

UNARY = {
    "abs": (np.abs, _OFF, True),
    "acos": (np.arccos, _UNIT, True),
    "asin": (np.arcsin, _UNIT, True),
    "atan": (np.arctan, _SYM, True),
    "ceil": (np.ceil, _OFF, False),
    "cos": (np.cos, _SYM, True),
    "cosh": (np.cosh, _SYM, True),
    "erf": (np.vectorize(math.erf), _SYM, True),
    "exp": (np.exp, _SYM, True),
    "floor": (np.floor, _OFF, False),
    "log": (np.log, _POS, True),
    "log2": (np.log2, _POS, True),
    "log10": (np.log10, _POS, True),
    "log1p": (np.log1p, _POS, True),
    "logsigmoid": (lambda x: np.log(_sigmoid(x)), _SYM, True),
    "reciprocal": (lambda x: 1.0 / x, _POS, True),
    "relu": (lambda x: np.maximum(x, 0), _OFF, True),
    "relu6": (lambda x: np.clip(x, 0, 6), _OFF, True),
    "round": (np.round, _OFF, False),
    "rsqrt": (lambda x: 1.0 / np.sqrt(x), _POS, True),
    "sigmoid": (_sigmoid, _SYM, True),
    "sign": (np.sign, _OFF, False),
    "silu": (lambda x: x * _sigmoid(x), _SYM, True),
    "sin": (np.sin, _SYM, True),
    "sinh": (np.sinh, _SYM, True),
    "softplus": (lambda x: np.log1p(np.exp(x)), _SYM, True),
    "softsign": (lambda x: x / (1 + np.abs(x)), _OFF, True),
    "sqrt": (np.sqrt, _POS, True),
    "square": (np.square, _SYM, True),
    "tan": (np.tan, _UNIT, True),
    "tanh": (np.tanh, _SYM, True),
    "gelu": (lambda x: x * 0.5 * (1 + np.vectorize(math.erf)(
        x / np.sqrt(2))), _SYM, True),
    "elu": (lambda x: np.where(x > 0, x, np.expm1(x)), _OFF, True),
    "mish": (lambda x: x * np.tanh(np.log1p(np.exp(x))), _SYM, True),
    "swish": (lambda x: x * _sigmoid(x), _SYM, True),
    "hard_sigmoid": (lambda x: np.clip(0.2 * x + 0.5, 0, 1), _OFF, False),
    "hard_swish": (lambda x: x * np.clip(x + 3, 0, 6) / 6, _OFF, True),
    "softshrink": (lambda x: np.where(x > 0.5, x - 0.5,
                                      np.where(x < -0.5, x + 0.5, 0)),
                   _OFF, False),
}


@pytest.mark.parametrize("op", sorted(UNARY))
def test_unary(op):
    fn, data, do_grad = UNARY[op]
    run_case(OpCase(op, {"X": data}, ref=lambda X: fn(X),
                    grad=["X"] if do_grad else [], rtol=2e-5, atol=2e-6))


def test_leaky_relu_and_prelu():
    run_case(OpCase("leaky_relu", {"X": _OFF}, attrs={"alpha": 0.1},
                    ref=lambda X, alpha: np.where(X > 0, X, alpha * X),
                    grad=["X"]))
    alpha = np.full((1,), 0.25, "float32")
    run_case(OpCase("prelu", {"X": _OFF, "Alpha": alpha},
                    attrs={"mode": "all"},
                    ref=lambda X, Alpha, mode: np.where(X > 0, X,
                                                        Alpha * X),
                    grad=["X", "Alpha"]))


def test_scale_clip_increment_assign_cast():
    run_case(OpCase("scale", {"X": _SYM},
                    attrs={"scale": 2.0, "bias": 1.0},
                    ref=lambda X, scale, bias: scale * X + bias,
                    grad=["X"]))
    run_case(OpCase("clip", {"X": _SYM}, attrs={"min": -1.0, "max": 1.0},
                    ref=lambda X, min, max: np.clip(X, min, max)))
    run_case(OpCase("assign", {"X": _SYM}, ref=lambda X: X, grad=["X"]))
    run_case(OpCase("share_data", {"X": _SYM}, ref=lambda X: X))
    run_case(OpCase("cast", {"X": _SYM},
                    attrs={"out_dtype": "int32"},
                    ref=lambda X, out_dtype: X.astype("int32"),
                    check_dtype=False))
    run_case(OpCase("logsumexp", {"X": _SYM},
                    attrs={"dim": [-1], "keep_dim": False},
                    ref=lambda X, dim, keep_dim: np.log(
                        np.exp(X).sum(-1)), grad=["X"]))
    run_case(OpCase("pow", {"X": _POS}, attrs={"factor": 2.5},
                    ref=lambda X, factor: X ** 2.5, grad=["X"],
                    rtol=1e-4, atol=1e-5))
    run_case(OpCase("maxout", {"X": R(3).rand(2, 4, 3, 3).astype(
        "float32")}, attrs={"groups": 2, "axis": 1},
        ref=lambda X, groups, axis: X.reshape(2, 2, 2, 3, 3).max(2)))


def test_finite_checks():
    x = np.array([1.0, np.inf, -np.inf, np.nan, 3.0], "float32")
    run_case(OpCase("isfinite_v2", {"X": x}, ref=lambda X: np.isfinite(X),
                    check_dtype=False))
    run_case(OpCase("isinf_v2", {"X": x}, ref=lambda X: np.isinf(X),
                    check_dtype=False))
    run_case(OpCase("isnan_v2", {"X": x}, ref=lambda X: np.isnan(X),
                    check_dtype=False))


# ---------------------------------------------------------------------------
# binary elementwise + comparisons + logicals
# ---------------------------------------------------------------------------
_A = R(4).uniform(0.5, 2.0, (3, 4)).astype("float32")
_B = R(5).uniform(0.5, 2.0, (3, 4)).astype("float32")
_BCOL = R(6).uniform(0.5, 2.0, (4,)).astype("float32")

BINARY = {
    "elementwise_add": (np.add, True),
    "elementwise_sub": (np.subtract, True),
    "elementwise_mul": (np.multiply, True),
    "elementwise_div": (np.divide, True),
    "elementwise_max": (np.maximum, True),
    "elementwise_min": (np.minimum, True),
    "elementwise_pow": (np.power, True),
    "elementwise_mod": (np.mod, False),
    "elementwise_floordiv": (np.floor_divide, False),
}


@pytest.mark.parametrize("op", sorted(BINARY))
def test_binary(op):
    fn, do_grad = BINARY[op]
    run_case(OpCase(op, {"X": _A, "Y": _B}, ref=lambda X, Y: fn(X, Y),
                    grad=["X", "Y"] if do_grad else [], rtol=2e-5,
                    atol=2e-6))


def test_binary_broadcast_axis():
    run_case(OpCase("elementwise_add", {"X": _A, "Y": _BCOL},
                    attrs={"axis": -1},
                    ref=lambda X, Y, axis: X + Y, grad=["X", "Y"]))


COMPARE = {"equal": np.equal, "not_equal": np.not_equal,
           "less_than": np.less, "less_equal": np.less_equal,
           "greater_than": np.greater, "greater_equal": np.greater_equal}


@pytest.mark.parametrize("op", sorted(COMPARE))
def test_compare(op):
    a = np.array([[1, 2], [3, 4]], "float32")
    b = np.array([[1, 3], [2, 4]], "float32")
    run_case(OpCase(op, {"X": a, "Y": b},
                    ref=lambda X, Y: COMPARE[op](X, Y),
                    check_dtype=False))


LOGICAL = {"logical_and": np.logical_and, "logical_or": np.logical_or,
           "logical_xor": np.logical_xor}


@pytest.mark.parametrize("op", sorted(LOGICAL))
def test_logical(op):
    a = np.array([True, True, False, False])
    b = np.array([True, False, True, False])
    run_case(OpCase(op, {"X": a, "Y": b},
                    ref=lambda X, Y: LOGICAL[op](X, Y),
                    check_dtype=False))


def test_logical_not():
    run_case(OpCase("logical_not", {"X": np.array([True, False])},
                    ref=lambda X: ~X, check_dtype=False))


# ---------------------------------------------------------------------------
# matmul family
# ---------------------------------------------------------------------------
def test_matmul_family():
    x = R(7).rand(3, 4).astype("float32")
    y = R(8).rand(4, 5).astype("float32")
    run_case(OpCase("matmul", {"X": x, "Y": y},
                    ref=lambda X, Y: X @ Y, grad=["X", "Y"],
                    rtol=1e-4, atol=1e-5))
    run_case(OpCase("matmul_v2", {"X": x, "Y": y},
                    ref=lambda X, Y: X @ Y, grad=["X", "Y"],
                    rtol=1e-4, atol=1e-5))
    run_case(OpCase("matmul", {"X": x.T.copy(), "Y": y},
                    attrs={"transpose_X": True},
                    ref=lambda X, Y, transpose_X: X.T @ Y,
                    rtol=1e-4, atol=1e-5))
    run_case(OpCase("mul", {"X": x, "Y": y},
                    attrs={"x_num_col_dims": 1, "y_num_col_dims": 1},
                    ref=lambda X, Y, **kw: X @ Y, grad=["X", "Y"],
                    rtol=1e-4, atol=1e-5))
    bx = R(9).rand(2, 3, 4).astype("float32")
    by = R(10).rand(2, 4, 5).astype("float32")
    run_case(OpCase("bmm", {"X": bx, "Y": by},
                    ref=lambda X, Y: X @ Y, grad=["X", "Y"],
                    rtol=1e-4, atol=1e-5))
    run_case(OpCase("dot", {"X": x[0], "Y": x[1]},
                    ref=lambda X, Y: np.array(np.dot(X, Y)),
                    grad=["X", "Y"], rtol=1e-4, atol=1e-5))


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------
def test_reductions():
    x = R(11).rand(2, 3, 4).astype("float32") + 0.1
    for op, fn in [("reduce_sum", np.sum), ("reduce_mean", np.mean),
                   ("reduce_max", np.max), ("reduce_min", np.min),
                   ("reduce_prod", np.prod)]:
        grad = ["X"] if op in ("reduce_sum", "reduce_mean") else []
        run_case(OpCase(op, {"X": x}, attrs={"dim": [1],
                                             "keep_dim": False},
                        ref=lambda X, dim, keep_dim, fn=fn: fn(X, axis=1),
                        grad=grad, rtol=1e-4, atol=1e-5))
    run_case(OpCase("reduce_sum", {"X": x},
                    attrs={"dim": [0], "keep_dim": True},
                    ref=lambda X, dim, keep_dim: X.sum(0, keepdims=True),
                    rtol=1e-4, atol=1e-5))
    run_case(OpCase("mean", {"X": x},
                    ref=lambda X: np.array(X.mean(), "float32"),
                    grad=["X"]))
    run_case(OpCase("max", {"X": x}, attrs={"dim": [-1]},
                    ref=lambda X, dim: X.max(-1)))
    run_case(OpCase("min", {"X": x}, attrs={"dim": [-1]},
                    ref=lambda X, dim: X.min(-1)))
    run_case(OpCase("sum", {"X": [_A, _B, _A]},
                    ref=lambda X: X[0] + X[1] + X[2], grad=["X"]))
    b = np.array([[True, False], [True, True]])
    run_case(OpCase("reduce_all", {"X": b}, attrs={"dim": [1]},
                    ref=lambda X, dim: X.all(1), check_dtype=False))
    run_case(OpCase("reduce_any", {"X": b}, attrs={"dim": [1]},
                    ref=lambda X, dim: X.any(1), check_dtype=False))
    run_case(OpCase("squared_l2_norm", {"X": _A},
                    ref=lambda X: np.array((X ** 2).sum(), "float32"),
                    grad=["X"], rtol=1e-4, atol=1e-5))
    run_case(OpCase("cumsum", {"X": x}, attrs={"axis": 1},
                    ref=lambda X, axis: X.cumsum(1), grad=["X"],
                    rtol=1e-4, atol=1e-5))


def test_norms():
    x = _A
    run_case(OpCase("norm", {"X": x}, outputs={"Out": 1, "Norm": 1},
                    attrs={"axis": 1, "epsilon": 1e-10},
                    ref=lambda X, axis, epsilon: {
                        "Out": X / np.sqrt((X ** 2).sum(1, keepdims=True)
                                           + epsilon)},
                    grad=["X"], rtol=1e-4, atol=1e-5))
    run_case(OpCase("p_norm", {"X": x},
                    attrs={"porder": 2.0, "axis": 1, "keepdim": False,
                           "epsilon": 1e-12},
                    ref=lambda X, porder, axis, keepdim, epsilon:
                    np.sqrt((X ** 2).sum(1)), grad=["X"],
                    rtol=1e-4, atol=1e-5))
    run_case(OpCase("clip_by_norm", {"X": x}, attrs={"max_norm": 1.0},
                    ref=lambda X, max_norm: X * min(
                        1.0, max_norm / np.sqrt((X ** 2).sum()))))


# ---------------------------------------------------------------------------
# shape / indexing ops
# ---------------------------------------------------------------------------
def test_shape_ops():
    x = R(12).rand(2, 3, 4).astype("float32")
    run_case(OpCase("reshape2", {"X": x},
                    outputs={"Out": 1, "XShape": 1},
                    attrs={"shape": [6, 4]},
                    ref=lambda X, shape: {"Out": X.reshape(6, 4)},
                    grad=["X"]))
    run_case(OpCase("transpose2", {"X": x},
                    outputs={"Out": 1, "XShape": 1},
                    attrs={"axis": [2, 0, 1]},
                    ref=lambda X, axis: {"Out": X.transpose(2, 0, 1)},
                    grad=["X"]))
    run_case(OpCase("concat", {"X": [_A, _B]}, attrs={"axis": 1},
                    ref=lambda X, axis: np.concatenate(X, 1),
                    grad=["X"]))
    run_case(OpCase("split", {"X": _A}, outputs={"Out": 2},
                    attrs={"num": 2, "axis": 1},
                    ref=lambda X, num, axis: {"Out": [X[:, :2], X[:, 2:]]},
                    grad=["X"]))
    run_case(OpCase("stack", {"X": [_A, _B]}, outputs={"Y": 1},
                    attrs={"axis": 0},
                    ref=lambda X, axis: {"Y": np.stack(X)}, grad=["X"]))
    run_case(OpCase("unstack", {"X": np.stack([_A, _B])},
                    outputs={"Y": 2}, attrs={"axis": 0, "num": 2},
                    ref=lambda X, axis, num: {"Y": [X[0], X[1]]},
                    grad=["X"]))
    run_case(OpCase("squeeze2", {"X": x[:, :1]},
                    outputs={"Out": 1, "XShape": 1},
                    attrs={"axes": [1]},
                    ref=lambda X, axes: {"Out": X[:, 0]}, grad=["X"]))
    run_case(OpCase("unsqueeze2", {"X": _A},
                    outputs={"Out": 1, "XShape": 1},
                    attrs={"axes": [1]},
                    ref=lambda X, axes: {"Out": X[:, None]}, grad=["X"]))
    run_case(OpCase("squeeze", {"X": x[:, :1]}, attrs={"axes": [1]},
                    ref=lambda X, axes: X[:, 0]))
    run_case(OpCase("unsqueeze", {"X": _A}, attrs={"axes": [0]},
                    ref=lambda X, axes: X[None]))
    run_case(OpCase("reshape", {"X": x}, attrs={"shape": [4, 6]},
                    ref=lambda X, shape: X.reshape(4, 6)))
    run_case(OpCase("transpose", {"X": _A}, attrs={"axis": [1, 0]},
                    ref=lambda X, axis: X.T))
    run_case(OpCase("flatten2", {"X": x},
                    outputs={"Out": 1, "XShape": 1}, attrs={"axis": 1},
                    ref=lambda X, axis: {"Out": X.reshape(2, 12)}))
    run_case(OpCase("flatten", {"X": x}, attrs={"axis": 2},
                    ref=lambda X, axis: X.reshape(6, 4)))
    run_case(OpCase("flatten_contiguous_range", {"X": x},
                    outputs={"Out": 1, "XShape": 1},
                    attrs={"start_axis": 1, "stop_axis": 2},
                    ref=lambda X, start_axis, stop_axis:
                    {"Out": X.reshape(2, 12)}))
    run_case(OpCase("slice", {"Input": x},
                    attrs={"axes": [1], "starts": [1], "ends": [3]},
                    ref=lambda Input, axes, starts, ends: Input[:, 1:3],
                    grad=["Input"]))
    run_case(OpCase("strided_slice", {"Input": x},
                    attrs={"axes": [2], "starts": [0], "ends": [4],
                           "strides": [2]},
                    ref=lambda Input, **kw: Input[:, :, 0:4:2]))
    run_case(OpCase("pad", {"X": _A},
                    attrs={"paddings": [1, 0, 0, 2], "pad_value": 0.5},
                    ref=lambda X, paddings, pad_value: np.pad(
                        X, [(1, 0), (0, 2)], constant_values=0.5),
                    grad=["X"]))
    run_case(OpCase("tile", {"X": _A},
                    attrs={"repeat_times": [2, 1]},
                    ref=lambda X, repeat_times: np.tile(X, (2, 1))))
    run_case(OpCase("expand", {"X": _A[:1]},
                    attrs={"expand_times": [3, 1]},
                    ref=lambda X, expand_times: np.tile(X, (3, 1))))
    run_case(OpCase("expand_v2", {"X": _A[:1]},
                    attrs={"shape": [3, 4]},
                    ref=lambda X, shape: np.broadcast_to(X, (3, 4))))
    run_case(OpCase("flip", {"X": _A}, attrs={"axis": [1]},
                    ref=lambda X, axis: X[:, ::-1]))
    run_case(OpCase("roll", {"X": _A}, attrs={"shifts": [1],
                                              "axis": [0]},
                    ref=lambda X, shifts, axis: np.roll(X, 1, 0)))
    run_case(OpCase("shape", {"Input": x},
                    ref=lambda Input: np.array(Input.shape),
                    check_dtype=False))


def test_gather_scatter():
    x = R(13).rand(5, 3).astype("float32")
    idx = np.array([0, 3, 1], "int64")
    run_case(OpCase("gather", {"X": x, "Index": idx},
                    ref=lambda X, Index: X[Index], grad=["X"]))
    run_case(OpCase("index_select", {"X": x, "Index": idx},
                    attrs={"dim": 0},
                    ref=lambda X, Index, dim: X[Index]))
    nd_idx = np.array([[0, 1], [3, 2]], "int64")
    run_case(OpCase("gather_nd", {"X": x, "Index": nd_idx},
                    ref=lambda X, Index: X[Index[:, 0], Index[:, 1]],
                    grad=["X"]))
    upd = np.ones((3, 3), "float32")
    run_case(OpCase("scatter", {"X": x, "Ids": idx, "Updates": upd},
                    attrs={"overwrite": True},
                    ref=lambda X, Ids, Updates, overwrite: _scatter_ref(
                        X, Ids, Updates)))
    nd_upd = np.ones((2,), "float32")
    run_case(OpCase("scatter_nd_add",
                    {"X": x, "Index": nd_idx, "Updates": nd_upd},
                    ref=lambda X, Index, Updates: _scatter_nd_ref(
                        X, Index, Updates)))
    ta_idx = np.array([[0, 1, 0], [2, 0, 1]], "int64")
    run_case(OpCase("take_along_axis",
                    {"Input": x[:2], "Index": ta_idx},
                    outputs={"Result": 1}, attrs={"Axis": 1},
                    ref=lambda Input, Index, Axis: {
                        "Result": np.take_along_axis(Input, Index, 1)}))
    cond = np.array([[True, False], [False, True]])
    a2, b2 = _A[:2, :2], _B[:2, :2]
    run_case(OpCase("where", {"Condition": cond, "X": a2, "Y": b2},
                    ref=lambda Condition, X, Y: np.where(Condition, X, Y),
                    grad=["X", "Y"]))
    run_case(OpCase("lookup_table_v2",
                    {"W": x, "Ids": np.array([[1, 4], [0, 2]], "int64")},
                    ref=lambda W, Ids: W[Ids], grad=["W"]))
    run_case(OpCase("lookup_table",
                    {"W": x, "Ids": np.array([[1], [4]], "int64")},
                    ref=lambda W, Ids: W[Ids[:, 0]]))
    run_case(OpCase("embedding",
                    {"W": x, "Ids": np.array([2, 0], "int64")},
                    ref=lambda W, Ids: W[Ids]))


def _scatter_ref(x, ids, upd):
    out = x.copy()
    out[ids] = upd
    return out


def _scatter_nd_ref(x, index, upd):
    out = x.copy()
    for k in range(index.shape[0]):
        out[tuple(index[k])] += upd[k]
    return out


def test_structural_ops():
    x = R(31).rand(2, 4, 4).astype("float32")
    run_case(OpCase("tril_triu", {"X": x},
                    attrs={"diagonal": 0, "lower": True},
                    ref=lambda X, diagonal, lower: np.tril(X),
                    grad=["X"]))
    run_case(OpCase("tril_triu", {"X": x},
                    attrs={"diagonal": 1, "lower": False},
                    ref=lambda X, diagonal, lower: np.triu(X, 1),
                    name="triu"))
    a = np.arange(3, dtype="float32")
    b = np.arange(4, dtype="float32")
    run_case(OpCase("meshgrid", {"X": [a, b]}, outputs={"Out": 2},
                    ref=lambda X: {"Out": list(np.meshgrid(
                        X[0], X[1], indexing="ij"))}))
    run_case(OpCase("cumprod", {"X": _POS}, attrs={"dim": 1},
                    ref=lambda X, dim: np.cumprod(X, 1), grad=["X"],
                    rtol=1e-4, atol=1e-5))
    img = R(32).rand(1, 2, 4, 4).astype("float32")
    run_case(OpCase("nearest_interp", {"X": img},
                    attrs={"out_h": 8, "out_w": 8,
                           "align_corners": False},
                    ref=lambda X, out_h, out_w, align_corners: np.repeat(
                        np.repeat(X, 2, 2), 2, 3)))
    def bilinear_ref(X, out_h, out_w, align_corners):
        n, c, h, w = X.shape
        ys = np.linspace(0, h - 1, out_h) if align_corners else \
            np.clip((np.arange(out_h) + 0.5) * h / out_h - 0.5, 0, h - 1)
        xs = np.linspace(0, w - 1, out_w) if align_corners else \
            np.clip((np.arange(out_w) + 0.5) * w / out_w - 0.5, 0, w - 1)
        y0 = np.floor(ys).astype(int); x0 = np.floor(xs).astype(int)
        y1 = np.minimum(y0 + 1, h - 1); x1 = np.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[None, None, :, None]
        wx = (xs - x0)[None, None, None, :]
        g = lambda yi, xi: X[:, :, yi, :][:, :, :, xi]
        return (g(y0, x0) * (1 - wy) * (1 - wx) + g(y1, x0) * wy * (1 - wx)
                + g(y0, x1) * (1 - wy) * wx + g(y1, x1) * wy * wx
                ).astype("float32")

    for align in (True, False):
        run_case(OpCase("bilinear_interp", {"X": img},
                        attrs={"out_h": 8, "out_w": 8,
                               "align_corners": align},
                        ref=bilinear_ref, grad=["X"], rtol=1e-4,
                        atol=1e-5, name=f"bilinear_align{align}"))
    ps = R(33).rand(1, 8, 2, 2).astype("float32")

    def ps_ref(X, upscale_factor):
        n, c, h, w = X.shape
        r = upscale_factor
        o = X.reshape(n, c // (r * r), r, r, h, w)
        return o.transpose(0, 1, 4, 2, 5, 3).reshape(
            n, c // (r * r), h * r, w * r)

    run_case(OpCase("pixel_shuffle", {"X": ps},
                    attrs={"upscale_factor": 2}, ref=ps_ref, grad=["X"]))


def test_argsort_topk_onehot():
    x = R(14).rand(3, 5).astype("float32")
    run_case(OpCase("arg_max", {"X": x}, attrs={"axis": 1},
                    ref=lambda X, axis: X.argmax(1), check_dtype=False))
    run_case(OpCase("arg_min", {"X": x}, attrs={"axis": 1},
                    ref=lambda X, axis: X.argmin(1), check_dtype=False))
    run_case(OpCase("argsort", {"X": x},
                    outputs={"Out": 1, "Indices": 1}, attrs={"axis": 1},
                    ref=lambda X, axis: {"Out": np.sort(X, 1),
                                         "Indices": np.argsort(X, 1)},
                    check_dtype=False))
    run_case(OpCase("top_k_v2", {"X": x},
                    outputs={"Out": 1, "Indices": 1}, attrs={"k": 2},
                    ref=lambda X, k: {
                        "Out": np.sort(X, 1)[:, ::-1][:, :2],
                        "Indices": np.argsort(-X, 1)[:, :2]},
                    check_dtype=False))
    run_case(OpCase("top_k", {"X": x},
                    outputs={"Out": 1, "Indices": 1}, attrs={"k": 1},
                    ref=lambda X, k: {"Out": X.max(1, keepdims=True)},
                    check_dtype=False))
    ids = np.array([[1], [3]], "int64")
    run_case(OpCase("one_hot", {"X": ids}, attrs={"depth": 4},
                    ref=lambda X, depth: np.eye(4, dtype="float32")[
                        X[:, 0]], check_dtype=False))
    run_case(OpCase("one_hot_v2", {"X": ids[:, 0]}, attrs={"depth": 4},
                    ref=lambda X, depth: np.eye(4, dtype="float32")[X],
                    check_dtype=False))
    run_case(OpCase("label_smooth", {"X": np.eye(3, dtype="float32")},
                    attrs={"epsilon": 0.1},
                    ref=lambda X, epsilon: X * 0.9 + 0.1 / 3))


# ---------------------------------------------------------------------------
# creation ops (forward-only, exact)
# ---------------------------------------------------------------------------
def test_creation_ops():
    run_case(OpCase("fill_constant", {}, attrs={"shape": [2, 3],
                                                "dtype": "float32",
                                                "value": 2.5},
                    ref=lambda shape, dtype, value: np.full((2, 3), 2.5,
                                                            "float32")))
    run_case(OpCase("fill_any_like", {"X": _A}, attrs={"value": 3.0},
                    ref=lambda X, value: np.full_like(X, 3.0)))
    run_case(OpCase("fill_zeros_like", {"X": _A},
                    ref=lambda X: np.zeros_like(X)))
    run_case(OpCase("assign_value", {}, attrs={
        "shape": [2, 2], "dtype": "float32",
        "values": np.arange(4, dtype="float32")},
        ref=lambda **kw: np.arange(4, dtype="float32").reshape(2, 2)))
    run_case(OpCase("eye", {}, attrs={"num_rows": 3, "num_columns": 4,
                                      "dtype": "float32"},
                    ref=lambda **kw: np.eye(3, 4, dtype="float32")))
    run_case(OpCase("linspace", {}, attrs={"start": 0.0, "stop": 1.0,
                                           "num": 5, "dtype": "float32"},
                    ref=lambda **kw: np.linspace(0, 1, 5,
                                                 dtype="float32")))
    run_case(OpCase("range", {}, attrs={"start": 1.0, "end": 7.0,
                                        "step": 2.0, "dtype": "float32"},
                    ref=lambda **kw: np.arange(1, 7, 2, dtype="float32")))


def test_random_ops_statistics():
    got = check_forward(OpCase("gaussian_random", {}, attrs={
        "shape": [2000], "mean": 1.0, "std": 2.0, "dtype": "float32"}))
    a = np.asarray(got[0])
    assert abs(a.mean() - 1.0) < 0.2 and abs(a.std() - 2.0) < 0.2
    got = check_forward(OpCase("uniform_random", {}, attrs={
        "shape": [2000], "min": -1.0, "max": 1.0, "dtype": "float32"}))
    a = np.asarray(got[0])
    assert a.min() >= -1 and a.max() <= 1 and abs(a.mean()) < 0.1
    got = check_forward(OpCase("randint", {}, attrs={
        "shape": [1000], "low": 0, "high": 5, "dtype": "int64"}))
    a = np.asarray(got[0])
    assert a.min() >= 0 and a.max() < 5
    got = check_forward(OpCase("randperm", {}, attrs={"n": 64,
                                                      "dtype": "int64"}))
    a = np.asarray(got[0])
    assert sorted(a.tolist()) == list(range(64))
    got = check_forward(OpCase("bernoulli",
                               {"X": np.full((2000,), 0.3, "float32")}))
    a = np.asarray(got[0])
    assert set(np.unique(a)) <= {0.0, 1.0} and abs(a.mean() - 0.3) < 0.1
    got = check_forward(OpCase("truncated_gaussian_random", {}, attrs={
        "shape": [2000], "mean": 0.0, "std": 1.0, "dtype": "float32"}))
    a = np.asarray(got[0])
    assert np.abs(a).max() <= 2.0 + 1e-5


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------
def test_losses():
    logits = R(15).rand(4, 5).astype("float32")
    label = np.array([[1], [0], [4], [2]], "int64")
    onehot = np.eye(5, dtype="float32")[label[:, 0]]

    run_case(OpCase("softmax", {"X": logits},
                    ref=lambda X: _softmax(X), grad=["X"],
                    rtol=1e-4, atol=1e-5))
    run_case(OpCase("log_softmax", {"X": logits},
                    ref=lambda X: np.log(_softmax(X)), grad=["X"],
                    rtol=1e-4, atol=1e-5))
    run_case(OpCase("cross_entropy", {"X": _softmax(logits),
                                      "Label": label},
                    outputs={"Y": 1},
                    ref=lambda X, Label: {
                        "Y": -np.log(X[np.arange(4), Label[:, 0]]
                                     )[:, None]},
                    grad=["X"], rtol=1e-4, atol=1e-5))
    run_case(OpCase("softmax_with_cross_entropy",
                    {"Logits": logits, "Label": label},
                    outputs={"Softmax": 1, "Loss": 1},
                    ref=lambda Logits, Label: {
                        "Softmax": _softmax(Logits),
                        "Loss": -np.log(_softmax(Logits)[
                            np.arange(4), Label[:, 0]])[:, None]},
                    grad=["Logits"], rtol=1e-4, atol=1e-5))
    p = R(16).uniform(0.1, 0.9, (4, 1)).astype("float32")
    y = np.array([[1.0], [0.0], [1.0], [0.0]], "float32")
    run_case(OpCase("bce_loss", {"X": p, "Label": y},
                    ref=lambda X, Label: -(Label * np.log(X) + (
                        1 - Label) * np.log(1 - X)),
                    grad=["X"], rtol=1e-4, atol=1e-5))
    run_case(OpCase("sigmoid_cross_entropy_with_logits",
                    {"X": logits[:, :1], "Label": y},
                    ref=lambda X, Label: np.maximum(X, 0) - X * Label +
                    np.log1p(np.exp(-np.abs(X))),
                    grad=["X"], rtol=1e-4, atol=1e-5))
    run_case(OpCase("mse_loss", {"X": _A, "Y": _B},
                    ref=lambda X, Y: (X - Y) ** 2, grad=["X"]))
    run_case(OpCase("huber_loss", {"X": _A[:, :1], "Y": _B[:, :1]},
                    outputs={"Out": 1, "Residual": 1},
                    attrs={"delta": 0.3},
                    ref=lambda X, Y, delta: {
                        "Out": _huber_ref(Y - X, 0.3),
                        "Residual": Y - X}, grad=["X"]))
    run_case(OpCase("smooth_l1_loss", {"X": _A, "Y": _B},
                    outputs={"Out": 1, "Diff": 1}, attrs={"sigma": 1.0},
                    ref=lambda X, Y, sigma: {
                        "Out": _smooth_l1_ref(X - Y).sum(
                            1, keepdims=True)},
                    grad=["X"]))
    t = _softmax(R(17).rand(3, 4).astype("float32"))
    xlog = np.log(_softmax(R(18).rand(3, 4).astype("float32")))
    run_case(OpCase("kldiv_loss", {"X": xlog, "Target": t},
                    outputs={"Loss": 1}, attrs={"reduction": "none"},
                    ref=lambda X, Target, reduction: {
                        "Loss": Target * (np.log(Target) - X)},
                    grad=["X"], rtol=1e-4, atol=1e-5))


def _huber_ref(r, d):
    return np.where(np.abs(r) <= d, 0.5 * r * r,
                    d * (np.abs(r) - 0.5 * d))


def _smooth_l1_ref(d):
    a = np.abs(d)
    return np.where(a < 1, 0.5 * d * d, a - 0.5)


def test_accuracy_op():
    # accuracy(Out from topk, Indices, Label)
    pred = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]], "float32")
    idx = pred.argmax(1)[:, None].astype("int64")
    label = np.array([[1], [1], [1]], "int64")
    run_case(OpCase("accuracy",
                    {"Out": pred, "Indices": idx, "Label": label},
                    outputs={"Accuracy": 1, "Correct": 1, "Total": 1},
                    ref=lambda Out, Indices, Label: {
                        "Accuracy": np.array(2 / 3, "float32")},
                    check_dtype=False))


# ---------------------------------------------------------------------------
# nn ops
# ---------------------------------------------------------------------------
def _conv2d_ref(x, w, stride=1, pad=0):
    n, ci, h, ww = x.shape
    co, _, kh, kw = w.shape
    xp = np.pad(x, [(0, 0), (0, 0), (pad, pad), (pad, pad)])
    oh = (h + 2 * pad - kh) // stride + 1
    ow = (ww + 2 * pad - kw) // stride + 1
    out = np.zeros((n, co, oh, ow), "float64")
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * stride:i * stride + kh,
                       j * stride:j * stride + kw]
            out[:, :, i, j] = np.einsum("nchw,ochw->no", patch, w)
    return out.astype("float32")


def test_conv_pool():
    x = (R(19).permutation(2 * 3 * 5 * 5).reshape(2, 3, 5, 5)
         * 0.02).astype("float32")
    w = R(20).rand(4, 3, 3, 3).astype("float32")
    run_case(OpCase("conv2d", {"Input": x, "Filter": w},
                    outputs={"Output": 1},
                    attrs={"strides": [1, 1], "paddings": [1, 1],
                           "dilations": [1, 1], "groups": 1},
                    ref=lambda Input, Filter, **kw: {
                        "Output": _conv2d_ref(Input, Filter, 1, 1)},
                    grad=["Input", "Filter"], rtol=1e-3, atol=1e-4,
                    grad_rtol=8e-2))
    dw = R(21).rand(3, 1, 3, 3).astype("float32")
    run_case(OpCase("depthwise_conv2d", {"Input": x, "Filter": dw},
                    outputs={"Output": 1},
                    attrs={"strides": [1, 1], "paddings": [1, 1],
                           "dilations": [1, 1], "groups": 3},
                    ref=None, grad=["Input"], grad_rtol=8e-2))
    run_case(OpCase("pool2d", {"X": x},
                    attrs={"pooling_type": "max", "ksize": [2, 2],
                           "strides": [2, 2], "paddings": [0, 0]},
                    ref=lambda X, **kw: X.reshape(
                        2, 3, 2, 2, 2, 2).max(5).max(3)[:, :, :2, :2]
                    if False else _pool_ref(X, "max"),
                    grad=["X"], grad_rtol=8e-2))
    run_case(OpCase("pool2d", {"X": x},
                    attrs={"pooling_type": "avg", "ksize": [2, 2],
                           "strides": [2, 2], "paddings": [0, 0]},
                    ref=lambda X, **kw: _pool_ref(X, "avg"),
                    grad=["X"], name="pool2d_avg"))
    # conv2d_transpose: verify via adjointness on tiny shapes
    run_case(OpCase("conv2d_transpose",
                    {"Input": R(22).rand(1, 2, 3, 3).astype("float32"),
                     "Filter": R(23).rand(2, 2, 3, 3).astype("float32")},
                    outputs={"Output": 1},
                    attrs={"strides": [1, 1], "paddings": [0, 0],
                           "dilations": [1, 1], "groups": 1},
                    ref=None, grad=["Input"], grad_rtol=8e-2))


def _pool_ref(x, kind):
    n, c, h, w = x.shape
    oh, ow = h // 2, w // 2
    out = np.zeros((n, c, oh, ow), "float32")
    for i in range(oh):
        for j in range(ow):
            win = x[:, :, 2 * i:2 * i + 2, 2 * j:2 * j + 2]
            out[:, :, i, j] = win.max((2, 3)) if kind == "max" \
                else win.mean((2, 3))
    return out


def test_normalization_ops():
    x = R(24).rand(2, 6, 4).astype("float32")
    scale = R(25).rand(4).astype("float32")
    bias = R(26).rand(4).astype("float32")

    def ln_ref(X, Scale, Bias, epsilon, begin_norm_axis):
        m = X.mean(-1, keepdims=True)
        v = X.var(-1, keepdims=True)
        y = (X - m) / np.sqrt(v + epsilon) * Scale + Bias
        return {"Y": y}

    run_case(OpCase("layer_norm",
                    {"X": x.reshape(12, 4), "Scale": scale,
                     "Bias": bias},
                    outputs={"Y": 1, "Mean": 1, "Variance": 1},
                    attrs={"epsilon": 1e-5, "begin_norm_axis": 1},
                    ref=ln_ref, grad=["X", "Scale", "Bias"],
                    rtol=1e-4, atol=1e-5))

    def rms_ref(X, Scale, epsilon):
        return X / np.sqrt((X ** 2).mean(-1, keepdims=True)
                           + epsilon) * Scale

    run_case(OpCase("rms_norm", {"X": x.reshape(12, 4), "Scale": scale},
                    outputs={"Y": 1}, attrs={"epsilon": 1e-6},
                    ref=lambda **kw: {"Y": rms_ref(**kw)},
                    grad=["X", "Scale"], rtol=1e-4, atol=1e-5))

    xc = R(27).rand(2, 4, 3, 3).astype("float32")

    def bn_test_ref(X, Scale, Bias, Mean, Variance, epsilon, momentum,
                    is_test):
        y = (X - Mean[None, :, None, None]) / np.sqrt(
            Variance[None, :, None, None] + epsilon) \
            * Scale[None, :, None, None] + Bias[None, :, None, None]
        return {"Y": y}

    mean = R(28).rand(4).astype("float32")
    var = R(29).uniform(0.5, 1.5, 4).astype("float32")
    run_case(OpCase("batch_norm",
                    {"X": xc, "Scale": scale, "Bias": bias,
                     "Mean": mean, "Variance": var},
                    outputs={"Y": 1, "MeanOut": 1, "VarianceOut": 1,
                             "SavedMean": 1, "SavedVariance": 1},
                    attrs={"epsilon": 1e-5, "momentum": 0.9,
                           "is_test": True},
                    ref=bn_test_ref, rtol=1e-4, atol=1e-5))

    def gn_ref(X, Scale, Bias, epsilon, groups):
        n, c, h, w = X.shape
        g = X.reshape(n, groups, c // groups, h, w)
        m = g.mean((2, 3, 4), keepdims=True)
        v = g.var((2, 3, 4), keepdims=True)
        y = ((g - m) / np.sqrt(v + epsilon)).reshape(n, c, h, w)
        return {"Y": y * Scale[None, :, None, None]
                + Bias[None, :, None, None]}

    run_case(OpCase("group_norm",
                    {"X": xc, "Scale": scale, "Bias": bias},
                    outputs={"Y": 1, "Mean": 1, "Variance": 1},
                    attrs={"epsilon": 1e-5, "groups": 2},
                    ref=gn_ref, grad=["X"], rtol=1e-4, atol=1e-5))

    def in_ref(X, Scale, Bias, epsilon):
        m = X.mean((2, 3), keepdims=True)
        v = X.var((2, 3), keepdims=True)
        y = (X - m) / np.sqrt(v + epsilon)
        return {"Y": y * Scale[None, :, None, None]
                + Bias[None, :, None, None]}

    run_case(OpCase("instance_norm",
                    {"X": xc, "Scale": scale, "Bias": bias},
                    outputs={"Y": 1, "SavedMean": 1, "SavedVariance": 1},
                    attrs={"epsilon": 1e-5},
                    ref=in_ref, grad=["X"], rtol=1e-4, atol=1e-5))


def test_dropout_modes():
    x = np.ones((50, 50), "float32")
    got = check_forward(OpCase(
        "dropout", {"X": x}, outputs={"Out": 1, "Mask": 1},
        attrs={"dropout_prob": 0.3, "is_test": True,
               "dropout_implementation": "upscale_in_train"}))
    np.testing.assert_allclose(np.asarray(got[0]), x)  # test mode: identity
    got = check_forward(OpCase(
        "dropout", {"X": x}, outputs={"Out": 1, "Mask": 1},
        attrs={"dropout_prob": 0.3, "is_test": False,
               "dropout_implementation": "upscale_in_train"}))
    out = np.asarray(got[0])
    kept = out != 0
    assert abs(kept.mean() - 0.7) < 0.08
    np.testing.assert_allclose(out[kept], 1 / 0.7, rtol=1e-5)


def test_rope_op():
    x = R(30).rand(1, 2, 4, 8).astype("float32")  # [B,H,S,D]

    def rope_ref(X, base, position_offset):
        b, h, s, d = X.shape
        half = d // 2
        inv = 1.0 / (base ** (np.arange(half) / half))
        t = np.arange(s)[:, None] * inv[None, :]
        cos, sin = np.cos(t), np.sin(t)
        x1, x2 = X[..., :half], X[..., half:]
        return np.concatenate([x1 * cos - x2 * sin,
                               x2 * cos + x1 * sin], axis=-1)

    run_case(OpCase("rope", {"X": x},
                    attrs={"base": 10000.0, "position_offset": 0},
                    ref=rope_ref, grad=["X"], rtol=1e-4, atol=1e-5))


# ---------------------------------------------------------------------------
# optimizer ops (single step vs numpy)
# ---------------------------------------------------------------------------
def test_sgd_op():
    p = _A.copy()
    g = _B.copy()
    lr = np.array([0.1], "float32")
    run_case(OpCase("sgd", {"Param": p, "Grad": g, "LearningRate": lr},
                    outputs={"ParamOut": 1},
                    ref=lambda Param, Grad, LearningRate: {
                        "ParamOut": Param - 0.1 * Grad}))


def test_momentum_op():
    p, g = _A.copy(), _B.copy()
    v = np.zeros_like(p)
    lr = np.array([0.1], "float32")
    run_case(OpCase("momentum",
                    {"Param": p, "Grad": g, "Velocity": v,
                     "LearningRate": lr},
                    outputs={"ParamOut": 1, "VelocityOut": 1},
                    attrs={"mu": 0.9},
                    ref=lambda Param, Grad, Velocity, LearningRate, mu: {
                        "VelocityOut": mu * Velocity + Grad,
                        "ParamOut": Param - 0.1 * (mu * Velocity + Grad)}))


def test_adam_op():
    p, g = _A.copy(), _B.copy()
    m = np.full_like(p, 0.1)
    v = np.full_like(p, 0.2)
    lr = np.array([0.01], "float32")
    b1p = np.array([0.9], "float32")
    b2p = np.array([0.999], "float32")

    def ref(Param, Grad, Moment1, Moment2, LearningRate, Beta1Pow,
            Beta2Pow, beta1, beta2, epsilon):
        # reference adam_op.h: beta pows hold beta^t for the current step
        m2 = beta1 * Moment1 + (1 - beta1) * Grad
        v2 = beta2 * Moment2 + (1 - beta2) * Grad * Grad
        lr_t = 0.01 * np.sqrt(1 - Beta2Pow) / (1 - Beta1Pow)
        return {"ParamOut": Param - lr_t * m2 / (
                    np.sqrt(v2) + epsilon * np.sqrt(1 - Beta2Pow)),
                "Moment1Out": m2, "Moment2Out": v2}

    run_case(OpCase("adam",
                    {"Param": p, "Grad": g, "Moment1": m, "Moment2": v,
                     "LearningRate": lr, "Beta1Pow": b1p,
                     "Beta2Pow": b2p},
                    outputs={"ParamOut": 1, "Moment1Out": 1,
                             "Moment2Out": 1, "Beta1PowOut": 1,
                             "Beta2PowOut": 1},
                    attrs={"beta1": 0.9, "beta2": 0.999,
                           "epsilon": 1e-8},
                    ref=ref, rtol=1e-4, atol=1e-5))


def test_adagrad_op():
    p, g = _A.copy(), _B.copy()
    mom = np.full_like(p, 0.3)
    lr = np.array([0.1], "float32")
    run_case(OpCase("adagrad",
                    {"Param": p, "Grad": g, "Moment": mom,
                     "LearningRate": lr},
                    outputs={"ParamOut": 1, "MomentOut": 1},
                    attrs={"epsilon": 1e-6},
                    ref=lambda Param, Grad, Moment, LearningRate,
                    epsilon: {
                        "MomentOut": Moment + Grad * Grad,
                        "ParamOut": Param - 0.1 * Grad / (np.sqrt(
                            Moment + Grad * Grad) + epsilon)},
                    rtol=1e-4, atol=1e-5))


# ---------------------------------------------------------------------------
# coverage gate
# ---------------------------------------------------------------------------
# ops exercised by this file (directly above)
COVERED = (set(UNARY) | set(BINARY) | set(COMPARE) | set(LOGICAL) | {
    "leaky_relu", "prelu", "scale", "clip", "assign", "pow",
    "logical_not",
    "share_data", "cast", "logsumexp", "maxout",
    "isfinite_v2", "isinf_v2", "isnan_v2",
    "matmul", "matmul_v2", "mul", "bmm", "dot",
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
    "reduce_prod", "reduce_all", "reduce_any", "mean", "max", "min",
    "sum", "squared_l2_norm", "cumsum", "norm", "p_norm", "clip_by_norm",
    "reshape", "reshape2", "transpose", "transpose2", "concat", "split",
    "stack", "unstack", "squeeze", "squeeze2", "unsqueeze", "unsqueeze2",
    "tril_triu", "meshgrid", "cumprod", "nearest_interp",
    "bilinear_interp", "pixel_shuffle",
    "flatten", "flatten2", "flatten_contiguous_range", "slice",
    "strided_slice", "pad", "tile", "expand", "expand_v2", "flip",
    "roll", "shape", "gather", "gather_nd", "index_select", "scatter",
    "scatter_nd_add", "take_along_axis", "where", "lookup_table",
    "lookup_table_v2", "embedding", "arg_max", "arg_min", "argsort",
    "top_k", "top_k_v2", "one_hot", "one_hot_v2", "label_smooth",
    "fill_constant", "fill_any_like", "fill_zeros_like", "assign_value",
    "eye", "linspace", "range", "gaussian_random", "uniform_random",
    "randint", "randperm", "bernoulli", "truncated_gaussian_random",
    "softmax", "log_softmax", "cross_entropy",
    "softmax_with_cross_entropy", "bce_loss",
    "sigmoid_cross_entropy_with_logits", "mse_loss", "huber_loss",
    "smooth_l1_loss", "kldiv_loss", "accuracy",
    "conv2d", "depthwise_conv2d", "conv2d_transpose", "pool2d",
    "layer_norm", "rms_norm", "batch_norm", "group_norm",
    "instance_norm", "dropout", "rope",
    "sgd", "momentum", "adam", "adagrad",
})

# every other registered op must appear here, with the test that covers it
SKIP = {
    # collectives: numerically tested on the virtual 8-device mesh
    **{op: "tests/test_fleet_collective.py" for op in [
        "c_allgather", "c_allreduce_max", "c_allreduce_min",
        "c_allreduce_prod", "c_allreduce_sum", "c_broadcast", "c_concat",
        "c_identity", "c_reduce_max", "c_reduce_min", "c_reduce_sum",
        "c_reducescatter", "c_split", "barrier"]},
    **{op: "no-op stream/init stubs (XLA owns ordering); asserted "
       "harmless in tests/test_fleet_collective.py" for op in [
           "c_comm_init", "c_comm_init_all", "c_gen_nccl_id",
           "c_sync_calc_stream", "c_sync_comm_stream", "c_wait_comm",
           "c_wait_compute"]},
    "send_v2": "tests/test_pipeline_pp.py (p2p pairing inside shard_map)",
    "recv_v2": "tests/test_pipeline_pp.py",
    # io: roundtrip-tested
    "save": "tests/test_io.py", "load": "tests/test_io.py",
    "save_combine": "tests/test_io.py",
    "load_combine": "tests/test_io.py",
    # control flow: trajectory-tested
    "while": "tests/test_backward_training.py (while_loop training)",
    "increment": "in-place loop-counter op; exercised by while-loop "
                 "tests (tests/test_backward_training.py)",
    "cond2": "tests/test_backward_training.py",
    "conditional_block": "tests/test_backward_training.py",
    # fused attention: parity + grad vs unfused in test_attention
    "flash_attention": "tests/test_attention.py (fwd+grad vs unfused)",
    "flash_attention_qkv": "tests/test_attention.py (packed vs unfused)",
    "beam_search": "tests/test_beam_search.py (finished semantics)",
    "beam_search_decode": "tests/test_beam_search.py (padding/lengths)",
    "gather_tree": "tests/test_beam_search.py (vs reference loop)",
    "linear_chain_crf": "tests/test_crf_ctc.py (brute-force + finite diff)",
    "crf_decoding": "tests/test_crf_ctc.py (viterbi vs brute force)",
    "warpctc": "tests/test_crf_ctc.py (alignment enum + finite diff)",
    "nce": "tests/test_crf_ctc.py (word2vec training smoke)",
    "hierarchical_sigmoid": "tests/test_crf_ctc.py (manual tree ref)",
    "addmm": "tests/test_longtail_ops.py",
    "mv": "tests/test_longtail_ops.py",
    "minus": "tests/test_longtail_ops.py",
    "allclose": "tests/test_longtail_ops.py",
    "l1_norm": "tests/test_longtail_ops.py",
    "squared_l2_distance": "tests/test_longtail_ops.py",
    "size": "tests/test_longtail_ops.py",
    "shard_index": "tests/test_longtail_ops.py",
    "multiplex": "tests/test_longtail_ops.py",
    "unbind": "tests/test_longtail_ops.py",
    "reverse": "tests/test_longtail_ops.py",
    "cos_sim": "tests/test_longtail_ops.py",
    "log_loss": "tests/test_longtail_ops.py",
    "selu": "tests/test_longtail_ops.py",
    "conv_shift": "tests/test_longtail_ops.py",
    # round-5 catalog batches
    **{op: "tests/test_interp_pool_ops.py (loop numpy refs + FD grads)"
       for op in [
           "linear_interp", "linear_interp_v2", "bicubic_interp",
           "bicubic_interp_v2", "trilinear_interp", "trilinear_interp_v2",
           "max_pool2d_with_index", "max_pool3d_with_index", "unpool"]},
    **{op: "tests/test_misc2_ops.py" for op in [
        "space_to_depth", "crop", "crop_tensor", "pad_constant_like",
        "expand_as", "expand_as_v2", "frobenius_norm", "cross_entropy2",
        "where_index", "coalesce_tensor", "inplace_abn",
        "sigmoid_focal_loss", "shuffle_batch", "sample_logits",
        "positive_negative_pair", "hash"]},
    **{op: "tests/test_rnn_fused_ops.py (step-loop refs + FD grads)"
       for op in ["lstm", "lstmp", "gru", "rnn", "cudnn_lstm"]},
    **{op: "tests/test_catalog_ops.py" for op in [
        "sequence_reshape", "sequence_scatter", "lod_reset",
        "lod_tensor_to_array", "array_to_lod_tensor",
        "split_lod_tensor", "merge_lod_tensor", "shrink_rnn_memory",
        "merge_selected_rows", "get_tensor_from_selected_rows",
        "split_ids", "merge_ids", "select_input", "select_output",
        "batch_fc", "rank_attention", "tree_conv", "var_conv_2d",
        "pyramid_hash", "filter_by_instag", "prroi_pool",
        "correlation", "chunk_eval", "attention_lstm", "bilateral_slice",
        "depthwise_conv2d_transpose", "quantize",
        "dequantize",
        "requantize", "proximal_adagrad", "dgc", "dgc_clip_by_norm",
        "multihead_matmul", "skip_layernorm",
        "fused_embedding_eltwise_layernorm"]},
    "split_selected_rows": "tests/test_selected_rows.py "
                           "(lowering-level shard test)",
    "sync_batch_norm": "tests/test_sync_batch_norm.py (8-mesh parity "
                       "vs full-batch BN + training)",
    **{op: "tests/test_jit_save.py" for op in [
        "py_func", "run_program", "distributed_lookup_table"]},
    "moe_ffn": "tests/test_moe.py (numpy Switch ref, ep8 all_to_all "
               "parity, capacity drop, training)",
    "global_norm_sq": "tests/test_lr_clip_ema.py (fused-clip parity "
                      "vs the per-grad default)",
    **{op: "tests/test_fleet_collective.py (8-mesh numeric)" for op in [
        "allreduce", "broadcast", "c_reduce_prod", "c_scatter"]},
    "add_position_encoding": "tests/test_longtail_ops.py",
    "cvm": "tests/test_longtail_ops.py",
    "hinge_loss": "tests/test_longtail_ops.py",
    "modified_huber_loss": "tests/test_longtail_ops.py",
    "margin_rank_loss": "tests/test_longtail_ops.py",
    "rank_loss": "tests/test_longtail_ops.py",
    "bpr_loss": "tests/test_longtail_ops.py",
    "nll_loss": "tests/test_longtail_ops.py",
    "teacher_student_sigmoid_loss": "tests/test_longtail_ops.py",
    "center_loss": "tests/test_longtail_ops.py",
    "fill_constant_batch_size_like": "tests/test_longtail_ops.py",
    "uniform_random_batch_size_like": "tests/test_longtail_ops.py",
    "gaussian_random_batch_size_like": "tests/test_longtail_ops.py",
    "empty": "tests/test_longtail_ops.py",
    "fill": "tests/test_longtail_ops.py",
    "is_empty": "tests/test_longtail_ops.py",
    "sampling_id": "tests/test_longtail_ops.py",
    "mean_iou": "tests/test_longtail_ops.py",
    "edit_distance": "tests/test_longtail_ops.py",
    "unique_with_counts": "tests/test_longtail_ops.py",
    "conv3d": "tests/test_longtail_ops.py",
    "conv3d_transpose": "tests/test_longtail_ops.py",
    "pool3d": "tests/test_longtail_ops.py",
    "pad2d": "tests/test_longtail_ops.py",
    "pad3d": "tests/test_longtail_ops.py",
    "lrn": "tests/test_longtail_ops.py",
    "data_norm": "tests/test_longtail_ops.py",
    "spectral_norm": "tests/test_longtail_ops.py",
    "shuffle_channel": "tests/test_longtail_ops.py",
    "temporal_shift": "tests/test_longtail_ops.py",
    "row_conv": "tests/test_longtail_ops.py",
    "im2sequence": "tests/test_longtail_ops.py",
    "bilinear_tensor_product": "tests/test_longtail_ops.py",
    "fsp": "tests/test_longtail_ops.py",
    "partial_concat": "tests/test_longtail_ops.py",
    "partial_sum": "tests/test_longtail_ops.py",
    "psroi_pool": "tests/test_longtail_ops.py",
    "deformable_conv": "tests/test_longtail_ops.py",
    "deformable_conv_v1": "tests/test_longtail_ops.py",
    "segment_pool": "tests/test_longtail_ops.py",
    "gru_unit": "tests/test_longtail_ops.py",
    "lstm_unit": "tests/test_longtail_ops.py",
    "auc": "tests/test_longtail_ops.py",
    "sequence_conv": "tests/test_longtail_ops.py",
    "sequence_expand": "tests/test_longtail_ops.py",
    "sequence_pad": "tests/test_longtail_ops.py",
    "sequence_unpad": "tests/test_longtail_ops.py",
    "sequence_concat": "tests/test_longtail_ops.py",
    "sequence_slice": "tests/test_longtail_ops.py",
    "sequence_erase": "tests/test_longtail_ops.py",
    "sequence_enumerate": "tests/test_longtail_ops.py",
    # amp machinery: inf-recovery trajectories
    "check_finite_and_unscale": "tests/test_round2_fixes.py (amp)",
    "update_loss_scaling": "tests/test_round2_fixes.py (amp)",
    # optimizer long tail: convergence-tested end to end
    **{op: "tests/test_backward_training.py (optimizer trajectories)"
       for op in ["adamax", "adadelta", "adamw", "rmsprop",
                  "decayed_adagrad", "ftrl", "dpsgd", "lamb",
                  "lars_momentum", "proximal_gd"]},
    "dgc_momentum": "tests/test_meta_optimizers.py (DGC trajectory)",
    "average_accumulates": "tests/test_lr_clip_ema.py (ModelAverage)",
    # dynamic output shapes: cannot run under a static-shape jit; the
    # lowering pads/masks — exercised via layers tests
    "print": "tests/test_observability.py (passthrough, grad, output)",
    "bilinear_interp_v2": "same lowering as bilinear_interp (tested)",
    "nearest_interp_v2": "same lowering as nearest_interp (tested)",
    **{op: "tests/test_quant.py (fake-quant semantics + STE grads)"
       for op in ["fake_quantize_dequantize_abs_max",
                  "fake_quantize_dequantize_moving_average_abs_max",
                  "fake_channel_wise_quantize_dequantize_abs_max"]},
    **{op: "tests/test_sequence.py (masked refs vs numpy, training)"
       for op in ["sequence_mask", "sequence_pool", "sequence_softmax",
                  "sequence_reverse", "sequence_expand_as",
                  "write_to_array", "read_from_array", "lstm_rnn",
                  "gru_rnn"]},
    **{op: "tests/test_generation.py (kv_cache_write ragged-offset "
       "unit; all three via cached-decode bit-exactness vs the "
       "uncached forward, tolerance 0)" for op in [
           "kv_cache_write", "kv_cache_insert", "cached_attention"]},
    **{op: "tests/test_paged_generation.py (scatter/gather round trip "
       "+ trash-page redirect unit; both via paged-decode "
       "bit-exactness vs the dense cache, tolerance 0)" for op in [
           "kv_pool_write", "kv_pool_gather"]},
    "masked_select": "dynamic shape; covered via layers.masked_select "
                     "usage in tests/test_models.py",
    "unique": "dynamic shape; lowering returns padded/size pair",
    **{op: "tests/test_linalg_misc.py (forward vs numpy refs + "
       "finite-difference grads)" for op in [
           "cholesky", "inverse", "kron", "trace", "cross", "dist",
           "diag", "diag_v2", "diag_embed", "index_sample",
           "affine_channel", "affine_grid", "grid_sampler", "unfold",
           "histogram", "multinomial"]},
    **{op: "tests/test_detection.py (forward vs numpy refs; "
       "iou_similarity/roi_align grad-checked there)" for op in [
           "iou_similarity", "box_coder", "prior_box",
           "anchor_generator", "yolo_box", "box_clip",
           "bipartite_match", "roi_align", "roi_pool",
           "multiclass_nms", "density_prior_box", "target_assign",
           "mine_hard_examples", "generate_proposals", "matrix_nms",
           "distribute_fpn_proposals", "collect_fpn_proposals",
           "yolov3_loss"]},
}


def test_registry_coverage_complete():
    """Every registered op is either tested above or skip-listed with a
    pointer to the test that covers it (reference op_test coverage
    policy: tools/check_op_test_coverage)."""
    from paddle_tpu.ops.registry import all_registered_ops
    # auto-derived <type>_grad entries register lazily while other test
    # modules build backwards; the gate governs forward ops
    ops = {o for o in all_registered_ops() if not o.endswith("_grad")}
    untracked = ops - COVERED - set(SKIP)
    assert not untracked, f"ops with no test or skip reason: " \
                          f"{sorted(untracked)}"
    stale = (COVERED | set(SKIP)) - ops
    assert not stale, f"stale coverage entries: {sorted(stale)}"
    overlap = COVERED & set(SKIP)
    assert not overlap, f"both covered and skipped: {sorted(overlap)}"
