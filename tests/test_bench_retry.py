"""Forced-fault tests for bench.py's fault-tolerance core (VERDICT r4 #1).

BENCH_r04 exited rc=1 when one transient axon remote-compile disconnect
aborted the run mid-measurement. These tests inject the exact fault
signatures and prove the measurement survives: fence (readback) faults
retry in place, dispatch faults escalate to a bounded rebuild, outlier
windows are re-timed, and only deterministic failures propagate.
"""
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import bench  # noqa: E402


class XlaRuntimeError(Exception):
    """Same type name the tunnel raises — _transient matches on it."""


def _ok_window(state):
    return state + 1, ("fetch",)


def test_transient_predicate():
    assert bench._transient(XlaRuntimeError("INTERNAL: boom"))
    assert bench._transient(RuntimeError(
        "response body closed before all bytes were read"))
    assert bench._transient(OSError("Connection reset by peer"))
    assert not bench._transient(ValueError("shape mismatch"))
    assert not bench._transient(RuntimeError("non-finite loss nan"))
    # known-deterministic device faults fail fast, no rebuild cycles
    assert not bench._transient(XlaRuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating 12345 bytes"))
    assert not bench._transient(XlaRuntimeError(
        "INVALID_ARGUMENT: mismatched operand shapes"))


def test_fence_fault_retries_in_place():
    calls = {"n": 0}

    def fence(fetches):
        calls["n"] += 1
        if calls["n"] == 2:  # one window's readback dies once
            raise XlaRuntimeError("INTERNAL: response body closed "
                                  "before all bytes were read")
        return 1.0

    faults = {}
    dts, state, loss, n_reruns = bench.measure_windows(
        _ok_window, fence, 0, n_windows=4, faults=faults)
    assert len(dts) == 4 and state >= 4 and loss == 1.0
    assert faults["fence_retries"] == 1
    assert faults["dispatch_retries"] == 0


def test_dispatch_fault_retries_then_succeeds():
    calls = {"n": 0}

    def run_window(state):
        calls["n"] += 1
        if calls["n"] == 1:
            raise XlaRuntimeError("UNAVAILABLE: socket closed")
        return state + 1, ("fetch",)

    faults = {}
    dts, state, loss, _ = bench.measure_windows(
        run_window, lambda f: 0.5, 0, n_windows=3, faults=faults)
    assert len(dts) == 3
    assert faults["dispatch_retries"] == 1


def test_dispatch_double_fault_escalates_to_rebuild():
    def run_window(state):
        raise XlaRuntimeError("INTERNAL: stream broken")

    with pytest.raises(bench.RebuildNeeded):
        bench.measure_windows(run_window, lambda f: 0.5, 0, n_windows=3)


def test_deleted_buffer_after_fault_escalates():
    calls = {"n": 0}

    def run_window(state):
        calls["n"] += 1
        if calls["n"] == 1:
            raise XlaRuntimeError("INTERNAL: boom")
        raise RuntimeError("Array has been deleted")  # donated input gone

    with pytest.raises(bench.RebuildNeeded):
        bench.measure_windows(run_window, lambda f: 0.5, 0, n_windows=3)


def test_deterministic_error_propagates_unchanged():
    def run_window(state):
        raise ValueError("shape mismatch (deterministic)")

    with pytest.raises(ValueError):
        bench.measure_windows(run_window, lambda f: 0.5, 0, n_windows=3)


def test_nonfinite_loss_propagates():
    def fence(fetches):
        raise RuntimeError("non-finite loss nan")

    with pytest.raises(RuntimeError, match="non-finite"):
        bench.measure_windows(_ok_window, fence, 0, n_windows=2)


def test_outlier_window_rerun(monkeypatch):
    """A window 1.5x slower than the rest is re-timed (VERDICT weak #3:
    a 1.54x spread must not pass silently)."""
    ticks = iter([0.0, 1.0,    # window 0: 1.0s
                  1.0, 2.0,    # window 1: 1.0s
                  2.0, 3.6,    # window 2: 1.6s  -> outlier
                  3.6, 4.6])   # re-run:   1.0s
    monkeypatch.setattr(bench.time, "perf_counter", lambda: next(ticks))
    dts, state, loss, n_reruns = bench.measure_windows(
        _ok_window, lambda f: 1.0, 0, n_windows=3)
    assert n_reruns == 1
    assert max(dts) / min(dts) <= bench.RERUN_SPREAD + 1e-9


def test_rerun_budget_bounds(monkeypatch):
    """A persistently slow chip exhausts the budget and stops."""
    t = {"now": 0.0}

    def clock():
        return t["now"]

    monkeypatch.setattr(bench.time, "perf_counter", clock)
    slow = iter([1.0, 2.0] + [2.0] * 100)  # every re-run is slow too

    def run_window(state):
        t["now"] += next(slow)
        return state + 1, ("f",)

    dts, state, loss, n_reruns = bench.measure_windows(
        run_window, lambda f: 1.0, 0, n_windows=2)
    assert n_reruns == bench.RERUN_BUDGET


def test_with_rebuilds_recovers():
    attempts = {"n": 0}

    def build():
        attempts["n"] += 1
        if attempts["n"] < 2:
            raise bench.RebuildNeeded("tunnel died")
        return {"value": 42}

    faults = {}
    out = bench.with_rebuilds(build, faults=faults, settle=lambda s: None)
    assert out["value"] == 42
    assert faults["rebuilds"] == 1


def test_with_rebuilds_transient_generic_exception():
    attempts = {"n": 0}

    def build():
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise XlaRuntimeError("INTERNAL: compile rpc lost")
        return "ok"

    assert bench.with_rebuilds(build, settle=lambda s: None) == "ok"


def test_with_rebuilds_deterministic_fails_fast():
    attempts = {"n": 0}

    def build():
        attempts["n"] += 1
        raise ValueError("bad config")

    with pytest.raises(ValueError):
        bench.with_rebuilds(build)
    assert attempts["n"] == 1  # no pointless rebuilds


def test_with_rebuilds_bounded():
    attempts = {"n": 0}

    def build():
        attempts["n"] += 1
        raise bench.RebuildNeeded("always")

    with pytest.raises(bench.RebuildNeeded):
        bench.with_rebuilds(build, settle=lambda s: None)
    assert attempts["n"] == bench.MAX_REBUILDS + 1
