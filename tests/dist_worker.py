"""Worker script for the multi-process harness test; launched by
``python -m paddle_tpu.distributed.launch --nproc_per_node 2`` (see
test_multiprocess.py).  Mirrors the reference's test_dist_base.py
runtime-main pattern (tests/unittests/test_dist_base.py:642).
"""
import json
import os
import sys

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from paddle_tpu.distributed.parallel_env import (  # noqa: E402
    get_rank, get_world_size, init_parallel_env)


def main(out_dir):
    env = init_parallel_env()
    rank, world = get_rank(), get_world_size()
    assert world == 2, f"expected world 2, got {world}"
    assert jax.process_count() == 2

    from paddle_tpu.distributed.collective import (all_gather, all_reduce,
                                                   broadcast)

    # -- collective smoke over the 2-process cpu ring -----------------------
    red = all_reduce(np.full((3,), float(rank + 1), "float32"))
    gat = all_gather(np.full((2,), float(rank), "float32"))
    bc = broadcast(np.full((2,), float(rank + 7), "float32"), src=1)

    # -- dygraph DataParallel grad parity -----------------------------------
    import paddle_tpu as pt
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph.parallel import DataParallel, ParallelStrategy

    rng = np.random.RandomState(0)
    xs = rng.rand(8, 4).astype("float32")
    ys = (xs.sum(1, keepdims=True) * 0.5).astype("float32")
    w0 = rng.rand(4, 1).astype("float32")

    def build_model():
        with dygraph.guard():
            lin = dygraph.nn.Linear(
                4, 1, param_attr=pt.initializer.NumpyArrayInitializer(w0),
                bias_attr=pt.initializer.ConstantInitializer(0.0))
            return lin

    def grads_of(model, x, y, dp=None):
        with dygraph.guard():
            xv = dygraph.to_variable(x)
            yv = dygraph.to_variable(y)
            pred = model(xv)
            diff = pred - yv
            loss = pt.layers.reduce_mean(diff * diff)
            if dp is not None:
                # canonical DataParallel sequence: scaled loss ->
                # backward -> allreduce-sum == mean over ranks
                loss = dp.scale_loss(loss)
            loss.backward()
            if dp is not None:
                dp.apply_collective_grads()
            return {n: p.gradient()
                    for n, p in model.named_parameters()}

    # reference: full-batch grads, single process
    ref_model = build_model()
    ref = grads_of(ref_model, xs, ys)

    # distributed: each rank a half-batch through DataParallel
    model = build_model()
    strategy = ParallelStrategy()
    strategy.nranks = world
    dp = DataParallel(model, strategy)
    shard = slice(rank * 4, (rank + 1) * 4)
    got = grads_of(dp, xs[shard], ys[shard], dp=dp)

    result = {
        "rank": rank,
        "endpoint": env.current_endpoint if hasattr(
            env, "current_endpoint") else "",
        "all_reduce": red.tolist(),
        "all_gather": gat.tolist(),
        "broadcast": bc.tolist(),
        "grad_max_err": max(
            float(np.abs(got[n] - ref[n]).max()) for n in ref),
    }
    with open(os.path.join(out_dir, f"result.{rank}.json"), "w") as f:
        json.dump(result, f)
    print(f"WORKER {rank} DONE")


if __name__ == "__main__":
    main(sys.argv[1])
