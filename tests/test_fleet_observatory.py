"""Fleet observatory matrix: tsdb windowed math, burn-rate alerting,
router metrics federation (/fleetz + fleet-labeled /metrics),
per-sequence TTFT/ITL timelines, the streaming /generate contract,
and the loadgen's client-side TTFT/ITL SLO bounds.

In-process throughout: two real ServingServers behind a Router give
real sockets and real scrapes with deterministic control (manual
``poll_once`` sweeps, injectable tsdb timestamps).
"""
import importlib.util
import json
import os
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import promtext, telemetry, tsdb
from paddle_tpu.serving import (GenerationEngine, Router, RouterServer,
                                ServingEngine)
from paddle_tpu.serving.server import ServingServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_loadgen():
    spec = importlib.util.spec_from_file_location(
        "serving_loadgen_observatory_tests",
        os.path.join(REPO, "tools", "serving_loadgen.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


lg = _load_loadgen()

TINY_LLAMA = dict(vocab_size=64, hidden=32, num_layers=2, num_heads=4,
                  num_kv_heads=2, intermediate=64)


# ---------------------------------------------------------------------------
# tsdb core
# ---------------------------------------------------------------------------

def test_tsdb_ring_eviction_and_memory_bound():
    db = tsdb.TSDB(points=8, max_series=3)
    for i in range(50):
        db.record("a", i, ts=1000.0 + i)
    assert len(db.points("a")) == 8
    assert [v for _, v in db.points("a")] == list(range(42, 50))
    # series cap: past max_series new names drop, counted, never OOM
    db.record("b", 1, ts=1.0)
    db.record("c", 1, ts=1.0)
    assert db.record("d", 1, ts=1.0) is False
    assert db.stats()["series_dropped"] == 1
    assert db.stats()["series"] == 3
    # non-numeric / non-finite points are refused, not stored
    assert db.record("a", "nope") is False
    assert db.record("a", float("nan")) is False


def test_tsdb_windowed_rate_delta_quantile():
    db = tsdb.TSDB(points=64)
    t0 = 5000.0
    for i in range(11):
        db.record("ctr", 10 * i, ts=t0 + i)     # +10/s counter
        db.record("g", float(i), ts=t0 + i)     # gauge ramp 0..10
    now = t0 + 10
    assert db.delta("ctr", 5.0, now=now) == 50
    assert abs(db.rate("ctr", 5.0, now=now) - 10.0) < 1e-9
    # window scoping: only the trailing points count
    assert db.delta("ctr", 2.0, now=now) == 20
    assert db.quantile("g", 50, 100.0, now=now) == 5.0
    assert db.quantile("g", 100, 100.0, now=now) == 10.0
    assert db.avg("g", 2.0, now=now) == pytest.approx(9.0)
    assert db.minmax("g", 100.0, now=now) == (0.0, 10.0)
    # empty window: None, never 0 (no evidence != no traffic)
    assert db.delta("ctr", 5.0, now=now + 100) is None
    assert db.rate("missing", 5.0) is None
    assert db.quantile("g", 99, 0.0001, now=now + 100) is None


def test_tsdb_monotonic_counter_reset():
    """A replica restart drops its counters to ~0: the post-reset
    value is the increment — the raw negative difference must never
    erase real traffic from a fleet rate."""
    db = tsdb.TSDB(points=16)
    t0 = 0.0
    for i, v in enumerate([100, 150, 200, 5, 30]):  # reset after 200
        db.record("c", v, ts=t0 + i)
    # 50 + 50 + (reset: 5) + 25 = 130
    assert db.delta("c", 100.0, now=t0 + 4) == 130


# ---------------------------------------------------------------------------
# burn-rate monitor
# ---------------------------------------------------------------------------

def _availability_monitor(db, **kw):
    spec = tsdb.SloSpec("avail", "availability", error_series="err",
                        total_series="tot", objective_pct=99.0)
    kw.setdefault("fast_s", 10.0)
    kw.setdefault("slow_s", 30.0)
    kw.setdefault("threshold", 2.0)
    return tsdb.BurnRateMonitor(db, [spec], publish=False, **kw)


def _feed(db, t0, n, err_rate, base_tot=0.0, base_err=0.0, step_s=1.0):
    """n seconds of traffic at 10 req/s with the given error rate."""
    for i in range(n):
        db.record("tot", base_tot + 10 * i, ts=t0 + i * step_s)
        db.record("err", base_err + 10 * i * err_rate,
                  ts=t0 + i * step_s)
    return t0 + (n - 1) * step_s


def test_burn_rate_window_pair_both_must_burn():
    """The multi-window contract: a fast-only spike (slow window still
    healthy) must NOT page; sustained burn over both windows fires."""
    db = tsdb.TSDB(points=256)
    mon = _availability_monitor(db)
    # 30s clean, then a 2s spike at 30% errors: the fast (10s) window
    # burns at ~3x budget, the slow (30s) window still sits at ~1x —
    # no page on a blip
    end = _feed(db, 1000.0, 31, 0.0)
    end = _feed(db, end + 1, 2, 0.3, base_tot=310, base_err=0.0)
    st = mon.evaluate(now=end)
    a = st["alerts"][0]
    assert a["burn_fast"] is not None and a["burn_fast"] >= 2.0
    assert a["burn_slow"] is not None and a["burn_slow"] < 2.0
    assert a["state"] == "ok", a  # slow window hasn't confirmed yet
    # sustain the burn until the slow window agrees -> fires
    end = _feed(db, end + 1, 20, 0.3, base_tot=330, base_err=3.0)
    st = mon.evaluate(now=end)
    a = st["alerts"][0]
    assert a["burn_slow"] >= 2.0 and a["state"] == "firing", a
    assert a["firing_for_s"] is not None
    assert st["firing"] == 1


def test_burn_rate_hysteresis_and_clear():
    db = tsdb.TSDB(points=512)
    mon = _availability_monitor(db, clear_ratio=0.5)
    end = _feed(db, 0.0, 40, 0.5)        # sustained 50% errors
    st = mon.evaluate(now=end)
    assert st["alerts"][0]["state"] == "firing"
    # errors stop; fast burn decays below threshold but above
    # threshold*clear_ratio -> still firing (hysteresis)
    t = end
    cleared_at = None
    for i in range(40):
        t += 1.0
        db.record("tot", 390 + 10 * (i + 1), ts=t)
        db.record("err", 195, ts=t)  # frozen error counter
        st = mon.evaluate(now=t)
        a = st["alerts"][0]
        if a["state"] == "ok":
            cleared_at = i
            break
        if a["burn_fast"] is not None:
            # never cleared while fast burn still >= thr * ratio
            assert a["burn_fast"] >= 0.0
    assert cleared_at is not None, "alert never cleared"
    # transitions recorded (fired once, cleared once)
    assert st["alerts"][0]["transitions"] == 2


def test_burn_rate_budget_exhaustion_and_config_guards():
    db = tsdb.TSDB(points=512)
    mon = _availability_monitor(db, budget_window_s=100.0)
    # 2% errors sustained = 2x the 1% budget -> exhausted over the
    # budget-integration window
    end = _feed(db, 0.0, 60, 0.02)
    st = mon.evaluate(now=end)
    a = st["alerts"][0]
    assert a["budget_spent_pct"] == pytest.approx(200.0, rel=0.1)
    assert a["exhausted"] is True
    # latency spec units: share of samples over threshold / budget
    for i in range(100):
        db.record("lat", 10.0 if i % 20 else 500.0, ts=end + i)
    lat = tsdb.SloSpec("p99", "latency", latency_series="lat",
                       threshold_ms=250.0, objective_pct=99.0)
    frac = lat.bad_fraction(db, 1000.0, now=end + 99)
    assert frac == pytest.approx(0.05)     # 5 of 100 over
    # 5% over a 1% budget = burn 5
    assert frac / lat.budget == pytest.approx(5.0)
    # config guards: window pair must be ordered; specs validated
    with pytest.raises(ValueError):
        tsdb.BurnRateMonitor(db, [], fast_s=60.0, slow_s=30.0)
    with pytest.raises(ValueError):
        tsdb.SloSpec("x", "availability", error_series="e")
    with pytest.raises(ValueError):
        tsdb.SloSpec("x", "latency", latency_series="l")
    with pytest.raises(ValueError):
        tsdb.SloSpec("x", "nope")


def test_sample_registry_cadence_and_flag_gate():
    tsdb.reset_default()
    telemetry.gauge_set("obs_test_gauge", 7.0)
    n = tsdb.sample_registry()
    assert n > 0
    assert tsdb.default().last("obs_test_gauge") == 7.0
    # FLAGS_tsdb=0: zero recording
    pt.set_flags({"FLAGS_tsdb": 0})
    try:
        assert tsdb.sample_registry() == 0
    finally:
        pt.set_flags({"FLAGS_tsdb": 1})
    tsdb.reset_default()


# ---------------------------------------------------------------------------
# promtext: shared parser
# ---------------------------------------------------------------------------

def test_promtext_parses_live_exposition():
    telemetry.gauge_set("obs_parse_gauge", 3.5)
    telemetry.histogram_observe("obs_parse_ms", 12.0)
    text = telemetry.prometheus_text()
    assert promtext.validate_lines(text) == []
    fams = promtext.parse_exposition(text, strict=True)
    g = fams["paddle_tpu_obs_parse_gauge"]
    assert g.type == "gauge" and g.value() == 3.5
    h = fams["paddle_tpu_obs_parse_ms"]
    assert h.type == "histogram"
    assert h.histogram_count() == 1.0
    assert h.histogram_sum() == pytest.approx(12.0)
    buckets = h.histogram_buckets()
    assert buckets[-1][0] == float("inf") and buckets[-1][1] == 1.0
    # labels parse; strict mode raises on garbage
    s = promtext.parse_labels('{a="x",le="+Inf"}')
    assert s == {"a": "x", "le": "+Inf"}
    # escape decoding is a left-to-right scan: an escaped backslash
    # followed by 'n' is backslash+n, never a newline
    assert promtext.parse_labels('{p="C:\\\\net"}') == {"p": "C:\\net"}
    assert promtext.parse_labels('{p="a\\nb\\"c"}') == {"p": 'a\nb"c'}
    with pytest.raises(ValueError):
        promtext.parse_exposition("no_type_sample 1\n", strict=True)
    # value() is the UNLABELED sample only: a federated family whose
    # labeled per-replica samples precede the aggregate must not have
    # one replica misread as the process total
    doc = ("# HELP fleet_x d\n# TYPE fleet_x counter\n"
           'fleet_x{replica="a:1"} 5\nfleet_x{replica="b:2"} 7\n'
           "fleet_x 12\n")
    assert promtext.parse_exposition(doc, strict=True)["fleet_x"] \
        .value() == 12.0
    doc2 = ("# HELP fleet_y d\n# TYPE fleet_y counter\n"
            'fleet_y{replica="a:1"} 5\n')
    assert promtext.parse_exposition(doc2)["fleet_y"].value() is None


def test_promtext_merged_histogram_percentile():
    # two replicas' cumulative buckets, element-wise summed
    merged = [(10.0, 40.0), (100.0, 80.0), (float("inf"), 80.0)]
    p50 = promtext.merged_histogram_percentile(merged, 50)
    assert p50 == pytest.approx(10.0)  # rank 40 sits at bucket edge
    p99 = promtext.merged_histogram_percentile(merged, 99)
    assert 10.0 < p99 <= 100.0
    # +Inf-censored: estimate past the top finite edge reports it
    merged = [(10.0, 1.0), (float("inf"), 100.0)]
    assert promtext.merged_histogram_percentile(merged, 99) == 10.0
    assert promtext.merged_histogram_percentile([], 99) is None
    assert promtext.merged_histogram_percentile(
        [(10.0, 0.0), (float("inf"), 0.0)], 99) is None


def test_graftcheck_validator_is_the_shared_module():
    """The lint's validator and the runtime scraper must be ONE
    implementation (the extraction satellite's whole point)."""
    from tools.graftcheck.passes import stat_catalog as sc
    bad = "paddle_tpu_x{le=} 1\n"
    assert sc.validate_exposition(bad)
    assert promtext.validate_lines(bad)
    # the pass re-exports the shared regexes
    assert sc._SAMPLE_RE is promtext.SAMPLE_RE


# ---------------------------------------------------------------------------
# router federation
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def two_replica_fleet():
    lg_mod = lg
    pred, shapes = lg_mod.build_synthetic(4, 8, 1)
    servers = []
    for _ in range(2):
        eng = ServingEngine(pred.clone(), workers=1)
        eng.warmup({"x": (4,)})
        servers.append(ServingServer(eng).start())
    router = Router([s.url for s in servers], poll_interval_ms=200.0,
                    autostart=False, slo_fast_s=2.0, slo_slow_s=6.0)
    rserver = RouterServer(router).start()
    router.poll_once()
    yield router, rserver, servers
    rserver.close()
    for s in servers:
        s.close()


def _post_predict(url, n=6):
    body = json.dumps(
        {"inputs": {"x": np.random.RandomState(0)
                    .rand(1, 4).tolist()}}).encode()
    for _ in range(n):
        req = urllib.request.Request(
            url + "/predict", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200


def test_federation_two_replicas_aggregate_equals_sum(
        two_replica_fleet):
    router, rserver, servers = two_replica_fleet
    _post_predict(rserver.url)
    router.poll_once()
    # counter motion BETWEEN two sweeps is what a windowed rate needs
    _post_predict(rserver.url)
    time.sleep(0.25)
    router.poll_once()
    with urllib.request.urlopen(rserver.url + "/fleetz?window_s=30",
                                timeout=30) as r:
        fz = json.loads(r.read())
    assert fz["window_s"] == 30.0
    rids = sorted(fz["replicas"])
    assert len(rids) == 2
    for rid in rids:
        assert fz["replicas"][rid]["up"] is True
        assert fz["replicas"][rid]["scrape_age_ms"] is not None
    agg = fz["aggregate"]["counters"]["serving_http_requests"]
    per = [fz["replicas"][rid]["counters"]["serving_http_requests"]
           for rid in rids]
    assert agg["total"] == sum(per)
    assert agg["replicas"] == 2
    assert agg["rate_per_s"] is not None and agg["rate_per_s"] > 0
    # gauges aggregate sum AND max
    gq = fz["aggregate"]["gauges"]
    assert any(v["replicas"] == 2 and v["max"] is not None
               for v in gq.values())
    # merged latency histogram with interpolated percentiles
    hists = fz["aggregate"]["histograms"]
    req_ms = hists.get("serving_request_ms")
    assert req_ms and req_ms["count"] > 0 and req_ms["p99"] is not None
    # SLO/alert + autoscale + tsdb occupancy blocks ride along
    assert {a["name"] for a in fz["slo"]["alerts"]} == {
        "availability", "replica_availability", "p99"}
    assert all(a["state"] == "ok" for a in fz["slo"]["alerts"])
    assert fz["autoscale"]["wanted_replicas"] is not None
    assert fz["tsdb"]["series"] > 0
    assert fz["router"]["request_ms"]["p99"] is not None


def test_federation_labels_on_router_metrics(two_replica_fleet):
    router, rserver, servers = two_replica_fleet
    _post_predict(rserver.url, n=2)
    router.poll_once()
    with urllib.request.urlopen(rserver.url + "/metrics",
                                timeout=30) as r:
        text = r.read().decode()
    # strictly valid exposition INCLUDING the fleet families
    assert promtext.validate_lines(text) == []
    fams = promtext.parse_exposition(text, strict=True)
    fleet = fams["paddle_tpu_fleet_serving_http_requests"]
    assert fleet.type == "counter"
    labeled = [s for s in fleet.samples if "replica" in s.labels]
    bare = [s for s in fleet.samples if not s.labels]
    assert len(labeled) == 2 and len(bare) == 1
    # the unlabeled aggregate equals the sum of the labeled samples
    assert bare[0].value == sum(s.value for s in labeled)
    rids = {r_.rid for r_ in router._all()}
    assert {s.labels["replica"] for s in labeled} == rids


def test_fleetz_statusz_and_healthz_carry_alerts(two_replica_fleet):
    router, rserver, servers = two_replica_fleet
    router.poll_once()
    with urllib.request.urlopen(rserver.url + "/statusz",
                                timeout=30) as r:
        sz = json.loads(r.read())
    assert sz["fleet"]["slo"]["alerts"]
    with urllib.request.urlopen(rserver.url + "/healthz",
                                timeout=30) as r:
        hz = json.loads(r.read())
    assert hz["alerts_firing"] == []
    # federation off: /fleetz still answers, explicitly disabled
    router2 = Router([], federate=False, autostart=False)
    try:
        fz = router2.fleetz()
        assert fz["federate"] is False and fz["aggregate"] is None
    finally:
        router2.close()


def test_fleetz_window_s_rejects_nonpositive_and_nonnumeric(
        two_replica_fleet):
    """``/fleetz?window_s=`` must 400 on garbage instead of silently
    clamping: a dashboard asking for a zero/negative/NaN window would
    otherwise get numbers computed over a window it never asked for."""
    router, rserver, servers = two_replica_fleet
    for bad in ("0", "-5", "abc", "nan", "inf", "-inf"):
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                rserver.url + f"/fleetz?window_s={bad}", timeout=30)
        assert ei.value.code == 400, bad
    # an EMPTY value means "not given": the default window answers
    with urllib.request.urlopen(rserver.url + "/fleetz?window_s=",
                                timeout=30) as r:
        assert json.loads(r.read())["window_s"] == 60.0
    # a legitimate window still answers
    with urllib.request.urlopen(rserver.url + "/fleetz?window_s=12.5",
                                timeout=30) as r:
        assert json.loads(r.read())["window_s"] == 12.5


def test_usage_federation_multi_tenant_conservation(two_replica_fleet):
    """The usage observatory end to end on a live fleet THROUGH the
    router: tenant headers survive the forward hop, replicas book and
    conserve at tolerance 0, labeled per-tenant samples federate into
    per-(tenant, replica) series, /fleetz rolls them up, and the sweep
    records ``fleet_tenant_*`` dashboard series."""
    from paddle_tpu.serving import usage

    router, rserver, servers = two_replica_fleet
    tenants = ("tenant-red", "tenant-blue")
    body = json.dumps(
        {"inputs": {"x": np.random.RandomState(0)
                    .rand(1, 4).tolist()}}).encode()
    for i in range(8):
        req = urllib.request.Request(
            rserver.url + "/predict", data=body,
            headers={"Content-Type": "application/json",
                     "X-PaddleTPU-Tenant": tenants[i % 2]})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
    router.poll_once()
    time.sleep(0.25)
    router.poll_once()  # two sweeps: windowed deltas need motion
    # (1) every replica conserves at tolerance 0 and measured both
    # tenants' latency (the in-process servers share one ledger, so
    # the same conserved truth shows on each)
    for s in servers:
        with urllib.request.urlopen(s.url + "/usagez", timeout=30) as r:
            uz = json.loads(r.read())
        assert uz["enabled"] is True
        for field, c in uz["conservation"].items():
            assert c["delta"] == 0, (s.url, field, c)
        for t in tenants:
            assert uz["tenants"][t]["vector"]["requests"] > 0
            assert uz["tenants"][t]["request_ms"]["p99"] is not None
        assert uz["sketch"]["within_bound"] is True
    # (2) the replica exposition carries labeled samples + a bare
    # all-tenant total that equals their sum (the federation's anchor)
    with urllib.request.urlopen(servers[0].url + "/metrics",
                                timeout=30) as r:
        text = r.read().decode()
    assert promtext.validate_lines(text) == []
    fams = promtext.parse_exposition(text, strict=True)
    fam = fams["paddle_tpu_serving_tenant_requests"]
    labeled = [s for s in fam.samples if "tenant" in s.labels]
    bare = [s for s in fam.samples if not s.labels]
    assert len(bare) == 1 and labeled
    assert bare[0].value == sum(s.value for s in labeled)
    assert {t for t in tenants} <= {s.labels["tenant"] for s in labeled}
    # (3) /fleetz federates per-tenant rollups: totals summed across
    # replicas, reset-aware deltas measured, and the per-tenant sum
    # equals the all-tenant family total at tolerance 0
    with urllib.request.urlopen(rserver.url + "/fleetz?window_s=60",
                                timeout=30) as r:
        fz = json.loads(r.read())
    ften = fz["aggregate"]["tenants"]
    assert "requests" in ften
    for t in tenants:
        assert ften["requests"][t]["total"] > 0
        assert ften["requests"][t]["replicas"] == 2
        assert ften["requests"][t]["delta"] is not None
    fam_total = fz["aggregate"]["counters"][
        "serving_tenant_requests"]["total"]
    assert sum(v["total"] for v in ften["requests"].values()) \
        == fam_total
    # (4) the sweep recorded fleet_tenant_* series for dashboards
    for t in tenants:
        assert router._db.last(f"fleet_tenant_requests{{{t}}}") \
            is not None
    # (5) per-(tenant, replica) series exist for every replica — the
    # reset-aware evidence conservation leans on after a respawn
    for rep_ in router._all():
        for t in tenants:
            assert router._db.points(
                f"serving_tenant_requests{{{t}}}[{rep_.rid}]"), (
                rep_.rid, t)
    # stray: a malformed header books to the default tenant, never a
    # new key (the sketch's key-space guard, end to end)
    req = urllib.request.Request(
        rserver.url + "/predict", data=body,
        headers={"Content-Type": "application/json",
                 "X-PaddleTPU-Tenant": "bad tenant!!"})
    with urllib.request.urlopen(req, timeout=30) as r:
        assert r.status == 200
    with urllib.request.urlopen(servers[0].url + "/usagez",
                                timeout=30) as r:
        uz = json.loads(r.read())
    assert "bad tenant!!" not in uz["tenants"]
    assert usage.default_tenant() in uz["tenants"]


def test_router_burn_alert_fires_on_dead_fleet_and_clears():
    """Deterministic alert cycle without processes: health polls
    against an unbound port fail -> replica_availability burns -> the
    alert fires once both windows agree, then clears after the
    (synthetic) recovery ages the fast window out."""
    router = Router(["http://127.0.0.1:9"], poll_interval_ms=50.0,
                    autostart=False, slo_fast_s=0.4, slo_slow_s=1.0,
                    slo_burn_threshold=2.0)
    try:
        deadline = time.monotonic() + 10.0
        fired = False
        while time.monotonic() < deadline:
            router.poll_once()
            if router.burn_monitor.firing():
                fired = True
                break
            time.sleep(0.05)
        assert fired, "replica_availability alert never fired"
        assert "replica_availability" in router.burn_monitor.firing()
        # recovery: stop failing (no more polls), feed clean poll
        # counters so the fast window ages the failures out
        db = router._db
        t = time.monotonic()
        with router._lock:
            n = dict(router._n)
        for i in range(1, 30):
            db.record("router_polls_total",
                      n["health_polls"] + 10 * i, ts=t + i * 0.1)
            db.record("router_poll_failures_total",
                      n["health_poll_failures"], ts=t + i * 0.1)
        st = router.burn_monitor.evaluate(now=t + 3.0)
        by_name = {a["name"]: a for a in st["alerts"]}
        assert by_name["replica_availability"]["state"] == "ok"
    finally:
        router.close()


# ---------------------------------------------------------------------------
# TTFT / inter-token timelines
# ---------------------------------------------------------------------------

def test_ttft_spans_admit_to_first_token_through_chunked_prefill():
    """Structural TTFT contract: with chunked prefill the first token
    arrives only after EVERY chunk paid out (one per scheduler
    iteration), and the TTFT histogram's measurement covers that whole
    span — claim, each chunk, and any interleaved decode work."""
    eng = GenerationEngine(TINY_LLAMA, num_slots=2, max_seq_len=64,
                           max_new_tokens=6, attn_impl="xla", seed=0,
                           paged=True, page_tokens=8, prefill_chunk=8,
                           prefix_reuse=False)
    try:
        prompt = np.arange(1, 25)  # 24 tokens = 3 chunks of 8
        res = eng.submit(prompt, 4).result(120)
        tl = res["timeline"]
        chunks = [e for e in tl["events"] if e["event"] == "chunk"]
        assert len(chunks) == 3
        assert [c["base"] for c in chunks] == [0, 8, 16]
        # first token strictly after the last chunk
        assert tl["token_ms"][0] >= chunks[-1]["at_ms"]
        assert res["ttft_ms"] == tl["token_ms"][0] == tl["ttft_ms"]
        # ttft >= prefill time is the "including interleave" claim:
        # admission-to-first-token, not prefill-only
        assert res["ttft_ms"] >= res["prefill_ms"] - 1e-6
        assert res["ttft_ms"] >= res["queue_wait_ms"] - 1e-6
        st = eng.stats()
        assert st["ttft_ms"]["count"] == 1
        assert st["inter_token_ms"]["count"] == len(res["tokens"]) - 1
        # inter-token gaps match the timeline's own arithmetic
        gaps = [round(b - a, 3) for a, b in
                zip(tl["token_ms"], tl["token_ms"][1:])]
        assert tl["inter_token_ms"]["max"] == pytest.approx(
            max(gaps), abs=1e-3)
    finally:
        eng.close()


def test_ttft_exemplar_trace_ids_resolve_in_tracez():
    eng = GenerationEngine(TINY_LLAMA, num_slots=2, max_seq_len=64,
                           max_new_tokens=6, attn_impl="xla", seed=0)
    try:
        results = [eng.submit(np.arange(1, 6 + i), 3).result(120)
                   for i in range(3)]
        tz = eng.tracez()
        known = {r["trace_id"] for r in tz["recent"]} \
            | {r["trace_id"] for r in tz["slowest"]}
        assert {r["trace_id"] for r in results} <= known
        assert tz["ttft_exemplars"]
        for ex in tz["ttft_exemplars"]:
            assert ex["trace_id"] in known
        # every stored record carries its timeline
        assert all(r["timeline"] is not None for r in tz["recent"])
        # the sequence spans share the request trace ids
        seq = {s.trace_id for s in telemetry.get_spans()
               if s.name == "generation/sequence"}
        assert {r["trace_id"] for r in results} <= seq
    finally:
        eng.close()


def test_ttft_histograms_on_live_metrics_and_stream(tmp_path):
    """/metrics exposes serving_ttft_ms / serving_inter_token_ms after
    traffic; the streaming /generate contract delivers per-token lines
    + a final summary, and the http loadgen measures client TTFT."""
    pred, shapes = lg.build_synthetic(4, 8, 1)
    eng = ServingEngine(pred, workers=1)
    gen = GenerationEngine(TINY_LLAMA, num_slots=2, max_seq_len=64,
                           max_new_tokens=8, attn_impl="xla", seed=0,
                           deadline_ms=60000.0)
    eng.attach_generator(gen)
    gen.warmup()  # cold compiles must not deadline-shed the loop
    srv = ServingServer(eng).start()
    try:
        mk = lg.prompt_maker(64, 4, 8, 4.0, 6)
        rep = lg.run_closed_loop_generate_http(srv.url, mk, 6, 2,
                                               stream=True)
        assert rep["ok"] == 6 and rep["failed"] == 0
        assert rep["ttft_ms"]["count"] == 6
        assert rep["inter_token_ms"]["count"] > 0
        with urllib.request.urlopen(srv.url + "/metrics",
                                    timeout=30) as r:
            text = r.read().decode()
        assert promtext.validate_lines(text) == []
        fams = promtext.parse_exposition(text)
        assert fams["paddle_tpu_serving_ttft_ms"].histogram_count() \
            >= 6
        assert fams["paddle_tpu_serving_inter_token_ms"] \
            .histogram_count() > 0
        # exemplars ride the histogram objects into /tracez
        with urllib.request.urlopen(srv.url + "/tracez",
                                    timeout=30) as r:
            tz = json.loads(r.read())
        gen_tz = tz["generation"]
        assert gen_tz["ttft_exemplars"]
        known = {rec["trace_id"] for rec in gen_tz["recent"]} \
            | {rec["trace_id"] for rec in gen_tz["slowest"]}
        assert gen_tz["ttft_exemplars"][0]["trace_id"] in known
        # check_slo TTFT/ITL bounds: generous passes, absent fails
        slo = lg.check_slo(rep, ttft_ms=60000.0, itl_ms=60000.0)
        assert slo["ok"], slo
        slo = lg.check_slo(rep, ttft_ms=0.0001)
        assert not slo["ok"] and "TTFT" in slo["violations"][0]
        plain = lg.run_closed_loop_generate_http(srv.url, mk, 2, 1,
                                                 stream=False)
        slo = lg.check_slo(plain, ttft_ms=60000.0)
        assert not slo["ok"]  # unmeasurable != vacuous pass
    finally:
        srv.close()


def test_stream_through_router_is_not_buffered():
    """The router's streaming passthrough must deliver token lines AS
    THEY ARE GENERATED: with decode steps slowed to ~40 ms, a client
    measuring through the router must see TTFT well below the total
    and inter-token gaps near the injected delay — a buffered forward
    (the route() path's read-to-EOF) would show ttft ≈ total and
    gaps ≈ 0."""
    from paddle_tpu import fault

    pred, shapes = lg.build_synthetic(4, 8, 1)
    eng = ServingEngine(pred, workers=1)
    gen = GenerationEngine(TINY_LLAMA, num_slots=2, max_seq_len=64,
                           max_new_tokens=16, attn_impl="xla", seed=0,
                           deadline_ms=60000.0)
    eng.attach_generator(gen)
    gen.warmup()
    srv = ServingServer(eng).start()
    router = Router([srv.url], poll_interval_ms=200.0, autostart=False)
    rserver = RouterServer(router).start()
    router.poll_once()
    try:
        fault.configure("decode_step:delay:40~1.0")
        body = json.dumps({"prompt": list(range(1, 9)),
                           "max_new_tokens": 10,
                           "stream": True}).encode()
        outcome, ntok, ttft, gaps = lg._http_generate_stream(
            rserver.url + "/generate", body, 120.0)
        assert outcome == "ok" and ntok == 10
        total = ttft + sum(gaps)
        # 9 inter-token gaps of >= 40ms each: a buffered forward would
        # put all of that into ttft and none into the gaps
        assert sum(1 for g in gaps if g >= 30.0) >= 7, gaps
        assert ttft < total * 0.5, (ttft, total)
        # the router booked it as a routed 200 with a latency sample
        # (poll: the client returns on the final NDJSON line, a beat
        # before the router's post-stream accounting runs)
        deadline = time.monotonic() + 5.0
        while router._db.last("router_request_ms") is None \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert router._db.last("router_request_ms") is not None
        fault.configure("")
        # containment parity with route(): an injected connect-level
        # failure on the stream path strikes and (single replica, no
        # alternate) surfaces the explicit no_ready 503 — never a hung
        # connection
        fault.configure("router_forward:fail@1")
        outcome, ntok, _, _ = lg._http_generate_stream(
            rserver.url + "/generate", body, 30.0)
        assert outcome == "failed" and ntok == 0
        fault.configure("")
        # a spent deadline sheds BEFORE any forward, stream or not
        req = urllib.request.Request(
            rserver.url + "/generate", data=body,
            headers={"Content-Type": "application/json",
                     "X-PaddleTPU-Deadline-Ms": "0.0"})
        try:
            urllib.request.urlopen(req, timeout=30)
            assert False, "expected 503 deadline shed"
        except urllib.error.HTTPError as e:
            doc = json.loads(e.read())
            assert e.code == 503 and doc["reason"] == "deadline", doc
    finally:
        fault.configure("")
        rserver.close()
        srv.close()


def test_timeline_off_with_telemetry_off():
    eng = GenerationEngine(TINY_LLAMA, num_slots=1, max_seq_len=64,
                           max_new_tokens=4, attn_impl="xla", seed=0)
    try:
        pt.set_flags({"FLAGS_telemetry": 0})
        res = eng.generate(np.arange(1, 6), 3)
        assert "timeline" not in res
        assert eng.stats()["ttft_ms"]["count"] == 0
        assert eng.tracez()["recent"] == []
        # the per-request switch forces it back on without telemetry
        res = eng.submit(np.arange(1, 6), 3, timeline=True).result(120)
        assert res["timeline"]["token_ms"]
    finally:
        pt.set_flags({"FLAGS_telemetry": 1})
        eng.close()
