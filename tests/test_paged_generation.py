"""Paged KV cache tests: block-paged decode bit-exactness vs the dense
cache, shared-prefix copy-on-write reuse, page refcount lifecycle,
chunked-prefill interleaving, and pool-exhaustion ``cache_full``.

The load-bearing contracts (ISSUE 11 acceptance):

* **Bit-exact vs dense** — with chunking and prefix reuse off, the
  paged engine's token streams AND per-step logits equal the dense
  engine's at tolerance 0 (``np.array_equal``) on ragged concurrent
  prompts spanning page boundaries (len = page-1 / page / page+1).
  The mechanism: paged prefill runs the *same* forward graph as dense
  (only the cache-insert op differs), and ``kv_pool_gather``
  reconstructs the dense logical cache layout so ``cached_attention``
  is the identical einsum at the identical contraction length.
* **COW isolation** — pages a prefix-index hit maps into a slot are
  never written by that slot (decode and tail-prefill writes target
  pages past the shared prefix; idle/pad writes redirect to the trash
  page), so concurrent borrowers cannot corrupt each other — asserted
  both on token streams and on the raw pool bytes.
* **Refcounts** — a reclaimed slot's pages return to the free list
  except those the prefix index still holds; eviction frees them too.
* **Chunked prefill** — a long prompt pays out one chunk per scheduler
  iteration while a rider keeps decoding (decode steps advance between
  chunks), and the rider's stream stays bit-exact.
* **Pool exhaustion** — a budget beyond the pool finishes
  ``cache_full`` with exactly ``usable_pages * page_tokens -
  prompt_len + 1`` tokens.
"""
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.serving import GenerationEngine, batcher
from paddle_tpu.serving.generation import PagePool, PrefixIndex

# GQA config (kv_heads < heads) so the paged gather runs under cache
# expansion, matching tests/test_generation.py
MODEL = dict(vocab_size=61, hidden=32, num_layers=2, num_heads=4,
             num_kv_heads=2, intermediate=64)
PAGE = 16


@pytest.fixture(scope="module")
def dense_ref():
    """Dense-cache reference engine; paged engines share its scope so
    both sides bind identical weights."""
    eng = GenerationEngine(MODEL, num_slots=3, max_seq_len=96,
                           max_new_tokens=8, keep_logits=True,
                           attn_impl="xla", seed=0, queue_cap=64,
                           deadline_ms=600000.0, paged=False)
    yield eng
    eng.close()


def _paged(dense, **kw):
    base = dict(num_slots=3, max_seq_len=96, max_new_tokens=8,
                keep_logits=True, attn_impl="xla", seed=0,
                queue_cap=64, deadline_ms=600000.0, paged=True,
                page_tokens=PAGE, prefill_chunk=0, prefix_reuse=False)
    base.update(kw)
    return GenerationEngine(MODEL, scope=dense.scope, **base)


@pytest.fixture(scope="module")
def paged_ref(dense_ref):
    """Module-shared paged engine (prefix reuse ON, chunking off) —
    one program-build cost for the bit-exactness / COW / refcount
    tests; tests needing deterministic pool counts drain the prefix
    index first via :func:`_drain_index`."""
    eng = _paged(dense_ref, prefix_reuse=True)
    yield eng
    eng.close()


def _drain_index(eng):
    while eng._prefix is not None and eng._prefix.evict_one():
        pass
    assert eng._pool.live_pages == 0


# ---------------------------------------------------------------------------
# op level: scatter/gather round trip + trash-page redirect
# ---------------------------------------------------------------------------

def test_kv_pool_write_gather_roundtrip():
    """Rows land in the block-table-routed pages at the right in-page
    offsets; rows beyond Lengths redirect to the trash page; gather
    reassembles the dense logical layout."""
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        block = main.global_block()
        pool = block.create_var(name="t_pool", persistable=True,
                                shape=[4, 1, 4, 2], dtype="float32",
                                stop_gradient=True)
        new = layers.data("new", [2, 1, 3, 2], dtype="float32",
                          append_batch_size=False)
        positions = layers.data("positions", [2], dtype="int32",
                                append_batch_size=False)
        bt = layers.data("bt", [2, 2], dtype="int32",
                         append_batch_size=False)
        lengths = layers.data("lengths", [2], dtype="int32",
                              append_batch_size=False)
        out = layers.kv_pool_write(pool, new, positions, bt, lengths)
        view = layers.kv_pool_gather(out, bt)
    scope = pt.Scope()
    scope.set_var("t_pool", np.zeros((4, 1, 4, 2), "float32"))
    new_v = np.arange(12, dtype="float32").reshape(2, 1, 3, 2)
    # slot 0: 3 rows from logical position 3 (crosses page boundary
    # 3 -> page bt[0,0]=1 off 3; 4,5 -> page bt[0,1]=2 off 0,1)
    # slot 1: only 1 valid row at logical 0 -> page bt[1,0]=3 off 0;
    # its 2 invalid rows must land on the trash page 0
    got_pool, got_view = pt.Executor().run(
        main,
        feed={"new": new_v,
              "positions": np.array([3, 0], "int32"),
              "bt": np.array([[1, 2], [3, 0]], "int32"),
              "lengths": np.array([3, 1], "int32")},
        fetch_list=[out, view], scope=scope)
    want = np.zeros((4, 1, 4, 2), "float32")
    want[1, 0, 3] = new_v[0, 0, 0]
    want[2, 0, 0] = new_v[0, 0, 1]
    want[2, 0, 1] = new_v[0, 0, 2]
    want[3, 0, 0] = new_v[1, 0, 0]
    # trash page (0) caught the two invalid rows of slot 1 — exact
    # contents indeterminate (duplicate scatter), but nothing else may
    # be touched
    assert np.array_equal(got_pool[1:], want[1:])
    # gather: slot 0's logical view is pages [1, 2] flattened
    assert np.array_equal(got_view[0, :, 0:8],
                          got_pool[[1, 2]].reshape(1, 8, 2))
    assert np.array_equal(got_view[1, :, 0:4],
                          got_pool[[3]].reshape(1, 4, 2))


def test_chunk_spans():
    assert batcher.chunk_spans(0, 20, 8) == [(0, 8), (8, 16), (16, 20)]
    assert batcher.chunk_spans(32, 40, 8) == [(32, 40)]
    assert batcher.chunk_spans(5, 5, 8) == []
    assert batcher.chunk_spans(0, 20, 0) == [(0, 20)]


# ---------------------------------------------------------------------------
# allocator / prefix index units
# ---------------------------------------------------------------------------

def test_page_pool_refcounts():
    pool = PagePool(5)  # pages 1..4 usable
    a, b = pool.alloc(), pool.alloc()
    assert {a, b} == {1, 2} and pool.free_pages == 2
    pool.incref([a])          # a shared (slot + index)
    pool.decref([a, b])       # slot releases both
    assert pool.free_pages == 3 and pool.refcount(a) == 1
    pool.decref([a])          # index releases a
    assert pool.free_pages == 4 and pool.live_pages == 0
    assert pool.alloc() is not None
    with pytest.raises(ValueError):
        PagePool(1)           # no room beyond the trash page


def test_prefix_index_lookup_register_evict():
    pool = PagePool(8)
    idx = PrefixIndex(pool, 4)
    prompt = np.arange(1, 11, dtype="int64")     # 10 tokens, 2 full pages
    p0, p1 = pool.alloc(), pool.alloc()
    idx.register(prompt, [p0, p1])
    assert pool.refcount(p0) == 2 and pool.refcount(p1) == 2
    # exact-prefix hit; a diverging prompt misses
    assert idx.lookup(np.arange(1, 14, dtype="int64")) == [p0, p1]
    other = np.arange(1, 14, dtype="int64")
    other[2] = 55
    assert idx.lookup(other) == []
    # a prompt equal to one indexed page must leave >= 1 token to
    # prefill: only page 0 may be served for a 5-token prompt, and
    # NOTHING for a 4-token prompt
    assert idx.lookup(np.arange(1, 6, dtype="int64")) == [p0]
    assert idx.lookup(np.arange(1, 5, dtype="int64")) == []
    pool.decref([p0, p1])     # the registering slot finishes
    assert pool.free_pages == 5  # 7 usable; index still holds p0, p1
    assert idx.evict_one() and pool.free_pages == 6
    assert idx.evict_one() and pool.free_pages == 7
    assert not idx.evict_one()
    # flush: the decode-crash integrity valve drops every entry
    q0, q1 = pool.alloc(), pool.alloc()
    idx.register(prompt, [q0, q1])
    pool.decref([q0, q1])
    assert idx.flush() == 2 and len(idx) == 0
    assert pool.free_pages == 7 and pool.live_pages == 0


# ---------------------------------------------------------------------------
# bit-exactness: paged == dense, tolerance 0, across page boundaries
# ---------------------------------------------------------------------------

def test_paged_bitexact_concurrent_ragged(dense_ref, paged_ref):
    """Prompts of page-1 / page / page+1 tokens decode CONCURRENTLY in
    the paged grid; every request's token stream and per-step logits
    are bit-equal to the dense engine's.  (The prompts are distinct
    randoms — no prefix hits — so this exercises the pure paged path;
    registration alone cannot perturb streams.)"""
    eng = paged_ref
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, MODEL["vocab_size"], size=n).tolist()
               for n in (PAGE - 1, PAGE, PAGE + 1)]
    steps = [6, 5, 7]
    fd = [dense_ref.submit(p, n) for p, n in zip(prompts, steps)]
    rd = [f.result(120) for f in fd]
    fp = [eng.submit(p, n) for p, n in zip(prompts, steps)]
    rp = [f.result(120) for f in fp]
    for a, b in zip(rd, rp):
        assert a["tokens"] == b["tokens"]
        assert a["finish"] == b["finish"] == "length"
        for i, (la, lb) in enumerate(zip(a["logits"], b["logits"])):
            assert np.array_equal(np.asarray(la), np.asarray(lb)), \
                f"step {i}: paged logits drifted (max |d|=" \
                f"{np.abs(np.asarray(la) - np.asarray(lb)).max()})"
    # every slot-held page was returned: only index-registered full
    # prefix pages stay live
    st = eng.stats()["paged"]
    assert st["pages_live"] == st["prefix_index_entries"]
    _drain_index(eng)


# ---------------------------------------------------------------------------
# shared-prefix reuse: hits skip prefill, COW isolation holds
# ---------------------------------------------------------------------------

def test_prefix_reuse_cow_isolation(dense_ref, paged_ref):
    """Requests sharing a page-aligned system header reuse its pages:
    the borrowers skip the header's prefill (counters prove it), their
    token streams stay bit-exact vs dense, concurrent borrowers don't
    corrupt each other, and the shared pages' raw bytes are untouched
    by the borrowers' decode writes (the COW contract)."""
    eng = paged_ref
    _drain_index(eng)
    hits0 = eng.stats()["counters"]["prefix_hits"]
    rng = np.random.RandomState(11)
    header = rng.randint(1, MODEL["vocab_size"], size=2 * PAGE
                         ).tolist()
    tails = [rng.randint(1, MODEL["vocab_size"], size=7).tolist()
             for _ in range(3)]
    # donor run registers the header's 2 pages
    ra = eng.generate(header + tails[0], 6)
    refs = [dense_ref.generate(header + t, 6) for t in tails]
    assert ra["tokens"] == refs[0]["tokens"]
    assert eng.stats()["counters"]["prefix_hits"] == hits0
    # shared-page bytes before the borrowers run
    idx_pages = sorted(
        p for p in range(1, eng.num_pages)
        if eng._pool.refcount(p) > 0)
    assert len(idx_pages) == 2
    pool_k0 = np.asarray(eng.scope.find_var("llama.pool_k_0"))
    shared_before = pool_k0[idx_pages].copy()
    # two borrowers decode CONCURRENTLY, both hitting the header
    futs = [eng.submit(header + t, 6) for t in tails[1:]]
    results = [f.result(120) for f in futs]
    for res, ref in zip(results, refs[1:]):
        assert res["tokens"] == ref["tokens"], \
            "borrower stream drifted — shared pages corrupted?"
        assert res["prefix_hit_tokens"] == 2 * PAGE
    st = eng.stats()
    assert st["counters"]["prefix_hits"] == hits0 + 2
    # the reused pages' bytes are bit-identical after the borrowers
    # wrote their private pages
    pool_k0 = np.asarray(eng.scope.find_var("llama.pool_k_0"))
    assert np.array_equal(pool_k0[idx_pages], shared_before), \
        "a borrower's write leaked into a shared prefix page"


def test_refcount_release_on_reclaim(dense_ref, paged_ref):
    """Finished slots return every private page; only the prefix
    index's refs persist, and eviction releases those too."""
    eng = paged_ref
    _drain_index(eng)
    rng = np.random.RandomState(13)
    header = rng.randint(1, MODEL["vocab_size"], size=PAGE).tolist()
    for i in range(3):
        tail = rng.randint(1, MODEL["vocab_size"], size=5).tolist()
        eng.generate(header + tail, 4)
    st = eng.stats()["paged"]
    # exactly the 1 indexed header page is live; all private pages
    # (tail + decode growth, per request) went back to the free list
    # at slot reclaim
    assert st["prefix_index_entries"] == 1
    assert st["pages_live"] == 1
    assert st["pages_free"] == eng.num_pages - 2
    assert eng.kv_live_bytes == eng.page_bytes
    _drain_index(eng)


# ---------------------------------------------------------------------------
# chunked prefill: long prompts interleave with decode steps
# ---------------------------------------------------------------------------

def test_chunked_prefill_interleaves_decode(dense_ref):
    """A long prompt pays out in chunks while a rider keeps decoding:
    decode steps advance BETWEEN chunks (one chunk per scheduler
    iteration — the inter-token-latency bound), and both streams stay
    correct."""
    eng = _paged(dense_ref, prefill_chunk=8, max_new_tokens=64)
    try:
        rng = np.random.RandomState(17)
        rider_prompt = rng.randint(1, MODEL["vocab_size"],
                                   size=4).tolist()
        long_prompt = rng.randint(1, MODEL["vocab_size"],
                                  size=40).tolist()
        rider_fut = eng.submit(rider_prompt, 36)
        deadline = time.monotonic() + 60
        while eng.stats()["counters"]["decode_steps"] < 3:
            assert time.monotonic() < deadline, "rider never decoded"
            time.sleep(0.01)
        s0 = eng.stats()["counters"]
        long_res = eng.submit(long_prompt, 4).result(120)
        s1 = eng.stats()["counters"]
        chunks = s1["prefill_chunks"] - s0["prefill_chunks"]
        assert chunks == 5  # ceil(40 / 8)
        # the rider decoded between chunks: >= one decode step per
        # chunk boundary (the scheduler runs at most one chunk, then a
        # grid step, per iteration)
        assert s1["decode_steps"] - s0["decode_steps"] >= chunks - 1
        rider_res = rider_fut.result(120)
        ref_long = dense_ref.generate(long_prompt, 4)
        rider_ref = dense_ref.generate(rider_prompt, 36)
        assert long_res["tokens"] == ref_long["tokens"]
        assert rider_res["tokens"] == rider_ref["tokens"], \
            "rider stream corrupted by interleaved chunk prefill"
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# pool exhaustion: cache_full exactness + recovery
# ---------------------------------------------------------------------------

def test_pool_exhaustion_cache_full(dense_ref):
    """A budget beyond the pool finishes cache_full with EXACTLY
    usable_pages * page_tokens - prompt_len + 1 tokens (every page
    filled, the +1 is the prefill's token which costs no cache row
    until the step after), and the freed pages serve the next
    request."""
    eng = GenerationEngine(MODEL, scope=dense_ref.scope, num_slots=1,
                           max_seq_len=96, attn_impl="xla", seed=0,
                           queue_cap=64, deadline_ms=600000.0,
                           paged=True, page_tokens=8, num_pages=5,
                           prefill_chunk=0, prefix_reuse=False)
    try:
        prompt = list(range(1, 11))          # 10 tokens
        capacity = (eng.num_pages - 1) * eng.page_tokens  # 32
        res = eng.generate(prompt, 500)
        assert res["finish"] == "cache_full"
        assert len(res["tokens"]) == capacity - len(prompt) + 1
        # pool drained and fully recovered
        assert eng._pool.live_pages == 0
        res2 = eng.generate(prompt, 500)
        assert res2["finish"] == "cache_full"
        assert res2["tokens"] == res["tokens"]
    finally:
        eng.close()


def test_loadgen_shared_prefix_prompts():
    """tools/serving_loadgen.py --gen-prompt-dist shared-prefix: every
    prompt starts with the SAME header, tails vary, determinism
    holds."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "lg", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "serving_loadgen.py"))
    lg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lg)
    mk = lg.prompt_maker(64, 4, 8, 8.0, 16, pool=32,
                         prompt_dist="shared-prefix", prefix_tokens=24)
    mk2 = lg.prompt_maker(64, 4, 8, 8.0, 16, pool=32,
                          prompt_dist="shared-prefix", prefix_tokens=24)
    header = mk(0)[0][:24]
    tails = set()
    for i in range(32):
        p, out_len = mk(i)
        assert np.array_equal(p[:24], header)
        assert 24 + 4 <= p.size <= 24 + 8
        assert 1 <= out_len <= 16
        assert np.array_equal(p, mk2(i)[0])  # deterministic
        tails.add(p[24:].tobytes())
    assert len(tails) > 1  # tails actually vary
    with pytest.raises(ValueError):
        lg.prompt_maker(64, 4, 8, 8.0, 16, prompt_dist="zipf")
    with pytest.raises(ValueError):
        lg.prompt_maker(64, 4, 8, 8.0, 16,
                        prompt_dist="shared-prefix", prefix_tokens=0)


def test_pool_stall_requeues_until_pages_free(dense_ref):
    """Pool exhaustion during PREFILL while other sequences hold the
    pages is transient saturation, not a broken request: the prefill
    requeues at the queue head (`serving_kv_pool_stalls`) and succeeds
    once the live sequence finishes — zero failed requests."""
    eng = GenerationEngine(MODEL, scope=dense_ref.scope, num_slots=2,
                           max_seq_len=64, attn_impl="xla", seed=0,
                           queue_cap=64, deadline_ms=600000.0,
                           paged=True, page_tokens=8, num_pages=6,
                           prefill_chunk=0, prefix_reuse=False,
                           autostart=False)
    try:
        rng = np.random.RandomState(19)
        # A: short prompt, long budget — claims first, holds pages
        # while decoding.  B: 30-token prompt needing 4 pages; only 3
        # are free while A lives -> deterministic stall, then success
        fa = eng.submit(rng.randint(1, MODEL["vocab_size"],
                                    size=10).tolist(), 24)
        b_prompt = rng.randint(1, MODEL["vocab_size"],
                               size=30).tolist()
        fb = eng.submit(b_prompt, 4)
        eng.start()
        ra, rb = fa.result(120), fb.result(120)
        assert ra["finish"] == "length" and rb["finish"] == "length"
        ref = dense_ref.generate(b_prompt, 4)
        assert rb["tokens"] == ref["tokens"]
        n = eng.stats()["counters"]
        assert n["pool_stalls"] >= 1
        assert n["failed"] == 0
    finally:
        eng.close()


def test_paged_config_validation():
    with pytest.raises(ValueError):  # not a power of two
        GenerationEngine(MODEL, num_slots=1, max_seq_len=96,
                         autostart=False, paged=True, page_tokens=12)
    with pytest.raises(ValueError):  # does not divide max_seq_len
        GenerationEngine(MODEL, num_slots=1, max_seq_len=100,
                         autostart=False, paged=True, page_tokens=16)
