"""Enforce/error layer (reference platform/enforce.h): taxonomy,
enforce helpers, and op-context attachment at the infer/lower
boundaries."""
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import errors


def test_taxonomy_is_catchable_at_base():
    for err in (errors.InvalidArgumentError, errors.NotFoundError,
                errors.OutOfRangeError, errors.UnimplementedError,
                errors.ResourceExhaustedError,
                errors.PreconditionNotMetError):
        with pytest.raises(errors.EnforceNotMet):
            raise err("boom")


def test_dual_inheritance_matches_python_idiom():
    # framework code catches EnforceNotMet; user code catching the
    # stdlib family still works (reference keeps errno-style codes)
    with pytest.raises(ValueError):
        raise errors.InvalidArgumentError("x")
    with pytest.raises(NotImplementedError):
        raise errors.UnimplementedError("x")
    with pytest.raises(KeyError):
        raise errors.NotFoundError("x")
    assert str(errors.NotFoundError("no quotes")) == "no quotes"


def test_enforce_helpers():
    errors.enforce(True, "fine")
    with pytest.raises(errors.InvalidArgumentError, match="bad"):
        errors.enforce(False, "bad")
    with pytest.raises(errors.EnforceNotMet, match="=="):
        errors.enforce_eq(3, 4)
    errors.enforce_shape_match((2, -1), (2, 7))
    with pytest.raises(errors.InvalidArgumentError):
        errors.enforce_shape_match((2, 3), (2, 4))


def test_infer_failure_names_the_op():
    prog = paddle_tpu.Program()
    with paddle_tpu.program_guard(prog):
        x = paddle_tpu.layers.data("x", shape=[4, 8], dtype="float32")
        y = paddle_tpu.layers.data("y", shape=[5, 9], dtype="float32")
        with pytest.raises(errors.EnforceNotMet) as ei:
            paddle_tpu.layers.matmul(x, y)  # inner dims mismatch
    msg = str(ei.value)
    assert "matmul" in msg and "operator context" in msg


def test_unregistered_op_is_not_found():
    prog = paddle_tpu.Program()
    with paddle_tpu.program_guard(prog):
        block = prog.global_block()
        with pytest.raises(errors.NotFoundError):
            block.append_op(type="definitely_not_an_op", inputs={},
                            outputs={}, attrs={})


def test_lowering_failure_carries_op_context():
    # gather with an out-of-graph dtype error at lowering time: feed a
    # program whose lowering raises inside jax and check the wrap
    prog = paddle_tpu.Program()
    startup = paddle_tpu.Program()
    with paddle_tpu.program_guard(prog, startup):
        x = paddle_tpu.layers.data("x", shape=[2, 3], dtype="float32")
        out = paddle_tpu.layers.reshape(x, shape=[7])  # 6 elems -> 7
    exe = paddle_tpu.Executor()
    with pytest.raises(errors.EnforceNotMet) as ei:
        exe.run(prog, feed={"x": np.zeros((2, 3), np.float32)},
                fetch_list=[out])
    assert "reshape" in str(ei.value)
