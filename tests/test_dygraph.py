"""Imperative-mode tests (reference tests/unittests/test_imperative_*.py)."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import dygraph, layers, optimizer
from paddle_tpu.dygraph import (BatchNorm, Conv2D, Embedding, LayerNorm,
                                Linear, Pool2D, Sequential, declarative,
                                load_dygraph, no_grad, save_dygraph,
                                to_variable)


def test_basic_autograd():
    with dygraph.guard():
        x = to_variable(np.array([[1.0, 2.0], [3.0, 4.0]], "float32"))
        x.stop_gradient = False
        y = x * x + 2.0 * x          # dy/dx = 2x + 2
        loss = layers.reduce_sum(y)
        loss.backward()
        np.testing.assert_allclose(x.gradient(),
                                   2 * x.numpy() + 2, rtol=1e-6)


def test_gradient_accumulation_and_clear():
    with dygraph.guard():
        x = to_variable(np.ones((2, 2), "float32"))
        x.stop_gradient = False
        layers.reduce_sum(x * 3.0).backward()
        np.testing.assert_allclose(x.gradient(), 3 * np.ones((2, 2)))
        layers.reduce_sum(x * 3.0).backward()
        np.testing.assert_allclose(x.gradient(), 6 * np.ones((2, 2)))
        x.clear_gradient()
        assert x.gradient() is None


def test_no_grad():
    with dygraph.guard():
        x = to_variable(np.ones((2,), "float32"))
        x.stop_gradient = False
        with no_grad():
            y = x * 2.0
        assert y.stop_gradient


def test_mlp_trains():
    with dygraph.guard():
        model = Sequential(Linear(16, 32, act="relu"), Linear(32, 4))
        opt = optimizer.AdamOptimizer(1e-2,
                                      parameter_list=model.parameters())
        rng = np.random.RandomState(0)
        x_np = rng.rand(8, 16).astype("float32")
        y_np = (x_np @ rng.rand(16, 4)).argmax(1).reshape(-1, 1)
        for i in range(20):
            x, y = to_variable(x_np), to_variable(y_np)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(model(x), y))
            loss.backward()
            opt.minimize(loss)
            model.clear_gradients()
            if i == 0:
                first = float(loss)
        assert float(loss) < first * 0.7


def test_cnn_batchnorm_train_eval():
    with dygraph.guard():
        class Net(dygraph.Layer):
            def __init__(self):
                super().__init__()
                self.conv = Conv2D(1, 6, 5, act="relu")
                self.bn = BatchNorm(6)
                self.pool = Pool2D(2, "max", 2)
                self.fc = Linear(6 * 12 * 12, 10)

            def forward(self, x):
                x = self.pool(self.bn(self.conv(x)))
                return self.fc(layers.reshape(x, [0, -1]))

        m = Net()
        opt = optimizer.SGDOptimizer(0.1, parameter_list=m.parameters())
        x = to_variable(np.random.rand(4, 1, 28, 28).astype("float32"))
        y = to_variable(np.random.randint(0, 10, (4, 1)).astype("int64"))
        for _ in range(3):
            loss = layers.mean(layers.softmax_with_cross_entropy(m(x), y))
            loss.backward()
            opt.minimize(loss)
            m.clear_gradients()
        assert not np.allclose(m.bn._mean.numpy(), 0)  # stats updated
        m.eval()
        mean_before = m.bn._mean.numpy().copy()
        m(x)
        np.testing.assert_allclose(m.bn._mean.numpy(), mean_before)


def test_embedding_layernorm():
    with dygraph.guard():
        emb = Embedding([50, 8])
        ln = LayerNorm(8)
        ids = to_variable(np.random.randint(0, 50, (4, 6)).astype("int64"))
        out = ln(emb(ids))
        assert out.shape == (4, 6, 8)
        np.testing.assert_allclose(np.asarray(out._value).mean(-1),
                                   np.zeros((4, 6)), atol=1e-5)


def test_state_dict_save_load():
    with dygraph.guard():
        m = Sequential(Linear(4, 8), Linear(8, 2))
        tmp = tempfile.mkdtemp()
        path = os.path.join(tmp, "model")
        save_dygraph(m.state_dict(), path)
        m2 = Sequential(Linear(4, 8), Linear(8, 2))
        params, opt_state = load_dygraph(path)
        assert opt_state is None
        m2.set_state_dict(params)
        for (n1, p1), (n2, p2) in zip(m.named_parameters(),
                                      m2.named_parameters()):
            np.testing.assert_array_equal(p1.numpy(), p2.numpy())


def test_declarative_matches_eager():
    with dygraph.guard():
        m = Sequential(Linear(6, 12, act="relu"), Linear(12, 3))
        x_np = np.random.rand(5, 6).astype("float32")
        eager_out = m(to_variable(x_np)).numpy()

        static_fn = declarative(lambda x: m(x))
        static_out = static_fn(x_np).numpy()
        np.testing.assert_allclose(eager_out, static_out, rtol=1e-5)
        # cached second call, different data
        x2 = np.random.rand(5, 6).astype("float32")
        np.testing.assert_allclose(static_fn(x2).numpy(),
                                   m(to_variable(x2)).numpy(), rtol=1e-5)


def test_dygraph_dataparallel_api():
    with dygraph.guard():
        m = dygraph.DataParallel(Linear(4, 2))
        x = to_variable(np.random.rand(3, 4).astype("float32"))
        out = m(x)
        assert out.shape == (3, 2)
        loss = layers.mean(out)
        scaled = m.scale_loss(loss)       # world_size 1: identity
        scaled.backward()
        m.apply_collective_grads()        # no-op at world_size 1
        assert m.parameters()[0].gradient() is not None


def test_dropout_modes():
    with dygraph.guard():
        d = dygraph.Dropout(p=0.5)
        x = to_variable(np.ones((100, 100), "float32"))
        out_train = d(x).numpy()
        assert (out_train == 0).mean() > 0.3
        d.eval()
        out_eval = d(x).numpy()
        np.testing.assert_allclose(out_eval, 0.5 * np.ones((100, 100)),
                                   rtol=1e-6)  # downgrade_in_infer


def test_optimizer_momentum_matches_static():
    """Same model/data/optimizer: dygraph loop == static executor loop."""
    rng = np.random.RandomState(3)
    x_np = rng.rand(6, 5).astype("float32")
    y_np = rng.rand(6, 1).astype("float32")
    w0 = rng.rand(5, 1).astype("float32")

    # static
    from paddle_tpu.framework.initializer import NumpyArrayInitializer
    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    with pt.program_guard(main, startup):
        x = layers.data("x", [6, 5], append_batch_size=False)
        y = layers.data("y", [6, 1], append_batch_size=False)
        pred = layers.fc(x, 1, param_attr=pt.ParamAttr(
            initializer=NumpyArrayInitializer(w0)), bias_attr=False)
        loss = layers.mean(layers.square_error_cost(pred, y))
        optimizer.MomentumOptimizer(0.1, 0.9).minimize(loss)
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    static_losses = [float(exe.run(main, feed={"x": x_np, "y": y_np},
                                   fetch_list=[loss], scope=scope)[0])
                     for _ in range(5)]

    # dygraph
    with dygraph.guard():
        lin = Linear(5, 1, param_attr=pt.ParamAttr(
            initializer=NumpyArrayInitializer(w0)), bias_attr=False)
        opt = optimizer.MomentumOptimizer(0.1, 0.9,
                                          parameter_list=lin.parameters())
        dy_losses = []
        for _ in range(5):
            xv, yv = to_variable(x_np), to_variable(y_np)
            l = layers.mean(layers.square_error_cost(lin(xv), yv))
            l.backward()
            opt.minimize(l)
            lin.clear_gradients()
            dy_losses.append(float(l))
    np.testing.assert_allclose(dy_losses, static_losses, rtol=1e-5)
