"""Usage observatory: the per-tenant cost ledger and its space-saving
heavy-hitter sketch.

Contracts under test, straight from the ledger's docstrings:

* **top-K exactness** — a tenant admitted before the sketch fills and
  never demoted keeps an EXACT vector (err == 0) no matter how
  adversarially the long tail churns around it;
* **conservation** — per-field sums over tracked tenants plus
  ``~other`` equal the ledger totals at tolerance 0, always, including
  under demotion storms;
* **determinism** — demotion picks the minimum-weight tenant with a
  lexicographic tie-break, so identical booking sequences produce
  identical sketches;
* **memory bound** — at most ``top_k`` tracked vectors (+1 for
  ``~other``) regardless of tenant cardinality;
* **zero work when off** — ``FLAGS_usage=0`` never constructs the
  ledger singleton (``peek_ledger() is None`` is the witness) and the
  serving request path books nothing.
"""
import importlib.util
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.serving import ServingEngine, usage
from paddle_tpu.serving.usage import (COST_FIELDS, OTHER_TENANT,
                                      UsageLedger, split_ints)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_loadgen():
    spec = importlib.util.spec_from_file_location(
        "serving_loadgen_usage_tests",
        os.path.join(REPO, "tools", "serving_loadgen.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _assert_conserved(led: UsageLedger):
    cons = led.conservation()
    assert set(cons) == set(COST_FIELDS)
    for field, c in cons.items():
        assert c["delta"] == 0, (field, c)


# ---------------------------------------------------------------------------
# integer cost splitting
# ---------------------------------------------------------------------------

def test_split_ints_sums_exactly_and_is_deterministic():
    rng = np.random.RandomState(3)
    for _ in range(200):
        total = int(rng.randint(0, 10_000))
        weights = [int(x) for x in rng.randint(0, 50, size=rng.randint(
            1, 9))]
        shares = split_ints(total, weights)
        assert sum(shares) == total
        assert shares == split_ints(total, weights)
        assert all(s >= 0 for s in shares)
    assert split_ints(7, []) == []
    # zero weights split evenly, remainder by index order
    assert sum(split_ints(10, [0, 0, 0])) == 10


def test_tenant_normalization_guards_the_key_space():
    assert usage.normalize_tenant(None) == usage.default_tenant()
    assert usage.normalize_tenant("") == usage.default_tenant()
    # a claim on the reserved aggregate bucket is remapped, not booked
    assert usage.normalize_tenant(OTHER_TENANT) == usage.default_tenant()
    assert usage.normalize_tenant("x" * 65) == usage.default_tenant()
    assert usage.normalize_tenant("no spaces!") == usage.default_tenant()
    assert usage.normalize_tenant("org:team.svc-1") == "org:team.svc-1"


# ---------------------------------------------------------------------------
# heavy-hitter sketch
# ---------------------------------------------------------------------------

def test_topk_exact_under_adversarial_interleaving():
    """Four heavy tenants booked early must survive a churning tail of
    hundreds of one-shot tenants with EXACT vectors: the tail demotes
    only itself (min weight) while the heavies' weights keep them
    pinned in the sketch."""
    led = UsageLedger(top_k=8)
    heavies = [f"heavy-{i}" for i in range(4)]
    booked = dict.fromkeys(heavies, 0)
    # seed each heavy past any single's possible inherited weight
    for h in heavies:
        for _ in range(50):
            led.book(h, requests=1, tokens_in=3)
            booked[h] += 1
    rng = np.random.RandomState(0)
    for i in range(600):
        led.book(f"one-shot-{i}", requests=1, tokens_in=1)
        h = heavies[int(rng.randint(len(heavies)))]
        led.book(h, requests=1, tokens_in=3)
        booked[h] += 1
    snap = led.snapshot()
    for h in heavies:
        assert h in snap["tenants"], h
        vec = snap["tenants"][h]
        assert vec["requests"] == booked[h]
        assert vec["tokens_in"] == 3 * booked[h]
    # exactness is certified: a never-demoted tenant carries err == 0
    uz = led.usagez()
    for h in heavies:
        assert uz["tenants"][h]["err"] == 0
    _assert_conserved(led)


def test_other_bucket_conserves_demoted_and_trailing_costs():
    led = UsageLedger(top_k=4)
    for i in range(40):
        led.book(f"t-{i:02d}", requests=1, tokens_out=5, page_us=7)
    snap = led.snapshot()
    # 40 tenants through a 4-slot sketch: everything demoted landed in
    # ~other and nothing was lost — per-field conservation at 0
    # (snapshot nests ~other inside "tenants" alongside the tracked 4)
    assert len(snap["tenants"]) <= 4 + 1
    _assert_conserved(led)
    assert snap["totals"]["requests"] == 40
    assert snap["totals"]["tokens_out"] == 200
    # a demoted tenant's TRAILING costs (requests=0 bookings: tokens
    # still decoding, pages still held) aggregate into ~other instead
    # of re-churning the sketch
    gone = sorted(set(f"t-{i:02d}" for i in range(40))
                  - set(snap["tenants"]))[0]
    before = led.snapshot()["tenants"]
    other_before = before[OTHER_TENANT]["tokens_out"]
    key = led.book(gone, tokens_out=9)
    assert key == OTHER_TENANT
    after = led.snapshot()
    assert after["tenants"].keys() == before.keys()
    assert after["tenants"][OTHER_TENANT]["tokens_out"] == \
        other_before + 9
    _assert_conserved(led)


def test_demotion_is_deterministic_min_weight_lexicographic():
    def run():
        led = UsageLedger(top_k=3)
        # equal weights: b, a, c each one request
        for t in ("b", "a", "c"):
            led.book(t, requests=1)
        # full sketch + a new requester: the tie among (a, b, c) breaks
        # to the lexicographically smallest — 'a' is demoted
        led.book("d", requests=1)
        return led

    led = run()
    snap = led.snapshot()
    assert set(snap["tenants"]) == {"b", "c", "d", OTHER_TENANT}
    # a's exact vector folded into ~other
    assert snap["tenants"][OTHER_TENANT]["requests"] == 1
    # the newcomer inherits the victim's weight as its overestimate
    assert led.usagez()["tenants"]["d"]["err"] == 1
    assert led.sketch_stats()["demotions"] == 1
    # identical sequences -> identical sketches, bit for bit
    led2 = run()
    assert led2.snapshot() == snap
    assert led2.usagez()["tenants"].keys() == led.usagez()[
        "tenants"].keys()
    _assert_conserved(led)


def test_sketch_memory_hard_bound_under_high_cardinality():
    led = UsageLedger(top_k=16)
    rng = np.random.RandomState(1)
    for i in range(5000):
        led.book(f"tenant-{int(rng.randint(100000)):06d}", requests=1,
                 tokens_in=int(rng.randint(10)))
        if i % 500 == 0:
            assert len(led._tenants) <= led.top_k
    sk = led.sketch_stats()
    assert sk["tracked"] <= sk["top_k"] == 16
    assert sk["capacity_vectors"] == 17
    assert sk["within_bound"] is True
    assert sk["demotions"] > 0
    _assert_conserved(led)


# ---------------------------------------------------------------------------
# flag-off zero work + live engine conservation
# ---------------------------------------------------------------------------

def _tiny_engine(lg):
    pred, _shapes = lg.build_synthetic(4, 8, 1)
    eng = ServingEngine(pred, workers=1)
    eng.warmup({"x": (4,)})
    return eng


def test_flags_usage_off_does_zero_per_request_work():
    lg = _load_loadgen()
    pt.set_flags({"FLAGS_usage": False})
    usage.reset_ledger()
    try:
        assert not usage.enabled()
        eng = _tiny_engine(lg)
        feed = {"x": np.random.RandomState(0).rand(1, 4)
                .astype("float32")}
        for _ in range(3):
            eng.predict(feed, timeout=60)
        # a tenant kwarg with the flag off must not resurrect the path
        eng.submit(feed, tenant="acme").result(60)
        eng.close()
        # the witness: the singleton was NEVER constructed — no vector,
        # no histogram, no lock was ever allocated on the request path
        assert usage.peek_ledger() is None
    finally:
        pt.set_flags({"FLAGS_usage": True})
        usage.reset_ledger()


def test_engine_books_tenants_and_conserves_against_totals():
    lg = _load_loadgen()
    pt.set_flags({"FLAGS_usage": True})
    usage.reset_ledger()
    try:
        eng = _tiny_engine(lg)
        feed = {"x": np.random.RandomState(0).rand(1, 4)
                .astype("float32")}
        for i in range(6):
            eng.submit(feed, tenant=("acme" if i % 2 else "umbrella")
                       ).result(60)
        # headerless traffic books to the default tenant, never drops
        eng.predict(feed, timeout=60)
        eng.close()
        led = usage.peek_ledger()
        assert led is not None
        snap = led.snapshot()
        assert snap["tenants"]["acme"]["requests"] == 3
        assert snap["tenants"]["umbrella"]["requests"] == 3
        assert snap["tenants"][usage.default_tenant()]["requests"] == 1
        # the tentpole contract: per-tenant sums equal the global
        # counters at tolerance 0 — and the ledger totals saw every
        # request the engine's own counter did (7 of them)
        assert snap["totals"]["requests"] == 7
        assert snap["totals"]["served"] == 7
        _assert_conserved(led)
        # per-tenant latency measured for every tracked tenant
        uz = led.usagez()
        for t in ("acme", "umbrella"):
            rm = uz["tenants"][t]["request_ms"]
            assert rm is not None and rm["count"] == 3
            assert rm["p99"] is not None
    finally:
        usage.reset_ledger()
