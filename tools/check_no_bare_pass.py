#!/usr/bin/env python
"""Lint: fail on ``except ...: pass`` handlers that silently swallow the
failure.

A robustness regression shipped exactly this way once: checkpoint.py's
orbax path fell back to pickle under a bare ``except Exception: pass``,
hiding every storage error.  This gate rejects any handler whose body is
a lone ``pass`` unless the except/pass line carries an explicit waiver
comment ``# ok: <reason>`` (for genuinely-expected control flow, e.g.
``except StopIteration``).  Handlers that log or bump a monitor stat have
a multi-statement body and pass automatically.

Usage: python tools/check_no_bare_pass.py [root ...]   (default: paddle_tpu)
"""
from __future__ import annotations

import ast
import os
import sys

WAIVER = "# ok:"


def check_file(path: str):
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, path)
    except SyntaxError as e:
        return [(path, e.lineno or 0, f"syntax error: {e.msg}")]
    lines = src.splitlines()
    bad = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if len(node.body) == 1 and isinstance(node.body[0], ast.Pass):
            waived = any(WAIVER in lines[ln - 1]
                         for ln in (node.lineno, node.body[0].lineno)
                         if 0 < ln <= len(lines))
            if not waived:
                bad.append((path, node.lineno,
                            "`except: pass` swallows the failure -- log "
                            "it, bump a monitor stat, or waive with "
                            "`# ok: <reason>`"))
    return bad


def main(*roots: str) -> int:
    roots = roots or ("paddle_tpu",)
    bad = []
    for root in roots:
        if os.path.isfile(root):
            bad += check_file(root)
            continue
        for dirpath, _dirs, files in os.walk(root):
            for name in sorted(files):
                if name.endswith(".py"):
                    bad += check_file(os.path.join(dirpath, name))
    for path, lineno, msg in bad:
        print(f"{path}:{lineno}: {msg}")
    if bad:
        print(f"{len(bad)} bare `except: pass` handler(s) found")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main(*sys.argv[1:]))
