#!/usr/bin/env python
"""Lint: fail on ``except ...: pass`` handlers that silently swallow
the failure.

THIN SHIM: the analysis lives in graftcheck
(``tools/graftcheck/passes/exception_policy.py``, rule
``bare-except-pass``) — this CLI remains so existing docs/commands
keep working.  Prefer::

    python -m tools.graftcheck --rule exception-policy

Handlers whose body is a lone ``pass`` must log, bump a monitor stat,
or carry an explicit waiver comment ``# ok: <reason>``.

Usage: python tools/check_no_bare_pass.py [root ...] (default: paddle_tpu)
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.graftcheck import core  # noqa: E402


def main(*roots: str) -> int:
    roots = roots or ("paddle_tpu",)
    # one code path with `python -m tools.graftcheck`: syntax errors
    # fail the gate (an unparseable file could hide any number of
    # handlers), and gc-ok/baseline waivers apply identically — the
    # shim and the framework CLI must never disagree
    try:
        report = core.run(roots=roots,
                          rule_filter=["exception-policy"])
    except FileNotFoundError as e:
        print(f"check_no_bare_pass: {e}", file=sys.stderr)
        return 2
    for v in report.violations:
        print(v.render())
    n_rule = sum(v.rule == "bare-except-pass"
                 for v in report.violations)
    extra = len(report.violations) - n_rule
    if report.violations:
        print(f"{n_rule} bare `except: pass` handler(s) found"
              + (f" (+{extra} other finding(s))" if extra else ""))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main(*sys.argv[1:]))
