"""Per-step host-side dispatch overhead: synchronous vs async guard path.

Quantifies the tentpole of the async-executor work: with the
PR-1-era synchronous guard, every guarded ``Executor.run`` paid a
device->host fence (``bool(ok)``) plus a blocking fetch, serializing
dispatch; the deferred guard + ``run_async`` keep the whole step loop
fence-free (host_syncs stays O(1) over the run).

Measures HOST time spent inside the run call only — the time until the
step is dispatched, not until the device finishes — which is exactly the
overhead that caps dispatch pipelining.  Prints one JSON line:

    {"steps": N,
     "sync_ms_per_step":  <run(); fetch + per-step guard resolve>,
     "async_ms_per_step": <run_async(); no fence>,
     "sync_host_syncs": ..., "async_host_syncs": ...,
     "speedup": sync/async}

Run on the real chip for the numbers quoted in BENCH/PR descriptions;
on CPU the ordering is the same, the magnitudes smaller.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def build_net(hidden=256):
    import paddle_tpu as pt
    from paddle_tpu import layers, optimizer

    x = layers.data("x", [hidden])
    y = layers.data("y", [1])
    h = layers.fc(x, hidden, act="relu")
    pred = layers.fc(h, 1)
    loss = layers.mean(pt.layers.square_error_cost(pred, y))
    optimizer.SGDOptimizer(0.01).minimize(loss)
    return loss


def measure(steps=50, hidden=256, batch=64):
    import paddle_tpu as pt
    from paddle_tpu.monitor import stat_get
    from paddle_tpu.train_guard import TrainGuard

    loss = build_net(hidden)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(batch, hidden).astype("float32"),
            "y": rng.rand(batch, 1).astype("float32")}

    guard = TrainGuard(exe, loss, handle_sigterm=False)

    def timed(fn):
        fn()  # warm the program cache (compile off the clock)
        exe.sync()
        t0 = time.perf_counter()
        for _ in range(steps):
            fn()
        dt = time.perf_counter() - t0
        exe.sync()
        return dt / steps * 1e3

    # synchronous path: per-step resolve (interval=1) + blocking fetch —
    # the PR-1 behavior (bool(ok) + np.asarray every step)
    pt.set_flags({"FLAGS_guard_resolve_interval": 1})
    h0 = stat_get("host_syncs")
    sync_ms = timed(lambda: guard.step(feed, fetch_list=[loss]))
    sync_syncs = stat_get("host_syncs") - h0

    # async path: deferred guard, lazy fetches, no fence until sync()
    pt.set_flags({"FLAGS_guard_resolve_interval": 0})
    h0 = stat_get("host_syncs")
    async_ms = timed(lambda: guard.step_async(feed, fetch_list=[loss]))
    async_syncs = stat_get("host_syncs") - h0
    pt.set_flags({"FLAGS_guard_resolve_interval": 64})
    guard.close()

    return {"steps": steps, "hidden": hidden, "batch": batch,
            "sync_ms_per_step": round(sync_ms, 4),
            "async_ms_per_step": round(async_ms, 4),
            "sync_host_syncs": int(sync_syncs),
            "async_host_syncs": int(async_syncs),
            "speedup": round(sync_ms / max(async_ms, 1e-9), 2)}


if __name__ == "__main__":
    print(json.dumps(measure()))
