#!/usr/bin/env python
"""Fleet chaos harness: kill / hang / slow / poison scenarios against
a LIVE replica fleet under open-loop load, asserting an availability
budget.

The serving tier's answer to the training tier's fault-matrix tests:
every containment mechanism the stack claims — router connect-refused
retry, forward timeouts + timeout retry (hung replicas), health
ejection, supervisor crash respawn and the liveness SIGKILL, poison
request bisection, deadline shedding — is exercised against real
processes and real sockets, and the run FAILS unless:

* **zero collateral failures** — every failed request must be
  attributable to an injected fault (inside the fault window, or a
  deliberately poisoned request); a failure outside any window means
  containment leaked;
* **zero poison leaks** — a poisoned request that returned 200 means
  bisection served a row the model should have crashed on;
* **availability >= the budget** (default 99%) over all non-poisoned
  requests across every scenario, injected damage included;

* **the burn-rate alert contract holds** — the router's multi-window
  SLO burn-rate monitor (paddle_tpu/tsdb.py, windows scaled to
  scenario time) must FIRE inside every crash/hang fault window (a
  dead or wedged replica burns replica-availability budget at 10-30x)
  and CLEAR after recovery, and a clean scenario — the leading
  ``baseline`` (no injection at all), ``slow``, ``poison`` — must
  raise ZERO alerts (the false-positive guard).  Both verdicts are
  scenario errors riding the same hard gate as collateral failures
  (``totals.alert_errors`` in the report).

Scenarios (one shared fleet; traffic is open-loop ``POST /predict``
through the router):

=============  ==========================================  =============
scenario       injection                                   recovery path
=============  ==========================================  =============
crash          SIGKILL one replica mid-traffic             connect-refused retry +
                                                           supervisor respawn
hang           SIGSTOP one replica (PID alive, sockets     forward-timeout retry +
               open)                                       health ejection +
                                                           liveness SIGKILL/respawn
slow           ``router_forward:delay:<ms>~<p>`` fault in  none needed: slow is
               the router process (random per-forward      not failure — zero
               delay)                                      failures allowed
poison         every Nth request carries the
               ``FLAGS_serving_poison_value`` sentinel     bisection: poisoned
                                                           request 500s, riders
                                                           answer bit-exact
poison_paged   every Nth *generation prompt* carries a     prefill-time poison
               poisoned token while sharing a cached       check fires BEFORE any
               prefix with clean prompts (in-process       shared page is mapped:
               paged GenerationEngine, prefix reuse on)    exactly the poisoned
                                                           request fails; the
                                                           shared pages are
                                                           neither evicted nor
                                                           corrupted — every
                                                           clean stream stays
                                                           bit-exact and later
                                                           borrowers still hit
                                                           the prefix index
spec_storm     poisoned prompts + a ``decode_step:fail``   speculation never
               fault detonated MID-VERIFY while            widens the blast
               concurrent slots speculate over a shared    radius: fault victims
               cached prefix (in-process paged             fail inside their own
               GenerationEngine, FLAGS_serving_speculate   window (injected),
               on)                                         surviving clean
                                                           streams stay
                                                           bit-exact vs the
                                                           speculation-on
                                                           reference, rollback
                                                           counters balance
                                                           (accepted <=
                                                           proposed, rollbacks
                                                           <= drafts), and the
                                                           page pool drains to
                                                           ZERO live pages
disagg_crash   role-split generation fleet (2 prefill +    router affinity
               2 decode) under MIXED long-prompt/short-    containment: requests
               chat /generate load; SIGKILL a prefill      on the dead replica
               replica mid-handoff, then a decode          fail inside the fault
               replica holding live adopted segments       window (affinity_lost
                                                           for the decode kill —
                                                           never silently
                                                           re-prefilled), the
                                                           survivors keep
                                                           serving (zero
                                                           collateral), the
                                                           supervisor respawns
                                                           both, burn-rate
                                                           alerts fire in-window
                                                           and clear, and after
                                                           the storm every
                                                           replica's page pool
                                                           drains to ZERO live
                                                           pages (no leak)
embedding_     recsys fleet (3 ``--recsys`` replicas, the  degraded-not-failed:
shard_crash    ep-sharded embedding tier) under zipfian    fault-hit lookups
               sparse-id /predict load routed by the       serve cache/default
               ``embedding`` capability; a fleet-wide      rows and still 200
               ``embedding_gather:fail~p`` fault degrades  (booked as
               random shard gathers, then one replica is   ``serving_embedding_
               SIGKILLed mid-storm                         degraded``, bounded);
                                                           the kill heals by
                                                           router retry +
                                                           supervisor respawn
                                                           (zero collateral),
                                                           postmortem attributed,
                                                           hot-row hit rate
                                                           reported first-class,
                                                           and every cache's
                                                           pinned refcounts
                                                           drain to ZERO
hot_swap       rolling ``hot_swap`` weight rollout under   quiesce-and-commit
               mixed /predict + /generate load, then a     swap discipline (zero
               second rollout with one replica SIGKILLed   non-shed failures
               MID-COMMIT (``weight_swap:delay`` fault     outside the kill
               widens the window)                          window), monotonic
                                                           per-replica
                                                           weights-version flip
                                                           (zero torn
                                                           responses), restart
                                                           fallback converges
                                                           the killed slot,
                                                           post-swap outputs
                                                           bit-exact vs a fresh
                                                           predictor
=============  ==========================================  =============

Usage::

    python tools/chaos.py --replicas 3 --qps 40 --duration 6 \
        --scenarios crash,hang,slow,poison --availability-pct 99 \
        --out chaos.json

``bench.py run_chaos`` publishes the same report as ``legs.chaos``
and ``tools/perf_gate.py`` hard-fails any capture with collateral
failures or poison leaks (no anomaly flag shields them).
"""
from __future__ import annotations

import argparse
import json
import os
import queue as queue_mod
import signal
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the poison sentinel: representable exactly in float32 and JSON, far
# outside any real feature distribution
POISON = 1e30

# the generation-path sentinel must be a real token id (prompts are
# int ids, not floats); the paged scenario keeps every legitimate
# token >= POISON_TOKEN + 1 so only deliberate prompts carry it
POISON_TOKEN = 7

DEFAULT_SCENARIOS = ("baseline", "crash", "hang", "slow", "poison",
                     "poison_paged", "spec_storm", "disagg_crash",
                     "embedding_shard_crash", "hot_swap",
                     "noisy_neighbor")

# burn-rate scaling for the chaos run: scenario durations are seconds,
# not SRE hours, so the router's alert windows shrink to fractions of
# one scenario (fast proves "still happening", slow proves "real")
_ALERT_CLEAR_GRACE_S = 5.0


class _AlertSampler:
    """Samples the router burn-rate monitor's firing set on a fast
    clock while a scenario runs, so assertions can ask 'did an alert
    fire INSIDE the fault window' and 'was it clear at the end' from
    the recorded (t, names) trail instead of racing the live state."""

    def __init__(self, router, period_s: float = 0.05):
        self._router = router
        self._period = period_s
        self.samples: List[tuple] = []  # (monotonic_t, (name, ...))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="chaos-alert-sampler",
                                        daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.wait(self._period):
            self.samples.append(
                (time.monotonic(),
                 tuple(self._router.burn_monitor.firing())))

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5.0)

    def fired_between(self, t0: float, t1: float) -> List[str]:
        names = set()
        for t, firing in self.samples:
            if t0 <= t <= t1:
                names.update(firing)
        return sorted(names)

    def fired_ever(self) -> List[str]:
        names = set()
        for _, firing in self.samples:
            names.update(firing)
        return sorted(names)


# ---------------------------------------------------------------------------
# traffic: open-loop POST /predict with per-request attribution
# ---------------------------------------------------------------------------

def _bodies(feat: int, n: int = 16, seed: int = 0) -> List[bytes]:
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        row = rng.rand(1, feat).astype("float32")
        out.append(json.dumps({"inputs": {"x": row.tolist()}}).encode())
    return out


def _poison_body(feat: int) -> bytes:
    row = [[POISON] + [0.5] * (feat - 1)]
    return json.dumps({"inputs": {"x": row}}).encode()


def _post(url: str, body: bytes, timeout_s: float,
          tenant: Optional[str] = None):
    """One POST → (outcome, http_status).  Same taxonomy as the
    loadgen: replica/router backpressure 503s are ``shed`` (the
    router's ``no_ready_replicas`` = total availability loss =
    ``failed``), everything else non-200 is ``failed``."""
    headers = {"Content-Type": "application/json"}
    if tenant:
        headers["X-PaddleTPU-Tenant"] = tenant
    req = urllib.request.Request(url, data=body, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            r.read()
            return "ok", r.status
    except urllib.error.HTTPError as e:
        try:
            payload = e.read()
        except OSError:
            payload = b""  # ok: error body gone with the connection
        if e.code != 503:
            return "failed", e.code
        try:
            reason = json.loads(payload).get("reason")
        except (ValueError, AttributeError):
            reason = None
        return (("failed", e.code) if reason == "no_ready_replicas"
                else ("shed", e.code))
    except (OSError, TimeoutError, ValueError):
        return "failed", None


def run_traffic(url: str, feat: int, qps: float, duration_s: float,
                poison_every: int = 0, timeout_s: float = 15.0,
                workers: int = 16, route: str = "/predict",
                bodies: Optional[List[bytes]] = None,
                tenant_of=None) -> List[dict]:
    """Open-loop traffic: a pacing clock enqueues bodies at ``qps``; a
    poster pool sends them.  Every request is recorded with its
    monotonic start/end and whether it was deliberately poisoned —
    the attribution the collateral-failure contract needs.
    ``route``/``bodies`` repoint the storm (the disagg scenario sends
    generation bodies at ``/generate``); ``tenant_of(i)`` stamps the
    i-th request with a usage-attribution tenant header and records it
    (the noisy-neighbor scenario's client-side ground truth)."""
    predict = url.rstrip("/") + route
    bodies = bodies if bodies is not None else _bodies(feat)
    poison = _poison_body(feat)
    records: List[dict] = []
    lock = threading.Lock()
    pending: queue_mod.Queue = queue_mod.Queue()

    def poster():
        while True:
            item = pending.get()
            if item is None:
                return
            body, is_poison, t0, tenant = item
            outcome, status = _post(predict, body, timeout_s,
                                    tenant=tenant)
            t1 = time.monotonic()
            with lock:
                records.append({"t0": t0, "t1": t1, "outcome": outcome,
                                "status": status, "poison": is_poison,
                                "tenant": tenant,
                                "ms": (t1 - t0) * 1e3})

    pool = [threading.Thread(target=poster, daemon=True)
            for _ in range(workers)]
    for t in pool:
        t.start()
    period = 1.0 / max(qps, 0.001)
    t_start = time.monotonic()
    i = 0
    while True:
        now = time.monotonic()
        if now - t_start >= duration_s:
            break
        is_poison = bool(poison_every and (i + 1) % poison_every == 0)
        pending.put((poison if is_poison else bodies[i % len(bodies)],
                     is_poison, now,
                     tenant_of(i) if tenant_of is not None else None))
        i += 1
        sleep_for = t_start + i * period - time.monotonic()
        if sleep_for > 0:
            time.sleep(sleep_for)
    for _ in pool:
        pending.put(None)
    for t in pool:
        t.join()
    return records


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

def classify(records: List[dict], windows: List[tuple]) -> dict:
    """Attribute every outcome: a failure is *injected* when the
    request was poisoned or its lifetime overlaps a fault window,
    *collateral* otherwise (the hard-zero contract); a poisoned
    request that returned 200 is a *leak* (bisection served a row the
    model must crash on)."""
    n = {"requests": len(records), "ok": 0, "shed": 0,
         "injected_failures": 0, "collateral_failures": 0,
         "poison_leaks": 0, "poisoned": 0}
    ok_ms = []
    for r in records:
        if r["poison"]:
            n["poisoned"] += 1
        if r["outcome"] == "ok":
            n["ok"] += 1
            ok_ms.append(r["ms"])
            if r["poison"]:
                n["poison_leaks"] += 1
        elif r["outcome"] == "shed":
            n["shed"] += 1
        else:
            in_window = any(r["t1"] >= w0 and r["t0"] <= w1
                            for w0, w1 in windows)
            if r["poison"] or in_window:
                n["injected_failures"] += 1
            else:
                n["collateral_failures"] += 1
    nonpoison = n["requests"] - n["poisoned"]
    failed_nonpoison = sum(
        1 for r in records
        if r["outcome"] not in ("ok", "shed") and not r["poison"])
    n["availability_pct"] = round(
        100.0 * (1.0 - failed_nonpoison / max(1, nonpoison)), 3)
    if ok_ms:
        ok_ms.sort()
        n["p99_ms"] = round(
            ok_ms[min(len(ok_ms) - 1,
                      int(np.ceil(0.99 * len(ok_ms))) - 1)], 3)
    else:
        n["p99_ms"] = None
    return n


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------

def _wait_respawned_ready(rep, old_pid, timeout_s: float = 90.0
                          ) -> Optional[float]:
    """Block until the replica slot runs a NEW, ready process; returns
    the monotonic recovery instant (None on timeout)."""
    from paddle_tpu.serving.fleet import _healthz

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        proc = rep.proc
        if proc is not None and proc.pid != old_pid \
                and proc.poll() is None:
            h = _healthz(rep.url, timeout=2.0)
            if h is not None and h.get("ready"):
                return time.monotonic()
        time.sleep(0.1)
    return None


def _postmortem_verdict(victim, old_pid: int,
                        expect_attr: Optional[str] = None,
                        timeout_s: float = 30.0):
    """The crash-forensics contract for one induced death: the
    supervisor must BOOK the death (harvest + attribution), the
    harvest must have collected at least one flight-recorder artifact
    (the fault-window evidence — a self/rolling dump or the
    supervisor's kill mark), and the attribution must not be
    ``unexplained`` (and must match ``expect_attr`` when the scenario
    knows exactly how it killed).  Returns ``(death_record, error)``
    — ``death_record`` None when the death was never booked."""
    deadline = time.monotonic() + timeout_s
    death = None
    while time.monotonic() < deadline:
        d = victim.last_death
        if d is not None and d.get("pid") == old_pid:
            death = d
            break
        time.sleep(0.1)
    if death is None:
        return None, (f"supervisor never booked the induced death of "
                      f"pid {old_pid} (no harvest/attribution)")
    if not death["postmortems"]:
        return death, (f"no postmortem collected for induced death "
                       f"pid {old_pid} ({death['attribution']})")
    if death["attribution"] == "unexplained":
        return death, (f"induced death pid {old_pid} attributed "
                       f"unexplained despite {len(death['postmortems'])}"
                       f" artifact(s)")
    if expect_attr is not None and death["attribution"] != expect_attr:
        return death, (f"induced death pid {old_pid} attributed "
                       f"{death['attribution']!r}, expected "
                       f"{expect_attr!r}")
    return death, None


def _scenario(name: str, sup, router, url: str, cfg: dict) -> dict:
    """Run one scenario's traffic with its injection; returns the
    classified report + the raw records (for the aggregate)."""
    from paddle_tpu import fault

    qps, duration = cfg["qps"], cfg["duration_s"]
    feat = cfg["feat"]
    box: Dict[str, Optional[float]] = {"t_fault": None, "t_recover": None}
    error = None
    notes = {}
    injector = None
    poison_every = 0

    if name == "baseline":
        # clean traffic, no injection: the burn-rate false-positive
        # guard (zero alerts allowed) plus the usual hard-zero
        # collateral contract
        pass
    elif name in ("crash", "hang"):
        victim = sup._replicas[0]
        old_pid = victim.proc.pid
        sig = signal.SIGKILL if name == "crash" else signal.SIGSTOP

        def inject():
            time.sleep(duration * 0.25)
            box["t_fault"] = time.monotonic()
            try:
                os.kill(old_pid, sig)
            except OSError as e:
                box["error"] = f"inject: {e}"
                return
            box["t_recover"] = _wait_respawned_ready(victim, old_pid)

        notes["victim"] = victim.url
        if name == "hang":
            notes["hung_kills_before"] = victim.hung_kills
        injector = threading.Thread(target=inject, daemon=True)
        injector.start()
    elif name == "slow":
        # injected in THIS process: the router's forward hop randomly
        # stalls — latency rises, nothing may fail
        fault.configure(f"router_forward:delay:{cfg['slow_delay_ms']}"
                        f"~{cfg['slow_prob']}")
        notes["delay_ms"] = cfg["slow_delay_ms"]
        notes["delay_prob"] = cfg["slow_prob"]
    elif name == "poison":
        poison_every = cfg["poison_every"]
        notes["poison_every"] = poison_every
    else:
        raise ValueError(f"unknown scenario {name!r}")

    sampler = _AlertSampler(router)
    try:
        records = run_traffic(url, feat, qps, duration,
                              poison_every=poison_every,
                              timeout_s=cfg["timeout_s"])
    finally:
        if name == "slow":
            fault.configure("")  # restore: later scenarios run clean
    if injector is not None:
        injector.join(timeout=120.0)
        if box.get("error"):
            error = box["error"]
        elif box["t_fault"] is None:
            error = "injection never fired"
        elif box["t_recover"] is None:
            error = "victim never respawned ready"
    if name == "hang" and error is None:
        victim = sup._replicas[0]
        notes["hung_kills_after"] = victim.hung_kills
        if victim.hung_kills <= notes["hung_kills_before"]:
            # the supervisor must have done the killing — a recovery
            # via any other path means the watchdog did not fire
            error = "liveness watchdog never SIGKILLed the hung replica"
    unexplained_deaths = None
    if name in ("crash", "hang"):
        # crash-forensics contract: the induced death must be booked,
        # carry >=1 harvested artifact, and be attributed exactly as
        # induced (SIGKILL decodes to signal:SIGKILL; the watchdog's
        # kill mark decodes to hung_kill).  The per-scenario
        # unexplained count rides into totals for the perf_gate
        # hard-zero (None = the death was never even booked)
        death, pm_err = _postmortem_verdict(
            sup._replicas[0], old_pid,
            "signal:SIGKILL" if name == "crash" else "hung_kill")
        notes["postmortem"] = death
        if death is not None:
            unexplained_deaths = \
                1 if death["attribution"] == "unexplained" else 0
        if error is None and pm_err is not None:
            error = pm_err

    windows = []
    if box["t_fault"] is not None:
        # +grace: the router may still be converging (poll cadence)
        # right after the successor reports ready
        w_end = (box["t_recover"] or time.monotonic()) + 1.0
        windows.append((box["t_fault"], w_end))

    # burn-rate alert contract.  Fault scenarios (a window exists):
    # an alert must FIRE inside the window and CLEAR after recovery.
    # Clean scenarios (baseline / slow / poison): any firing alert is
    # a false positive.  Both are scenario errors — they ride the same
    # hard gate as collateral failures.
    alerts: Dict[str, object] = {}
    if windows:
        w0, w1 = windows[0]
        # the fast window must age past the fault before the clear
        # verdict; sample until cleared or the grace runs out
        clear_deadline = time.monotonic() \
            + router.burn_monitor.fast_s + _ALERT_CLEAR_GRACE_S
        while time.monotonic() < clear_deadline \
                and router.burn_monitor.firing():
            time.sleep(0.1)
        sampler.stop()
        fired = sampler.fired_between(w0, w1)
        still = router.burn_monitor.firing()
        alerts = {"fired_in_window": fired, "cleared": not still,
                  "still_firing": still}
        if error is None and not fired:
            error = ("burn-rate alert never fired inside the "
                     f"{name} fault window")
        elif error is None and still:
            error = (f"burn-rate alert(s) {still} never cleared "
                     f"after {name} recovery")
    else:
        sampler.stop()
        fired = sampler.fired_ever()
        alerts = {"fired": fired, "expected": "none"}
        if error is None and fired:
            error = (f"false-positive burn-rate alert(s) {fired} "
                     f"during clean scenario {name}")
    rep = classify(records, windows)
    rep["scenario"] = name
    rep["notes"] = notes
    rep["alerts"] = alerts
    if name in ("crash", "hang"):
        rep["unexplained_deaths"] = unexplained_deaths
    if box["t_fault"] is not None and box["t_recover"] is not None:
        rep["recovery_s"] = round(box["t_recover"] - box["t_fault"], 3)
    if name == "poison" and error is None:
        if rep["poisoned"] == 0:
            error = "no poisoned requests were sent"
        elif rep["injected_failures"] == 0 and rep["poison_leaks"] == 0:
            # every poisoned request was shed before reaching a model:
            # the run proved nothing about bisection
            error = "no poisoned request reached a model"
    if error is not None:
        rep["error"] = error
    rep["_records"] = records
    return rep


def _scenario_poison_paged(cfg: dict) -> dict:
    """Paged-path poison containment, in-process (the page pool and
    prefix index live inside a GenerationEngine, not behind the
    router): clean prompts sharing a system header decode bit-exact
    against a poison-free reference while every Nth prompt — sharing
    the SAME cached prefix — carries the poison token.

    The contract under test: the prefill-time poison check fires
    BEFORE the prefix index maps any shared page into the slot, so a
    poisoned prompt (a) fails exactly itself, (b) evicts nothing, and
    (c) cannot corrupt the shared pages other slots are concurrently
    reading — asserted by bit-exact rider streams AND by a post-storm
    borrower that must still hit the index and match the reference."""
    import paddle_tpu as pt
    from paddle_tpu.serving import GenerationEngine

    model = dict(vocab_size=64, hidden=32, num_layers=2, num_heads=4,
                 num_kv_heads=2, intermediate=64)
    eng_kw = dict(num_slots=4, max_seq_len=64, max_new_tokens=8,
                  attn_impl="xla", seed=0, queue_cap=256,
                  deadline_ms=600000.0, paged=True, page_tokens=8,
                  prefill_chunk=0, prefix_reuse=True)
    poison_every = max(2, int(cfg.get("poison_every", 5)))
    rng = np.random.RandomState(5)
    # all legitimate tokens sit above the sentinel id
    header = rng.randint(POISON_TOKEN + 1, 64, size=32).tolist()
    tails = [rng.randint(POISON_TOKEN + 1, 64, size=6).tolist()
             for _ in range(9)]
    n_steps = 3
    error = None
    notes: Dict[str, object] = {}
    records: List[dict] = []

    # poison-free reference streams run on the SAME engine before the
    # sentinel flag arms (the poison check reads the flag per prefill),
    # so stream equality is exact and only one engine pays the
    # program-build cost; the reference pass also pre-warms the prefix
    # index, making the storm all-borrowers — the sharper COW test
    old_flag = pt.get_flags("FLAGS_serving_poison_value")[
        "FLAGS_serving_poison_value"]
    eng = GenerationEngine(model, **eng_kw)
    try:
        want = [eng.generate(header + t, n_steps)["tokens"]
                for t in tails]
        pt.set_flags({"FLAGS_serving_poison_value":
                      str(float(POISON_TOKEN))})

        def run_one(i, poisoned):
            prompt = header + tails[i]
            if poisoned:
                prompt = prompt[:-1] + [POISON_TOKEN]
            t0 = time.monotonic()
            return i, poisoned, t0, eng.submit(prompt, n_steps)

        # the donor populates the prefix index first, then the storm:
        # clean borrowers and poisoned prompts in flight CONCURRENTLY
        donor = run_one(0, False)
        futs = [donor] + [run_one(i, i % poison_every == 0)
                          for i in range(1, len(tails) - 1)]
        for i, poisoned, t0, fut in futs:
            rec = {"t0": t0, "poison": poisoned, "status": None}
            try:
                res = fut.result(120)
                # a clean stream that drifted from the reference means
                # a poisoned neighbor corrupted shared state: that is
                # a containment break, counted as a (collateral)
                # failure even though the HTTP-level answer was 200
                rec["outcome"] = "ok" if (poisoned
                                          or res["tokens"] == want[i]) \
                    else "failed"
                if not poisoned and res["tokens"] != want[i]:
                    notes.setdefault("corrupted", []).append(i)
            except Exception:  # noqa: BLE001 — the failure taxonomy is
                # the record's job; poisoned failures are the injection
                rec["outcome"] = "failed"
            rec["t1"] = time.monotonic()
            rec["ms"] = (rec["t1"] - rec["t0"]) * 1e3
            records.append(rec)

        hits_during = eng.stats()["counters"]["prefix_hits"]
        # post-storm borrower: the shared pages must still be indexed
        # (not evicted by the poisoned prompts) and bit-exact
        last = len(tails) - 1
        t0 = time.monotonic()
        res = eng.generate(header + tails[last], n_steps)
        records.append({"t0": t0, "t1": time.monotonic(),
                        "ms": (time.monotonic() - t0) * 1e3,
                        "status": None, "poison": False,
                        "outcome": "ok" if res["tokens"] == want[last]
                        else "failed"})
        st = eng.stats()
        notes["prefix_hits"] = st["counters"]["prefix_hits"]
        notes["prefix_index_entries"] = \
            st["paged"]["prefix_index_entries"]
        notes["page_evictions"] = st["counters"]["page_evictions"]
        if res["tokens"] != want[last]:
            error = "post-storm borrower stream drifted (shared " \
                    "pages corrupted?)"
        elif st["counters"]["prefix_hits"] <= hits_during:
            error = "post-storm borrower missed the prefix index " \
                    "(poisoned prompts evicted shared pages?)"
        elif notes.get("corrupted"):
            error = f"clean stream(s) {notes['corrupted']} drifted " \
                    f"from the poison-free reference"
    finally:
        pt.set_flags({"FLAGS_serving_poison_value": old_flag})
        eng.close()

    rep = classify(records, [])
    rep["scenario"] = "poison_paged"
    rep["notes"] = notes
    if error is None:
        if rep["poisoned"] == 0:
            error = "no poisoned prompts were submitted"
        elif rep["poison_leaks"] == 0 and rep["injected_failures"] == 0:
            error = "no poisoned prompt reached the prefill check"
    if error is not None:
        rep["error"] = error
    rep["_records"] = records
    return rep


def _scenario_spec_storm(cfg: dict) -> dict:
    """Speculative-decoding storm, in-process (extends the
    ``poison_paged`` family): concurrent speculating slots share a
    cached prefix while every Nth prompt carries the poison token AND
    a ``decode_step:fail`` fault detonates MID-VERIFY (the verify
    chunk fires the same decode_step fault site as the plain step).

    The contract under test: speculation never widens the blast
    radius.  A mid-verify fault fails exactly the requests active at
    that instant (injected, window = each victim's own lifetime), a
    poisoned prompt fails exactly itself, and every clean stream that
    COMPLETES is bit-exact against the speculation-on poison-free
    reference — drift is collateral.  Afterward the rollback
    accounting must balance (accepted <= proposed, rollbacks <=
    drafts) and the page pool must drain to ZERO live pages once the
    prefix index is flushed: rejected drafts and fault-killed slots
    alike return every provisionally-held page."""
    import paddle_tpu as pt
    from paddle_tpu import fault as fault_mod
    from paddle_tpu.serving import GenerationEngine

    model = dict(vocab_size=64, hidden=32, num_layers=2, num_heads=4,
                 num_kv_heads=2, intermediate=64)
    eng_kw = dict(num_slots=4, max_seq_len=64, max_new_tokens=8,
                  attn_impl="xla", seed=0, queue_cap=256,
                  deadline_ms=600000.0, paged=True, page_tokens=8,
                  prefill_chunk=0, prefix_reuse=True,
                  speculate=True, spec_tokens=4, spec_ngram=3)
    poison_every = max(2, int(cfg.get("poison_every", 5)))
    # periodic prompts so the n-gram drafter fires every round: the
    # suffix trigram always has an earlier occurrence in the header,
    # and the distinct repetitive tails keep the streams per-request
    header = [11, 23, 42, 9] * 8
    tails = [[20 + i, 33, 20 + i, 33, 20 + i, 33] for i in range(9)]
    n_steps = 6
    error = None
    notes: Dict[str, object] = {}
    records: List[dict] = []
    windows: List[tuple] = []

    # speculation-on reference streams run on the SAME engine before
    # the poison flag and the fault injector arm — bit-exactness of
    # spec-vs-plain is the tentpole's own gate; here the reference
    # fixes the target the storm's survivors must still hit
    old_flag = pt.get_flags("FLAGS_serving_poison_value")[
        "FLAGS_serving_poison_value"]
    eng = GenerationEngine(model, **eng_kw)
    try:
        want = [eng.generate(header + t, n_steps)["tokens"]
                for t in tails]
        sp0 = eng.stats()["speculate"]
        pt.set_flags({"FLAGS_serving_poison_value":
                      str(float(POISON_TOKEN))})
        # the 9th decode_step hit lands a few scheduler iterations in,
        # with several speculating slots in flight; one-shot (not
        # sticky) so the post-storm borrower decodes fault-free
        fault_mod.configure("decode_step:fail@9")

        def run_one(i, poisoned):
            prompt = header + tails[i]
            if poisoned:
                prompt = prompt[:-1] + [POISON_TOKEN]
            t0 = time.monotonic()
            return i, poisoned, t0, eng.submit(prompt, n_steps)

        futs = [run_one(0, False)] \
            + [run_one(i, i % poison_every == 0)
               for i in range(1, len(tails) - 1)]
        victims = 0
        poison_hits = 0
        for i, poisoned, t0, fut in futs:
            rec = {"t0": t0, "poison": poisoned, "status": None}
            try:
                res = fut.result(120)
                # a clean stream that COMPLETED but drifted means the
                # storm corrupted shared state: collateral (no window
                # covers a successful-but-wrong answer)
                rec["outcome"] = "ok" if (poisoned
                                          or res["tokens"] == want[i]) \
                    else "failed"
                if not poisoned and res["tokens"] != want[i]:
                    notes.setdefault("corrupted", []).append(i)
            except Exception as e:  # noqa: BLE001 — taxonomy below
                rec["outcome"] = "failed"
                rec["t1"] = time.monotonic()
                if "injected decode_step" in str(e):
                    # mid-verify fault victim: injected by
                    # construction, so its own lifetime is the window
                    victims += 1
                    windows.append((t0, rec["t1"]))
                elif poisoned:
                    poison_hits += 1
            rec.setdefault("t1", time.monotonic())
            rec["ms"] = (rec["t1"] - rec["t0"]) * 1e3
            if rec["outcome"] == "failed" and rec["poison"]:
                poison_hits = max(poison_hits, 1)
            records.append(rec)

        # disarm before the post-storm borrower: it must decode (and
        # speculate) clean, bit-exact, after the fault flushed the
        # prefix index and rolled every victim's pages back
        fault_mod.reset()
        last = len(tails) - 1
        t0 = time.monotonic()
        res = eng.generate(header + tails[last], n_steps)
        records.append({"t0": t0, "t1": time.monotonic(),
                        "ms": (time.monotonic() - t0) * 1e3,
                        "status": None, "poison": False,
                        "outcome": "ok" if res["tokens"] == want[last]
                        else "failed"})
        st = eng.stats()
        sp = st["speculate"]
        notes["spec"] = {k: sp[k] for k in
                         ("drafts", "tokens_proposed",
                          "tokens_accepted", "rollbacks",
                          "acceptance_rate")}
        notes["fault_victims"] = victims
        # drain accounting: every request resolved, so only the
        # prefix index may legitimately hold pages; flush it and the
        # pool must hit zero — anything left is a leaked draft page
        deadline = time.monotonic() + 5.0
        live = st["paged"]["pages_live"]
        while time.monotonic() < deadline:
            time.sleep(0.05)
            now_live = eng.stats()["paged"]["pages_live"]
            if now_live == live:
                break
            live = now_live
        eng._prefix.flush()
        leaked = eng.stats()["paged"]["pages_live"]
        notes["leaked_pages"] = leaked
        if res["tokens"] != want[last]:
            error = "post-storm borrower stream drifted (rollback " \
                    "left corrupt state behind?)"
        elif notes.get("corrupted"):
            error = f"clean stream(s) {notes['corrupted']} drifted " \
                    f"from the speculation-on reference"
        elif victims == 0:
            error = "decode_step fault never fired mid-verify"
        elif sp["drafts"] <= sp0["drafts"]:
            error = "no drafts proposed during the storm " \
                    "(speculation never exercised)"
        elif sp["tokens_accepted"] > sp["tokens_proposed"]:
            error = f"accepted {sp['tokens_accepted']} > proposed " \
                    f"{sp['tokens_proposed']} (counter imbalance)"
        elif sp["rollbacks"] > sp["drafts"]:
            error = f"rollbacks {sp['rollbacks']} > drafts " \
                    f"{sp['drafts']} (counter imbalance)"
        elif leaked > 0:
            error = f"{leaked} page(s) still live after drain " \
                    f"(rejected-draft rollback leaked)"
    finally:
        fault_mod.reset()
        pt.set_flags({"FLAGS_serving_poison_value": old_flag})
        eng.close()

    rep = classify(records, windows)
    rep["scenario"] = "spec_storm"
    rep["notes"] = notes
    rep["leaked_pages"] = notes.get("leaked_pages")
    if error is None:
        if rep["poisoned"] == 0:
            error = "no poisoned prompts were submitted"
        elif rep["poison_leaks"] == 0 and poison_hits == 0:
            error = "no poisoned prompt reached the prefill check"
    if error is not None:
        rep["error"] = error
    rep["_records"] = records
    return rep


def _scenario_disagg_crash(cfg: dict, log=print) -> dict:
    """Disaggregated-fleet containment: a role-split generation fleet
    (2 prefill + 2 decode replicas) serves MIXED long-prompt/
    short-chat ``/generate`` traffic through an affinity router while
    a prefill replica is SIGKILLed mid-handoff and then a decode
    replica is SIGKILLed while holding live adopted segments.

    The contract: (a) zero collateral failures — every failed request
    lies inside a fault window (prefill kills heal by the router's
    connect-refused retry onto the surviving prefill replica; decode
    kills surface as the explicit ``affinity_lost`` taxonomy, never a
    silent re-prefill); (b) the burn-rate alert fires inside each
    fault window and clears after recovery; (c) after the storm
    drains, EVERY replica's page pool reports zero live pages — a
    leaked page means a refcount path (export, adopt, failure) lost a
    decref; (d) the supervisor respawned both victims ready, roles
    pinned."""
    import paddle_tpu  # noqa: F401 — flags registered
    from paddle_tpu.serving import FleetSupervisor, Router, RouterServer
    from paddle_tpu.serving.fleet import _healthz

    duration = max(float(cfg["duration_s"]) * 1.5, 8.0)
    qps = min(float(cfg["qps"]), 10.0)  # generation >> /predict cost
    roles = ["prefill", "prefill", "decode", "decode"]
    argv = ["--feat", "8", "--hidden", "16", "--depth", "1",
            "--generate", "--gen-vocab", "64", "--gen-hidden", "32",
            "--gen-layers", "2", "--gen-heads", "4",
            "--gen-intermediate", "64", "--gen-slots", "4",
            "--gen-max-seq", "64", "--gen-max-new", "8",
            "--gen-page-tokens", "8",
            "--queue-cap", "512", "--deadline-ms", "60000"]
    # prefix reuse off: a drained pool must read EXACTLY zero live
    # pages (with reuse on, index-held pages are by-design residents)
    env = {"FLAGS_serving_prefix_reuse": "0"}
    error = None
    notes: Dict[str, object] = {"roles": roles}
    records: List[dict] = []
    windows: List[tuple] = []
    alerts: Dict[str, object] = {}
    leaked = None
    sup = FleetSupervisor(replicas=4, roles=roles, replica_argv=argv,
                          env=env, max_restarts=8, backoff_ms=100.0,
                          liveness_timeout_ms=cfg.get(
                              "liveness_timeout_ms", 1500.0))
    server = None
    sampler = None
    try:
        urls = sup.wait_ready(timeout_s=600)
        fast_s = max(1.0, duration / 4.0)
        slow_s = max(fast_s * 2.0, duration * 0.75)
        # the adopt hop carries a WHOLE generation (prefill hop +
        # decode to completion), not one /predict batch: derive its
        # bound from the caller's knob but floor it well above a
        # full generation on a contended host — a slow-but-healthy
        # adopt timing out outside a fault window would read as a
        # collateral failure and flake the hard-zero contract
        fwd_ms = max(4.0 * float(cfg.get("forward_timeout_ms", 800.0)),
                     5000.0)
        router = Router(urls, poll_interval_ms=100.0, stale_ms=1500.0,
                        eject_after=2, forward_timeout_ms=fwd_ms,
                        slo_fast_s=fast_s, slo_slow_s=slow_s)
        server = RouterServer(router).start()
        router.poll_once()
        if not router.disagg_active():
            raise RuntimeError("role-split fleet did not report "
                               "disagg roles through /healthz")
        # mixed long-prompt/short-chat bodies — the exact traffic
        # shape the subsystem exists to fix
        rng = np.random.RandomState(7)
        bodies = []
        for _ in range(32):
            if rng.random_sample() < 0.25:
                n = int(rng.randint(36, 49))   # long-prompt burst
            else:
                n = int(rng.randint(4, 9))     # short chat turn
            bodies.append(json.dumps(
                {"prompt": rng.randint(8, 64, size=n).tolist(),
                 "max_new_tokens": 4}).encode())
        box: Dict[str, Optional[float]] = {}
        victim_p, victim_d = sup._replicas[0], sup._replicas[2]
        notes["victims"] = {"prefill": victim_p.url,
                            "decode": victim_d.url}

        def inject():
            time.sleep(duration * 0.25)
            old_p = box["pid_p"] = victim_p.proc.pid
            box["t1"] = time.monotonic()
            try:
                os.kill(old_p, signal.SIGKILL)   # mid-handoff
            except OSError as e:
                box["err"] = f"prefill kill: {e}"
                return
            time.sleep(duration * 0.3)
            old_d = box["pid_d"] = victim_d.proc.pid
            box["t2"] = time.monotonic()
            try:
                os.kill(old_d, signal.SIGKILL)   # live segments die
            except OSError as e:
                box["err"] = f"decode kill: {e}"
                return
            box["r1"] = _wait_respawned_ready(victim_p, old_p)
            box["r2"] = _wait_respawned_ready(victim_d, old_d)

        sampler = _AlertSampler(router)
        injector = threading.Thread(target=inject, daemon=True)
        injector.start()
        records = run_traffic(server.url, 8, qps, duration,
                              timeout_s=cfg.get("timeout_s", 30.0),
                              workers=8, route="/generate",
                              bodies=bodies)
        injector.join(timeout=180.0)
        if box.get("err"):
            error = box["err"]
        elif box.get("t1") is None or box.get("t2") is None:
            error = "injection never fired both kills"
        elif box.get("r1") is None:
            error = "prefill victim never respawned ready"
        elif box.get("r2") is None:
            error = "decode victim never respawned ready"
        else:
            windows = [(box["t1"], box["r1"] + 1.0),
                       (box["t2"], box["r2"] + 1.0)]
            notes["recovery_s"] = {
                "prefill": round(box["r1"] - box["t1"], 3),
                "decode": round(box["r2"] - box["t2"], 3)}
        # crash-forensics contract for BOTH induced kills (same
        # verdict as the plain crash scenario): booked, artifacted,
        # attributed signal:SIGKILL
        unexplained = None
        if box.get("pid_p") is not None or box.get("pid_d") is not None:
            unexplained = 0
            notes["postmortems"] = {}
            for label, vic, pid in (("prefill", victim_p,
                                     box.get("pid_p")),
                                    ("decode", victim_d,
                                     box.get("pid_d"))):
                if pid is None:
                    continue
                death, pm_err = _postmortem_verdict(
                    vic, pid, "signal:SIGKILL")
                notes["postmortems"][label] = death
                if death is None:
                    unexplained = None
                elif death["attribution"] == "unexplained" \
                        and unexplained is not None:
                    unexplained += 1
                if error is None and pm_err is not None:
                    error = pm_err
        # burn-rate contract: fire inside EACH fault window, clear
        # after recovery (same machinery as the crash/hang scenarios)
        if windows:
            clear_deadline = time.monotonic() \
                + router.burn_monitor.fast_s + _ALERT_CLEAR_GRACE_S
            while time.monotonic() < clear_deadline \
                    and router.burn_monitor.firing():
                time.sleep(0.1)
        sampler.stop()
        if windows:
            fired = [sampler.fired_between(w0, w1)
                     for w0, w1 in windows]
            still = router.burn_monitor.firing()
            alerts = {"fired_in_windows": fired,
                      "cleared": not still, "still_firing": still}
            if error is None and not all(fired):
                error = ("burn-rate alert missed a disagg_crash "
                         "fault window")
            elif error is None and still:
                error = (f"burn-rate alert(s) {still} never cleared "
                         f"after disagg_crash recovery")
        # leak check: once the queues drain, every replica's pool
        # must hold ZERO live pages (reuse off) — retry until the
        # fleet settles, then read the verdict
        deadline = time.monotonic() + 60.0
        live_view = []
        while time.monotonic() < deadline:
            live_view = []
            for rep in sup._replicas:
                h = _healthz(rep.url, timeout=2.0) or {}
                g = h.get("generation") or {}
                paged = g.get("paged") or {}
                live_view.append({
                    "url": rep.url, "role": rep.role,
                    "pages_live": paged.get("pages_live"),
                    "queue_depth": g.get("queue_depth"),
                    "slots_active": g.get("slots_active")})
            settled = (len(live_view) == 4 and all(
                v["pages_live"] == 0 and v["queue_depth"] == 0
                and v["slots_active"] == 0 for v in live_view))
            if settled:
                leaked = 0
                break
            time.sleep(0.5)
        notes["pools_after"] = live_view
        if leaked is None:
            leaked = sum(v["pages_live"] or 0 for v in live_view)
            if error is None:
                error = (f"page pools never drained to zero after "
                         f"the storm: {live_view}")
        st = router.stats()["counters"]
        notes["router"] = {k: st[k] for k in
                           ("disagg_generations", "affinity_lost",
                            "reprefills", "retries", "no_ready")}
        if error is None and st["disagg_generations"] == 0:
            error = "no request took the disaggregated pipeline"
        if error is None and st["reprefills"] > 0:
            error = ("router re-prefilled despite "
                     "FLAGS_disagg_reprefill=0 (silent re-prefill "
                     "is forbidden by the taxonomy)")
    finally:
        if sampler is not None:
            sampler.stop()
        if server is not None:
            server.close()
        sup.close()

    rep = classify(records, windows)
    rep["scenario"] = "disagg_crash"
    rep["notes"] = notes
    rep["alerts"] = alerts
    rep["leaked_pages"] = leaked
    rep["unexplained_deaths"] = unexplained
    if "recovery_s" in notes:
        rep["recovery_s"] = max(notes["recovery_s"].values())
    if error is None and rep["ok"] == 0:
        error = "no generation request succeeded (fleet never served)"
    if error is not None:
        rep["error"] = error
    rep["_records"] = records
    return rep


def _scenario_embedding_shard_crash(cfg: dict, log=print) -> dict:
    """Recsys-tier containment: a fleet of 3 ``--recsys`` replicas
    (each running the ep-sharded embedding tier + hot-row cache)
    serves zipfian sparse-id ``/predict`` traffic steered by the
    ``embedding`` capability, while (a) a fleet-wide
    ``embedding_gather:fail~p`` fault degrades random shard gathers
    in-process and (b) one replica is SIGKILLed mid-storm.

    The contract: (a) zero collateral failures — fault-hit lookups
    DEGRADE (cache/default rows, still 200, booked as
    ``serving_embedding_degraded``) instead of failing, and the kill's
    failures lie inside its window (router connect-refused retry +
    supervisor respawn); (b) degraded service is bounded and counted —
    degraded rows > 0 (the fault really fired) and <= ``bound_pct`` of
    all looked-up rows (degradation must not swallow the feed);
    (c) the kill is harvested and attributed ``signal:SIGKILL``;
    (d) after the storm drains, EVERY replica's hot-row cache reports
    zero pinned rows — a leaked pin means a lookup path lost an unpin;
    (e) the hot-row hit rate rides ``/healthz`` as a first-class stat
    on every replica (the zipfian load makes it meaningfully > 0)."""
    import paddle_tpu  # noqa: F401 — flags registered
    from paddle_tpu.serving import FleetSupervisor, Router, RouterServer
    from paddle_tpu.serving.fleet import _healthz

    duration = max(float(cfg["duration_s"]), 6.0)
    qps = float(cfg["qps"])
    fail_prob = 0.08
    bound_pct = 30.0
    roles = ["embedding"] * 3
    argv = ["--rec-vocab", "2000", "--rec-dim", "4",
            "--rec-slots", "8", "--rec-dense", "4",
            "--rec-hidden", "16", "--rec-shards", "4",
            "--rec-cache-rows", "256",
            "--queue-cap", "512", "--deadline-ms", "60000"]
    env = {"FLAGS_fault_inject": f"embedding_gather:fail~{fail_prob}"}
    error = None
    notes: Dict[str, object] = {"roles": roles,
                                "gather_fail_prob": fail_prob,
                                "degraded_bound_pct": bound_pct}
    records: List[dict] = []
    windows: List[tuple] = []
    leaked = None
    unexplained = None
    sup = FleetSupervisor(replicas=3, roles=roles, replica_argv=argv,
                          env=env, max_restarts=8, backoff_ms=100.0,
                          liveness_timeout_ms=cfg.get(
                              "liveness_timeout_ms", 1500.0))
    server = None
    try:
        urls = sup.wait_ready(timeout_s=600)
        fwd_ms = max(4.0 * float(cfg.get("forward_timeout_ms", 800.0)),
                     5000.0)
        router = Router(urls, poll_interval_ms=100.0, stale_ms=1500.0,
                        eject_after=2, forward_timeout_ms=fwd_ms)
        server = RouterServer(router).start()
        router.poll_once()
        if not router.embedding_active():
            raise RuntimeError("recsys fleet did not advertise the "
                               "embedding capability through /healthz")
        # zipfian recsys bodies — hot ids concentrated enough that the
        # hot-row cache does real work (the hit-rate assertion below)
        rng = np.random.RandomState(11)
        w = 1.0 / np.power(np.arange(1, 2001, dtype=np.float64), 1.2)
        cdf = np.cumsum(w)
        cdf /= cdf[-1]
        bodies = []
        for _ in range(32):
            ids = np.searchsorted(
                cdf, rng.random_sample((1, 8))).astype(np.int64)
            bodies.append(json.dumps(
                {"inputs": {"sparse_ids": ids.tolist(),
                            "dense_x": rng.rand(1, 4).round(4).tolist()
                            }}).encode())
        box: Dict[str, Optional[float]] = {}
        victim = sup._replicas[0]
        notes["victim"] = victim.url

        def inject():
            time.sleep(duration * 0.35)
            old = box["pid"] = victim.proc.pid
            box["t_kill"] = time.monotonic()
            try:
                os.kill(old, signal.SIGKILL)
            except OSError as e:
                box["err"] = f"kill: {e}"
                return
            box["t_ready"] = _wait_respawned_ready(victim, old)

        injector = threading.Thread(target=inject, daemon=True)
        injector.start()
        records = run_traffic(server.url, 8, qps, duration,
                              timeout_s=cfg.get("timeout_s", 30.0),
                              workers=8, bodies=bodies)
        injector.join(timeout=180.0)
        if box.get("err"):
            error = box["err"]
        elif box.get("t_kill") is None:
            error = "injection never fired the kill"
        elif box.get("t_ready") is None:
            error = "victim never respawned ready"
        else:
            windows = [(box["t_kill"], box["t_ready"] + 1.0)]
            notes["recovery_s"] = round(
                box["t_ready"] - box["t_kill"], 3)
        # crash-forensics contract for the induced kill
        if box.get("pid") is not None:
            death, pm_err = _postmortem_verdict(victim, box["pid"],
                                                "signal:SIGKILL")
            notes["postmortem"] = death
            unexplained = (None if death is None else
                           int(death["attribution"] == "unexplained"))
            if error is None and pm_err is not None:
                error = pm_err
        # settle, then read every replica's embedding block: degraded
        # booked + bounded, pinned refcounts drained, hit rate present
        deadline = time.monotonic() + 60.0
        emb_view = []
        settled = False
        while time.monotonic() < deadline and not settled:
            emb_view = []
            for rep_ in sup._replicas:
                h = _healthz(rep_.url, timeout=2.0) or {}
                emb = h.get("embedding") or {}
                hot = emb.get("hot_rows") or {}
                cnt = emb.get("counters") or {}
                serving = h.get("serving") or {}
                emb_view.append({
                    "url": rep_.url,
                    "hit_rate": emb.get("hit_rate"),
                    "pinned": hot.get("pinned"),
                    "rows_cached": hot.get("rows"),
                    "evictions": hot.get("evictions"),
                    "bytes": hot.get("bytes"),
                    "rows_looked_up": cnt.get("rows"),
                    "degraded": cnt.get("degraded"),
                    "degraded_rows": cnt.get("degraded_rows"),
                    "queue_depth": serving.get("queue_depth")})
            settled = (len(emb_view) == 3 and all(
                v["pinned"] == 0 and v["queue_depth"] == 0
                for v in emb_view))
            if not settled:
                time.sleep(0.5)
        notes["embedding_after"] = emb_view
        if settled:
            leaked = 0
        else:
            leaked = sum(v["pinned"] or 0 for v in emb_view)
            if error is None:
                error = (f"hot-row pins never drained to zero after "
                         f"the storm: {emb_view}")
        total_rows = sum(v["rows_looked_up"] or 0 for v in emb_view)
        degraded_rows = sum(v["degraded_rows"] or 0 for v in emb_view)
        notes["degraded_rows"] = degraded_rows
        notes["total_rows"] = total_rows
        if error is None and degraded_rows == 0:
            error = ("embedding_gather fault never degraded a row — "
                     "the degradation path went unexercised")
        if error is None and total_rows > 0 \
                and degraded_rows > bound_pct / 100.0 * total_rows:
            error = (f"degraded rows {degraded_rows} exceed "
                     f"{bound_pct}% of {total_rows} looked-up rows — "
                     f"degradation swallowed the feed")
        # the hit rate must ride /healthz as a first-class stat (and
        # the zipfian skew makes it really > 0 on the survivors)
        missing = [v["url"] for v in emb_view if v["hit_rate"] is None]
        if error is None and missing:
            error = (f"replicas {missing} report no hot-row hit rate "
                     f"in /healthz")
        if error is None and not any(
                (v["hit_rate"] or 0) > 0 for v in emb_view):
            error = "no replica measured a non-zero hot-row hit rate"
    finally:
        if server is not None:
            server.close()
        sup.close()

    rep = classify(records, windows)
    rep["scenario"] = "embedding_shard_crash"
    rep["notes"] = notes
    rep["leaked_rows"] = leaked
    rep["unexplained_deaths"] = unexplained
    rep["degraded_rows"] = notes.get("degraded_rows")
    rep["hit_rates"] = [v["hit_rate"]
                        for v in notes.get("embedding_after", [])]
    if "recovery_s" in notes:
        rep["recovery_s"] = notes["recovery_s"]
    if error is None and rep["ok"] == 0:
        error = "no recsys request succeeded (fleet never served)"
    if error is not None:
        rep["error"] = error
    rep["_records"] = records
    return rep


def _get_json(url: str, timeout_s: float = 5.0) -> Optional[dict]:
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            return json.loads(r.read().decode("utf-8", "replace"))
    except (OSError, TimeoutError, ValueError,
            urllib.error.HTTPError):
        return None


def _epoch_total(points, boundaries) -> float:
    """True lifetime total of a counter series that may have been
    reset by process respawns: every boundary timestamp starts a new
    epoch (a fresh process whose counter restarted from zero), so the
    lifetime total is the sum of each epoch's final sample.  A naive
    ``last(series)`` read would lose every pre-respawn epoch — the
    dip the reset-aware federation exists to survive."""
    total, last, bi = 0.0, None, 0
    bounds = sorted(boundaries)
    for ts, v in points:
        while bi < len(bounds) and ts >= bounds[bi]:
            if last is not None:
                total += last
            last = None
            bi += 1
        last = v
    if last is not None:
        total += last
    return total


def _scenario_noisy_neighbor(cfg: dict, log=print) -> dict:
    """Usage-observatory forensics: a 3-replica dense fleet behind its
    own federating router serves multi-tenant ``/predict`` traffic —
    one zipf-hot hog tenant floods (~80% of offered load) while three
    background tenants trickle — and one replica is SIGKILLed
    mid-storm.

    The contract: (a) **attribution** — the hog's share of booked
    per-tenant request cost is at least 90% of its client-side share
    (a dropped tenant header anywhere on the path collapses the hog
    into ``~default`` and fails this); (b) **measurement** — every
    replica, including the respawned victim, reports a measured
    per-tenant request p99 for every background tenant via
    ``/usagez`` (noisy-neighbor forensics needs the victims' latency,
    not just the hog's volume); (c) **conservation across the
    respawn** — on every replica the live ledger's per-field deltas
    are zero, AND the router's federated per-(tenant, replica) series
    conserve at tolerance 0 against the per-replica all-tenant totals
    when both are summed epoch-aware across the SIGKILL reset (raw
    last-value reads would drop the victim's pre-kill bookings);
    (d) the sketch stays within its hard memory bound on every
    replica; (e) the kill is harvested and attributed
    ``signal:SIGKILL``."""
    import paddle_tpu  # noqa: F401 — flags registered
    from paddle_tpu.serving import FleetSupervisor, Router, RouterServer
    from paddle_tpu.serving import usage
    from paddle_tpu.serving.fleet import _healthz

    duration = max(float(cfg["duration_s"]), 6.0)
    qps = float(cfg["qps"])
    feat = cfg["feat"]
    hog = "tenant-hog"
    bg = ["tenant-bg-0", "tenant-bg-1", "tenant-bg-2"]
    tenant_names = [hog] + bg + [usage.OTHER_TENANT,
                                 usage.default_tenant()]
    fields = list(usage.COST_FIELDS)
    argv = ["--feat", str(feat), "--hidden", "16", "--depth", "1",
            "--max-batch", "8", "--max-delay-ms", "2.0",
            "--queue-cap", "512", "--deadline-ms", "30000"]
    error = None
    notes: Dict[str, object] = {"hog": hog, "background": bg}
    records: List[dict] = []
    windows: List[tuple] = []
    unexplained = None
    conservation_delta = None
    attribution_ratio = None
    sketch_violations = None
    sup = FleetSupervisor(replicas=3, replica_argv=argv,
                          max_restarts=8, backoff_ms=100.0,
                          liveness_timeout_ms=cfg.get(
                              "liveness_timeout_ms", 1500.0))
    server = None
    try:
        urls = sup.wait_ready(timeout_s=600)
        fwd_ms = max(4.0 * float(cfg.get("forward_timeout_ms", 800.0)),
                     5000.0)
        router = Router(urls, poll_interval_ms=100.0, stale_ms=1500.0,
                        eject_after=2, forward_timeout_ms=fwd_ms)
        server = RouterServer(router).start()
        router.poll_once()

        # 4 requests in 5 go to the hog; the rest round-robin the
        # background trickle — the zipf-hot shape at deterministic odds
        def tenant_of(i: int) -> str:
            return hog if i % 5 else bg[(i // 5) % len(bg)]

        box: Dict[str, Optional[float]] = {}
        victim = sup._replicas[0]
        notes["victim"] = victim.url

        def inject():
            time.sleep(duration * 0.35)
            old = box["pid"] = victim.proc.pid
            box["t_kill"] = time.monotonic()
            try:
                os.kill(old, signal.SIGKILL)
            except OSError as e:
                box["err"] = f"kill: {e}"
                return
            box["t_ready"] = _wait_respawned_ready(victim, old)

        injector = threading.Thread(target=inject, daemon=True)
        injector.start()
        records = run_traffic(server.url, feat, qps, duration,
                              timeout_s=cfg.get("timeout_s", 30.0),
                              workers=8, tenant_of=tenant_of)
        injector.join(timeout=180.0)
        if box.get("err"):
            error = box["err"]
        elif box.get("t_kill") is None:
            error = "injection never fired the kill"
        elif box.get("t_ready") is None:
            error = "victim never respawned ready"
        else:
            windows = [(box["t_kill"], box["t_ready"] + 1.0)]
            notes["recovery_s"] = round(
                box["t_ready"] - box["t_kill"], 3)
        if box.get("pid") is not None:
            death, pm_err = _postmortem_verdict(victim, box["pid"],
                                                "signal:SIGKILL")
            notes["postmortem"] = death
            unexplained = (None if death is None else
                           int(death["attribution"] == "unexplained"))
            if error is None and pm_err is not None:
                error = pm_err
        # direct per-replica background probes: forensics needs the
        # background tenants' latency MEASURED on every replica —
        # including the respawned victim, whose ledger restarted empty
        probe = _bodies(feat, n=1, seed=7)[0]
        probe_ok: Dict[str, int] = {}
        for rep_ in sup._replicas:
            for t in bg:
                for _ in range(3):
                    outcome, _status = _post(
                        rep_.url.rstrip("/") + "/predict", probe,
                        cfg.get("timeout_s", 30.0), tenant=t)
                    if outcome == "ok":
                        probe_ok[t] = probe_ok.get(t, 0) + 1
        # settle: queues drained on every replica, then one more poll
        # so the federation's final scrape sees every booking
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            depths = []
            for rep_ in sup._replicas:
                h = _healthz(rep_.url, timeout=2.0) or {}
                depths.append((h.get("serving") or {}).get(
                    "queue_depth"))
            if len(depths) == 3 and all(d == 0 for d in depths):
                break
            time.sleep(0.3)
        router.poll_once()
        # (b) + (d): per-replica /usagez — background p99 measured
        # everywhere, ledger conservation zero, sketch within bound
        ledger_delta = 0
        sketch_violations = 0
        unmeasured: List[str] = []
        usage_after = []
        for rep_ in sup._replicas:
            uz = _get_json(rep_.url.rstrip("/") + "/usagez")
            if uz is None:
                unmeasured.append(f"{rep_.url}: /usagez unreachable")
                continue
            tenants = uz.get("tenants") or {}
            for t in bg:
                p99 = ((tenants.get(t) or {}).get("request_ms")
                       or {}).get("p99")
                if p99 is None:
                    unmeasured.append(f"{rep_.url}: {t} p99 missing")
            for f, c in (uz.get("conservation") or {}).items():
                ledger_delta = max(ledger_delta, abs(c["delta"]))
            sk = uz.get("sketch") or {}
            if not (sk.get("within_bound")
                    and sk.get("tracked", 1 << 30) <= sk.get("top_k", 0)
                    and sk.get("capacity_vectors")
                    == sk.get("top_k", 0) + 1):
                sketch_violations += 1
            usage_after.append({
                "url": rep_.url,
                "requests": {t: (tenants.get(t) or {}).get(
                    "vector", {}).get("requests", 0)
                    for t in [hog] + bg},
                "sketch": sk})
        notes["usage_after"] = usage_after
        if error is None and unmeasured:
            error = ("background tenant latency unmeasured: "
                     + "; ".join(unmeasured))
        if error is None and sketch_violations:
            error = (f"{sketch_violations} replica(s) violate the "
                     f"sketch memory bound")
        # (c): federated conservation at tolerance 0, epoch-aware
        # across the victim's SIGKILL reset.  The victim's series
        # restart from zero mid-run; splitting every one of its series
        # at the first post-kill scrape and summing epoch-final values
        # recovers the true lifetime totals on both sides, so the
        # per-tenant sum must equal the all-tenant total EXACTLY
        fed_delta = 0.0
        booked: Dict[str, float] = {t: 0.0 for t in tenant_names}
        for rep_ in sup._replicas:
            rid = rep_.url.split("://", 1)[-1]
            t_kill = box.get("t_kill")
            bounds: List[float] = []
            if rep_ is victim and t_kill is not None:
                pts = router._db.points(
                    f"serving_tenant_requests[{rid}]")
                bounds = [ts for ts, _ in pts if ts > t_kill][:1]
            for f in fields:
                labeled = 0.0
                for t in tenant_names:
                    v = _epoch_total(router._db.points(
                        f"serving_tenant_{f}{{{t}}}[{rid}]"), bounds)
                    labeled += v
                    if f == "requests":
                        booked[t] += v
                total = _epoch_total(router._db.points(
                    f"serving_tenant_{f}[{rid}]"), bounds)
                fed_delta = max(fed_delta, abs(labeled - total))
        conservation_delta = max(float(ledger_delta), fed_delta)
        notes["ledger_conservation_delta"] = ledger_delta
        notes["federated_conservation_delta"] = fed_delta
        if error is None and conservation_delta != 0:
            error = (f"per-tenant usage does not conserve across the "
                     f"respawn: ledger delta {ledger_delta}, "
                     f"federated delta {fed_delta}")
        # (a): attribution — the hog's booked share must track its
        # client-side share (>= 90% of it); a header dropped on any
        # hop folds the hog into ~default and collapses this ratio
        ok_by_tenant: Dict[str, int] = dict(probe_ok)
        for r in records:
            if r["outcome"] == "ok" and r.get("tenant"):
                ok_by_tenant[r["tenant"]] = \
                    ok_by_tenant.get(r["tenant"], 0) + 1
        client_total = sum(ok_by_tenant.values())
        booked_total = sum(booked.values())
        notes["booked_requests"] = {t: booked[t] for t in tenant_names}
        notes["client_ok_requests"] = ok_by_tenant
        if client_total and booked_total:
            client_share = ok_by_tenant.get(hog, 0) / client_total
            booked_share = booked[hog] / booked_total
            attribution_ratio = round(
                booked_share / client_share, 4) if client_share else None
            notes["hog_client_share"] = round(client_share, 4)
            notes["hog_booked_share"] = round(booked_share, 4)
        if error is None and (attribution_ratio is None
                              or attribution_ratio < 0.9):
            error = (f"hog attribution ratio {attribution_ratio} "
                     f"below the 0.9 floor — excess cost was not "
                     f"booked to the noisy tenant")
    finally:
        if server is not None:
            server.close()
        sup.close()

    rep = classify(records, windows)
    rep["scenario"] = "noisy_neighbor"
    rep["notes"] = notes
    rep["unexplained_deaths"] = unexplained
    rep["usage_conservation_delta"] = conservation_delta
    rep["hog_attribution_ratio"] = attribution_ratio
    rep["sketch_violations"] = sketch_violations
    if "recovery_s" in notes:
        rep["recovery_s"] = notes["recovery_s"]
    if error is None and rep["ok"] == 0:
        error = "no multi-tenant request succeeded (fleet never served)"
    if error is not None:
        rep["error"] = error
    rep["_records"] = records
    return rep


def _scenario_hot_swap(cfg: dict, log=print) -> dict:
    """Hot-swap discipline under fire: a fleet serving MIXED open-loop
    ``/predict`` + ``/generate`` load takes a clean rolling hot-swap,
    then a second rolling swap with one replica SIGKILLed MID-SWAP
    (``weight_swap:delay`` fault widens the commit window so the kill
    reliably lands inside it; the supervisor's restart fallback must
    converge the slot anyway).

    The contract: (a) zero non-shed failures outside the kill window —
    a clean swap quiesces and queues, it never errors live traffic;
    (b) zero torn-version responses — per replica, the published
    ``X-PaddleTPU-Weights-Version`` must flip monotonically (a request
    that STARTED after a new-version response finished may never
    observe an older version; the killed replica may reset to baseline
    exactly once, at the kill); (c) post-rollout outputs are BIT-EXACT
    against a fresh in-process predictor loaded from the same
    checkpoint — swapped-in-place weights and freshly-built weights
    must be indistinguishable; (d) both rollouts report converged."""
    from paddle_tpu import io
    from paddle_tpu.framework.core import reset_unique_name
    from paddle_tpu.serving import FleetSupervisor
    from paddle_tpu.serving.replica import build_synthetic_checkpoint

    feat = int(cfg["feat"])
    duration = max(float(cfg["duration_s"]) * 2.5, 12.0)
    qps = min(float(cfg["qps"]), 30.0)
    dims = dict(feat=feat, hidden=16, depth=1, classes=8)
    argv = ["--feat", str(feat), "--hidden", "16", "--depth", "1",
            "--generate", "--gen-vocab", "64", "--gen-hidden", "32",
            "--gen-layers", "2", "--gen-heads", "4",
            "--gen-intermediate", "64", "--gen-slots", "4",
            "--gen-max-seq", "64", "--gen-max-new", "4",
            "--max-batch", "8", "--max-delay-ms", "2.0",
            "--queue-cap", "512"]
    # widen each replica's swap-commit window (per-array device_put
    # delay) so the mid-swap SIGKILL lands INSIDE a commit instead of
    # racing a millisecond flip
    env = {"FLAGS_fault_inject": "weight_swap:delay:150~1.0"}
    workdir = tempfile.mkdtemp(prefix="chaos-hotswap-")
    ck_v2 = os.path.join(workdir, "ck_v2")
    ck_v3 = os.path.join(workdir, "ck_v3")
    build_synthetic_checkpoint(ck_v2, seed=11, **dims)
    build_synthetic_checkpoint(ck_v3, seed=12, **dims)

    error = None
    notes: Dict[str, object] = {}
    records: List[dict] = []
    windows: List[tuple] = []
    rec_lock = threading.Lock()
    stop = threading.Event()
    sup = FleetSupervisor(replicas=3, replica_argv=argv, env=env,
                          max_restarts=8, backoff_ms=100.0,
                          liveness_timeout_ms=cfg.get(
                              "liveness_timeout_ms", 1500.0),
                          workdir=os.path.join(workdir, "fleet"))
    try:
        urls = sup.wait_ready(timeout_s=300)
        rng = np.random.RandomState(3)
        predict_bodies = _bodies(feat, seed=3)
        gen_bodies = [json.dumps(
            {"prompt": rng.randint(1, 64, size=int(n)).tolist(),
             "max_new_tokens": 3}).encode()
            for n in rng.randint(4, 12, size=16)]

        def one_request(i):
            """Round-robin direct-to-replica with one failover retry
            on a dead socket — the client plays router so the torn
            check keeps exact per-replica attribution."""
            gen = i % 4 == 3  # 25% generation load riding along
            body = (gen_bodies if gen else predict_bodies)[
                i % len(predict_bodies)]
            route = "/generate" if gen else "/predict"
            t0 = time.monotonic()
            for attempt in range(2):
                url = urls[(i + attempt) % len(urls)]
                req = urllib.request.Request(
                    url + route, data=body,
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(
                            req, timeout=cfg["timeout_s"]) as r:
                        r.read()
                        outcome, status = "ok", r.status
                        version = r.headers.get(
                            "X-PaddleTPU-Weights-Version")
                        break
                except urllib.error.HTTPError as e:
                    try:
                        e.read()
                    except OSError:
                        pass  # ok: draining the error body is best-effort
                    outcome = "shed" if e.code == 503 else "failed"
                    status = e.code
                    version = e.headers.get(
                        "X-PaddleTPU-Weights-Version")
                    break
                except (OSError, TimeoutError, ValueError):
                    outcome, status, version = "failed", None, None
                    # connect-level death: fail over once, like the
                    # router's connect-refused retry
            t1 = time.monotonic()
            with rec_lock:
                records.append({
                    "t0": t0, "t1": t1, "outcome": outcome,
                    "status": status, "ms": (t1 - t0) * 1e3,
                    "poison": False, "url": url,
                    "version": int(version) if version else None})

        def storm():
            period = 1.0 / max(qps, 0.001)
            t_start = time.monotonic()
            i = 0
            posters: List[threading.Thread] = []
            while not stop.is_set() \
                    and time.monotonic() - t_start < duration:
                th = threading.Thread(target=one_request, args=(i,),
                                      daemon=True)
                th.start()
                posters.append(th)
                i += 1
                sleep_for = t_start + i * period - time.monotonic()
                if sleep_for > 0:
                    time.sleep(sleep_for)
            for th in posters:
                th.join(timeout=cfg["timeout_s"] + 5.0)

        traffic = threading.Thread(target=storm, daemon=True)
        traffic.start()
        time.sleep(duration * 0.15)

        # phase 1: clean rolling hot-swap under load — no fault
        # window, so ANY failure it causes is collateral
        res1 = sup.hot_swap(ck_v2)
        notes["swap_clean"] = {
            "converged": res1["converged"],
            "duration_s": res1["duration_s"],
            "statuses": [r.get("swap_status") for r in
                         res1["replicas"]]}
        if not res1["converged"]:
            error = f"clean hot swap did not converge: {res1}"

        time.sleep(duration * 0.15)

        # phase 2: rolling swap with the middle replica SIGKILLed
        # mid-commit (in_rollout + the injected commit delay time the
        # kill inside the swap)
        victim = sup._replicas[1]
        box: Dict[str, Optional[float]] = {"t_kill": None}

        def killer():
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                if victim.in_rollout:
                    time.sleep(0.25)  # inside the delayed commit
                    try:
                        box["pid"] = victim.proc.pid
                        os.kill(victim.proc.pid, signal.SIGKILL)
                        box["t_kill"] = time.monotonic()
                    except OSError as e:
                        box["err"] = f"kill: {e}"
                    return
                time.sleep(0.002)

        kth = threading.Thread(target=killer, daemon=True)
        kth.start()
        res2 = sup.hot_swap(ck_v3) if error is None else None
        kth.join(timeout=90.0)
        t_swap2_done = time.monotonic()
        if error is None:
            notes["swap_killed"] = {
                "converged": res2["converged"],
                "duration_s": res2["duration_s"],
                "victim": victim.url,
                "fallbacks": sum(1 for r in res2["replicas"]
                                 if "fallback" in r)}
            if box.get("err"):
                error = box["err"]
            elif box["t_kill"] is None:
                error = "SIGKILL never landed mid-swap"
            elif not res2["converged"]:
                error = (f"post-kill rollout did not converge "
                         f"(restart fallback failed): {res2}")
            else:
                # +1s grace: round-robin clients may still be timing
                # out on the respawned socket right at ready
                windows.append((box["t_kill"], t_swap2_done + 1.0))
        # crash-forensics contract: the mid-swap SIGKILL is a death
        # like any other — the fallback-restart path must have booked
        # it (harvested + attributed signal:SIGKILL)
        unexplained = None
        if box.get("pid") is not None:
            death, pm_err = _postmortem_verdict(
                victim, box["pid"], "signal:SIGKILL")
            notes["postmortem"] = death
            if death is not None:
                unexplained = \
                    1 if death["attribution"] == "unexplained" else 0
            if error is None and pm_err is not None:
                error = pm_err

        traffic.join(timeout=duration + 60.0)
        stop.set()

        # torn-version check: per replica, happens-before monotonic —
        # for any request A started strictly after request B finished,
        # version(A) >= version(B).  The killed replica is checked per
        # segment (before / after the kill): its respawn legitimately
        # resets the counter to baseline exactly once
        torn = 0
        seen_versions: Dict[str, List[int]] = {}
        with rec_lock:
            recs = list(records)
        for url in urls:
            mine = [r for r in recs
                    if r["url"] == url and r["version"] is not None]
            seen_versions[url] = sorted(
                {r["version"] for r in mine})
            segments = [mine]
            if url == victim.url and box.get("t_kill"):
                segments = [
                    [r for r in mine if r["t1"] <= box["t_kill"]],
                    [r for r in mine if r["t0"] > box["t_kill"]]]
            for seg in segments:
                by_t1 = sorted(seg, key=lambda r: r["t1"])
                by_t0 = sorted(seg, key=lambda r: r["t0"])
                max_done = 0
                j = 0
                for a in by_t0:
                    while j < len(by_t1) and by_t1[j]["t1"] < a["t0"]:
                        max_done = max(max_done,
                                       by_t1[j]["version"])
                        j += 1
                    if a["version"] < max_done:
                        torn += 1
        notes["versions_seen"] = seen_versions
        notes["torn_responses"] = torn
        if error is None and torn:
            error = (f"{torn} torn-version response(s): a replica "
                     f"served an older weights version after a newer "
                     f"one was already visible")

        # bit-exact: every replica's post-rollout answer must equal a
        # FRESH in-process predictor loaded from the same checkpoint
        if error is None:
            import paddle_tpu as pt
            from paddle_tpu import layers
            from paddle_tpu.inference import Predictor

            reset_unique_name()
            main, startup = pt.Program(), pt.Program()
            startup._is_startup = True
            with pt.program_guard(main, startup):
                x = layers.data("x", [feat])
                h = layers.fc(x, 16, act="relu", name="rep_fc0")
                out = layers.fc(h, 8, name="rep_head")
            scope = pt.Scope()
            pt.Executor().run(startup, scope=scope)
            ref = Predictor(main, ["x"], [out], scope=scope)
            ref.swap_weights(io._read(os.path.join(ck_v3,
                                                   "__params__")))
            probe = np.linspace(-1.0, 1.0, feat,
                                dtype="float32").reshape(1, feat)
            want = ref.run({"x": probe})[0].tolist()
            body = json.dumps({"inputs": {"x": probe.tolist()}}
                              ).encode()
            mismatched = []
            for url in urls:
                req = urllib.request.Request(
                    url + "/predict", data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=30.0) as r:
                    got = json.loads(r.read())["outputs"][0]
                if got != want:
                    mismatched.append(url)
            notes["bit_exact"] = not mismatched
            if mismatched:
                error = (f"post-swap outputs diverged from a fresh "
                         f"predictor on {mismatched} — the swap "
                         f"discipline leaked state")
    finally:
        stop.set()
        sup.close()

    rep = classify(records, windows)
    rep["scenario"] = "hot_swap"
    rep["notes"] = notes
    rep["torn_responses"] = notes.get("torn_responses")
    rep["unexplained_deaths"] = unexplained
    if error is None and rep["ok"] == 0:
        error = "no request succeeded (fleet never served)"
    if error is None and rep.get("torn_responses") is None:
        error = "torn-version check never ran"
    if error is not None:
        rep["error"] = error
    rep["_records"] = records
    return rep


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------

def run_chaos(replicas: int = 3, qps: float = 40.0,
              duration_s: float = 6.0,
              scenarios=DEFAULT_SCENARIOS,
              availability_pct: float = 99.0,
              feat: int = 8, hidden: int = 32, depth: int = 1,
              liveness_timeout_ms: float = 1500.0,
              forward_timeout_ms: float = 800.0,
              poison_every: int = 5,
              slow_delay_ms: int = 40, slow_prob: float = 0.25,
              timeout_s: float = 15.0,
              workdir: Optional[str] = None,
              log=print) -> dict:
    """Spawn a fleet + router, run every scenario, and return the
    availability report (``report["ok"]`` is the harness verdict)."""
    from paddle_tpu.serving import FleetSupervisor, Router, RouterServer

    cfg = {"qps": qps, "duration_s": duration_s, "feat": feat,
           "poison_every": poison_every, "slow_delay_ms": slow_delay_ms,
           "slow_prob": slow_prob, "timeout_s": timeout_s,
           "liveness_timeout_ms": liveness_timeout_ms,
           "forward_timeout_ms": forward_timeout_ms}
    argv = ["--feat", str(feat), "--hidden", str(hidden),
            "--depth", str(depth), "--max-batch", "8",
            "--max-delay-ms", "2.0", "--queue-cap", "512",
            "--deadline-ms", "30000"]
    t_setup0 = time.monotonic()
    sup = FleetSupervisor(
        replicas=replicas, replica_argv=argv,
        env={"FLAGS_serving_poison_value": str(POISON)},
        max_restarts=8, backoff_ms=100.0,
        liveness_timeout_ms=liveness_timeout_ms, workdir=workdir)
    server = None
    per_scenario = {}
    all_records: List[dict] = []
    fault_records: List[dict] = []
    try:
        urls = sup.wait_ready(timeout_s=300)
        # burn-rate windows scaled to scenario time: fast ~ a quarter
        # scenario (clears quickly after recovery), slow ~ most of one
        # (a single bad scrape cannot page).  Alert threshold stays the
        # flag default — the chaos faults burn budget at 10-30x
        fast_s = max(1.0, duration_s / 4.0)
        slow_s = max(fast_s * 2.0, duration_s * 0.75)
        router = Router(urls, poll_interval_ms=100.0, stale_ms=1500.0,
                        eject_after=2,
                        forward_timeout_ms=forward_timeout_ms,
                        slo_fast_s=fast_s, slo_slow_s=slow_s)
        server = RouterServer(router).start()
        router.poll_once()
        log(f"chaos: fleet of {replicas} ready in "
            f"{time.monotonic() - t_setup0:.1f}s; running "
            f"{','.join(scenarios)} at {qps} qps x {duration_s}s each")
        for name in scenarios:
            if name == "poison_paged":
                # in-process paged-generation containment: needs no
                # fleet traffic, but runs inside the same harness so
                # its counters fold into the same hard-zero contract
                rep = _scenario_poison_paged(cfg)
            elif name == "spec_storm":
                # speculative-decoding storm: poison + mid-verify
                # decode_step faults against concurrent speculating
                # slots; in-process like poison_paged so the rollback
                # and leak counters fold into the same hard-zero gates
                rep = _scenario_spec_storm(cfg)
            elif name == "disagg_crash":
                # role-split generation fleet with its own router —
                # spawned fresh so the kills cannot bleed into the
                # shared /predict fleet's attribution
                rep = _scenario_disagg_crash(cfg, log=log)
            elif name == "embedding_shard_crash":
                # recsys fleet with its own router: shard-gather
                # faults + a SIGKILL must degrade (cache/default rows)
                # rather than fail, with pins drained afterwards
                rep = _scenario_embedding_shard_crash(cfg, log=log)
            elif name == "hot_swap":
                # rolling weight swap + mid-swap SIGKILL against its
                # own fleet (direct per-replica traffic so the torn-
                # version check keeps exact attribution)
                rep = _scenario_hot_swap(cfg, log=log)
            elif name == "noisy_neighbor":
                # multi-tenant usage forensics against its own fleet:
                # a hog tenant floods, background tenants trickle, one
                # replica dies mid-storm — attribution, per-tenant
                # latency, and conservation must survive the respawn
                rep = _scenario_noisy_neighbor(cfg, log=log)
            else:
                rep = _scenario(name, sup, router, server.url, cfg)
            records = rep.pop("_records")
            all_records.extend(records)
            if name in ("crash", "hang", "disagg_crash",
                        "embedding_shard_crash", "hot_swap",
                        "noisy_neighbor"):
                fault_records.extend(records)
            per_scenario[name] = rep
            al = rep.get("alerts") or {}
            log(f"chaos: {name}: {rep['requests']} requests, "
                f"{rep['ok']} ok, {rep['shed']} shed, "
                f"{rep['injected_failures']} injected, "
                f"{rep['collateral_failures']} collateral"
                + (f", recovery {rep['recovery_s']}s"
                   if "recovery_s" in rep else "")
                + (f", alerts fired {al['fired_in_window']} "
                   f"cleared={al['cleared']}"
                   if "fired_in_window" in al else "")
                + (f" ERROR: {rep['error']}" if "error" in rep else ""))
            # let the fleet settle (router re-admits the recovered
            # replica) before the next scenario's attribution starts
            time.sleep(0.5)
            router.poll_once()
    finally:
        if server is not None:
            server.close()
        sup.close()

    # aggregate counts + availability over every record; the
    # injected/collateral attribution needs each scenario's own fault
    # window, so those three fold by summation instead
    totals = classify(all_records, [])
    for k in ("injected_failures", "collateral_failures",
              "poison_leaks"):
        totals[k] = sum(r[k] for r in per_scenario.values())
    # alert-contract verdicts: missed fires, missed clears, and false
    # positives all land in scenario errors; this count gives the gate
    # (and the bench leg) a single number to hard-zero
    totals["alert_errors"] = sum(
        1 for r in per_scenario.values()
        if "error" in r and "burn-rate alert" in r["error"])
    # disagg page-pool leak verdict (None when the scenario didn't
    # run): perf_gate hard-zeroes it like collateral/leaks
    if any("leaked_pages" in r for r in per_scenario.values()):
        totals["leaked_pages"] = sum(
            r.get("leaked_pages") or 0 for r in per_scenario.values())
    # hot-swap torn-version verdict (None when the scenario didn't
    # run): a single torn response breaks the rollout contract, so
    # perf_gate hard-zeroes the sum
    if any("torn_responses" in r for r in per_scenario.values()):
        totals["torn_responses"] = sum(
            r.get("torn_responses") or 0 for r in per_scenario.values())
    # embedding-tier pin-leak verdict (None when the scenario didn't
    # run): a row still pinned after the storm means a lookup lost its
    # unpin — perf_gate hard-zeroes the sum like leaked_pages
    if any("leaked_rows" in r for r in per_scenario.values()):
        totals["leaked_rows"] = sum(
            r.get("leaked_rows") or 0 for r in per_scenario.values())
    # usage-observatory verdicts (None when noisy_neighbor didn't run,
    # or when it ran but could not measure — perf_gate treats a
    # present-but-None value as a failed rule, never a pass):
    # conservation delta hard-zeroes, the hog attribution ratio has a
    # floor, and the sketch bound violation count hard-zeroes
    if any("usage_conservation_delta" in r
           for r in per_scenario.values()):
        vals = [r["usage_conservation_delta"]
                for r in per_scenario.values()
                if "usage_conservation_delta" in r]
        totals["usage_conservation_delta"] = \
            None if any(v is None for v in vals) else max(vals)
    if any("hog_attribution_ratio" in r
           for r in per_scenario.values()):
        vals = [r["hog_attribution_ratio"]
                for r in per_scenario.values()
                if "hog_attribution_ratio" in r]
        totals["hog_attribution_ratio"] = \
            None if any(v is None for v in vals) else min(vals)
    if any("sketch_violations" in r for r in per_scenario.values()):
        vals = [r["sketch_violations"] for r in per_scenario.values()
                if "sketch_violations" in r]
        totals["sketch_violations"] = \
            None if any(v is None for v in vals) else sum(vals)
    # crash-forensics verdict: every induced death must be harvested
    # AND explained.  A per-scenario None means a death was never even
    # booked — that vacuousness propagates to the total (perf_gate
    # treats present-but-None as a failed rule, not a pass)
    pm_scens = [r for r in per_scenario.values()
                if "unexplained_deaths" in r]
    if pm_scens:
        totals["unexplained_deaths"] = None \
            if any(r["unexplained_deaths"] is None for r in pm_scens) \
            else sum(r["unexplained_deaths"] for r in pm_scens)
    fault_ok_ms = sorted(r["ms"] for r in fault_records
                         if r["outcome"] == "ok")
    p99_under_fault = round(
        fault_ok_ms[min(len(fault_ok_ms) - 1,
                        int(np.ceil(0.99 * len(fault_ok_ms))) - 1)], 3) \
        if fault_ok_ms else None
    errors = {n: r["error"] for n, r in per_scenario.items()
              if "error" in r}
    ok = (not errors
          and totals["collateral_failures"] == 0
          and totals["poison_leaks"] == 0
          and totals.get("unexplained_deaths", 0) == 0
          and totals["availability_pct"] >= availability_pct)
    return {
        "ok": ok,
        "availability_pct": totals["availability_pct"],
        "availability_floor": availability_pct,
        "p99_under_fault_ms": p99_under_fault,
        "totals": {k: v for k, v in totals.items() if k != "p99_ms"},
        "scenarios": per_scenario,
        "errors": errors,
        "config": {"replicas": replicas, "qps": qps,
                   "duration_s": duration_s,
                   "scenarios": list(scenarios),
                   "feat": feat, "hidden": hidden, "depth": depth,
                   "liveness_timeout_ms": liveness_timeout_ms,
                   "forward_timeout_ms": forward_timeout_ms,
                   "poison_every": poison_every},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--qps", type=float, default=40.0)
    ap.add_argument("--duration", type=float, default=6.0,
                    help="seconds of traffic per scenario")
    ap.add_argument("--scenarios",
                    default=",".join(DEFAULT_SCENARIOS),
                    help="comma-separated subset of "
                         "crash,hang,slow,poison,poison_paged,"
                         "spec_storm,disagg_crash,"
                         "embedding_shard_crash,hot_swap,"
                         "noisy_neighbor")
    ap.add_argument("--availability-pct", type=float, default=99.0)
    ap.add_argument("--feat", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--depth", type=int, default=1)
    ap.add_argument("--liveness-timeout-ms", type=float, default=1500.0)
    ap.add_argument("--forward-timeout-ms", type=float, default=800.0)
    ap.add_argument("--poison-every", type=int, default=5)
    ap.add_argument("--out", help="write the JSON report here")
    args = ap.parse_args(argv)

    scenarios = tuple(s for s in args.scenarios.split(",") if s)
    bad = sorted(set(scenarios) - set(DEFAULT_SCENARIOS))
    if bad:
        ap.error(f"unknown scenario(s) {bad}; "
                 f"known: {','.join(DEFAULT_SCENARIOS)}")
    report = run_chaos(
        replicas=args.replicas, qps=args.qps,
        duration_s=args.duration, scenarios=scenarios,
        availability_pct=args.availability_pct, feat=args.feat,
        hidden=args.hidden, depth=args.depth,
        liveness_timeout_ms=args.liveness_timeout_ms,
        forward_timeout_ms=args.forward_timeout_ms,
        poison_every=args.poison_every)
    text = json.dumps(report, indent=1, sort_keys=True)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
    print(text)
    print(f"CHAOS {'PASSED' if report['ok'] else 'FAILED'}: "
          f"availability {report['availability_pct']}% "
          f"(budget {args.availability_pct}%), "
          f"{report['totals']['collateral_failures']} collateral, "
          f"{report['totals']['poison_leaks']} leaks")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
