# tools/ is a package so `python -m tools.graftcheck` works; the
# standalone scripts in here remain directly runnable by path.
