"""graftcheck core: shared file walking, parsed-AST caching, the
violation format, waiver machinery, and the pass runner.

Every pass is a function ``(files: List[SourceFile]) -> List[Violation]``
registered in :mod:`tools.graftcheck.passes`.  Violations share one
format everywhere (CLI text, ``--json``, the baseline file)::

    file:line rule-id message

and carry a stable ``key`` (a symbol path like
``serving/generation.py::GenerationEngine._draining``) so waivers
survive line drift.

Waivers, two layers:

* **inline** — a violation whose source line (or the line above it)
  carries ``# gc-ok: <rule-id> <reason>`` (or ``# gc-ok: *``) is
  suppressed; the reason is mandatory.
* **baseline file** (``tools/graftcheck/baseline.txt``) — one waiver
  per line: ``rule-id  path  key  -- reason``.  Matching is on
  (rule, path, key), never on line numbers.  A baseline entry that no
  longer matches anything is itself reported (``stale-waiver``) so the
  file can only shrink as findings are fixed.
"""
from __future__ import annotations

import ast
import fnmatch
import json
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_ROOTS = ("paddle_tpu", "tools")
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.txt")
_INLINE_WAIVER_RE = re.compile(r"#\s*gc-ok:\s*(\S+)\s*(.*)")


def call_name(call: "ast.Call") -> str:
    """Terminal name of a call's function: ``f(...)`` -> ``f``,
    ``a.b.f(...)`` -> ``f`` (the shared helper every pass matches
    API calls with)."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


@dataclass(frozen=True)
class Violation:
    rule: str          # rule id, e.g. "lock-bare-access"
    path: str          # repo-relative, forward slashes
    line: int
    key: str           # stable symbol path for waiver matching
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "key": self.key, "message": self.message}

    def sort_key(self):
        return (self.path, self.line, self.rule, self.key, self.message)


class SourceFile:
    """One parsed source file, shared across passes (parse once)."""

    def __init__(self, abspath: str, relpath: str):
        self.abspath = abspath
        self.path = relpath.replace(os.sep, "/")
        with open(abspath, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(self.text, abspath)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = e

    def line_text(self, lineno: int) -> str:
        if 0 < lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def inline_waiver(self, lineno: int, rule: str) -> bool:
        """``# gc-ok: <rule> <reason>`` (or ``* <reason>``) on the
        line or the line above suppresses a finding there.  The
        reason is mandatory, exactly like baseline entries: a
        reason-less waiver does not waive."""
        for ln in (lineno, lineno - 1):
            m = _INLINE_WAIVER_RE.search(self.line_text(ln))
            if m and m.group(1) in (rule, "*") and m.group(2).strip():
                return True
        return False


def walk_files(roots: Sequence[str], repo: str = REPO,
               exclude: Sequence[str] = ()) -> List[SourceFile]:
    """Every ``.py`` under the given roots, sorted by repo-relative
    path so output order is deterministic.  Relative roots resolve
    against the CURRENT directory first (the historical shim-CLI
    behavior), falling back to the repo root (so the default
    ``paddle_tpu tools`` roots work from anywhere).  A root that
    exists in neither place is an error: a mistargeted lint that
    silently scans zero files is a false green."""
    out: List[SourceFile] = []
    seen = set()
    for root in roots:
        if os.path.isabs(root):
            absroot = root
        elif os.path.exists(os.path.abspath(root)):
            absroot = os.path.abspath(root)
        else:
            absroot = os.path.join(repo, root)
        if not os.path.exists(absroot):
            raise FileNotFoundError(
                f"graftcheck root not found: {root!r} (neither "
                f"{os.path.abspath(root)} nor {absroot})")
        if os.path.isfile(absroot):
            paths = [absroot]
        else:
            paths = []
            for dirpath, dirs, files in os.walk(absroot):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__",))
                paths += [os.path.join(dirpath, f) for f in sorted(files)
                          if f.endswith(".py")]
        for p in paths:
            rel = os.path.relpath(p, repo).replace(os.sep, "/")
            if rel in seen:
                continue
            if any(fnmatch.fnmatch(rel, pat) for pat in exclude):
                continue
            seen.add(rel)
            out.append(SourceFile(p, rel))
    out.sort(key=lambda sf: sf.path)
    return out


# ---------------------------------------------------------------------------
# baseline (waiver) file
# ---------------------------------------------------------------------------

@dataclass
class Waiver:
    rule: str
    path: str
    key: str
    reason: str
    lineno: int
    used: bool = False

    def matches(self, v: Violation) -> bool:
        return (self.rule in (v.rule, "*") and self.path == v.path
                and fnmatch.fnmatch(v.key, self.key))


def load_baseline(path: str) -> Tuple[List[Waiver], List[Violation]]:
    """Parse the baseline file.  Format errors (a waiver without a
    ``--``-separated reason) are violations themselves: an exception
    with no recorded justification is indistinguishable from a
    forgotten bug."""
    waivers: List[Waiver] = []
    errors: List[Violation] = []
    if not os.path.exists(path):
        return waivers, errors
    rel = os.path.relpath(path, REPO).replace(os.sep, "/")
    with open(path, encoding="utf-8") as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            head, sep, reason = line.partition("--")
            parts = head.split()
            if len(parts) != 3 or not sep or not reason.strip():
                errors.append(Violation(
                    "baseline-format", rel, lineno, f"line{lineno}",
                    "baseline entries are 'rule-id path key -- reason' "
                    f"(got {line[:60]!r})"))
                continue
            waivers.append(Waiver(parts[0], parts[1], parts[2],
                                  reason.strip(), lineno))
    return waivers, errors


# ---------------------------------------------------------------------------
# pass registry + runner
# ---------------------------------------------------------------------------

PassFn = Callable[[List[SourceFile]], List[Violation]]


@dataclass
class Pass:
    name: str           # pass name for --rule selection
    rules: Tuple[str, ...]  # rule ids this pass can emit
    fn: PassFn
    doc: str = ""


_PASSES: Dict[str, Pass] = {}


def register_pass(name: str, rules: Sequence[str], doc: str = ""):
    def deco(fn: PassFn) -> PassFn:
        _PASSES[name] = Pass(name, tuple(rules), fn, doc)
        return fn
    return deco


def all_passes() -> Dict[str, Pass]:
    # import for side effect: the passes package registers on import
    from . import passes  # noqa: F401
    return dict(_PASSES)


def run(roots: Sequence[str] = DEFAULT_ROOTS,
        rule_filter: Optional[Sequence[str]] = None,
        baseline_path: Optional[str] = DEFAULT_BASELINE,
        repo: str = REPO,
        exclude: Sequence[str] = ()) -> "Report":
    """Run the selected passes over the tree and apply waivers.

    ``rule_filter`` selects by pass name OR rule id.  Returns a
    :class:`Report`; ``report.violations`` is what should fail a build.
    """
    passes = all_passes()
    selected = []
    if rule_filter:
        wanted = set(rule_filter)
        for p in passes.values():
            if p.name in wanted or wanted.intersection(p.rules):
                selected.append(p)
        unknown = wanted - {p.name for p in passes.values()} \
            - {r for p in passes.values() for r in p.rules}
        if unknown:
            raise ValueError(f"unknown rule(s)/pass(es): {sorted(unknown)}; "
                             f"known passes: {sorted(passes)}")
    else:
        selected = list(passes.values())
    selected.sort(key=lambda p: p.name)

    files = walk_files(roots, repo=repo, exclude=exclude)
    raw: List[Violation] = []
    for sf in files:
        if sf.parse_error is not None:
            raw.append(Violation("syntax-error", sf.path,
                                 sf.parse_error.lineno or 0, "syntax",
                                 f"syntax error: {sf.parse_error.msg}"))
    by_path = {sf.path: sf for sf in files}
    for p in selected:
        raw += p.fn(files)

    waivers: List[Waiver] = []
    if baseline_path:
        waivers, berrs = load_baseline(baseline_path)
        raw += berrs

    kept: List[Violation] = []
    waived: List[Tuple[Violation, str]] = []
    for v in raw:
        sf = by_path.get(v.path)
        if sf is not None and sf.inline_waiver(v.line, v.rule):
            waived.append((v, "inline gc-ok"))
            continue
        w = next((w for w in waivers if w.matches(v)), None)
        if w is not None:
            w.used = True
            waived.append((v, w.reason))
            continue
        kept.append(v)
    # a waiver nothing matched is dead weight — or a typo silently
    # disarming a real rule; only enforced when its rule actually ran
    # AND its target file was in this scan (a subset-root run cannot
    # prove an out-of-scope waiver stale)
    ran_rules = {r for p in selected for r in p.rules}
    for w in waivers:
        if not w.used and w.path in by_path \
                and (w.rule in ran_rules or w.rule == "*"):
            rel = os.path.relpath(baseline_path, repo).replace(os.sep, "/")
            kept.append(Violation(
                "stale-waiver", rel, w.lineno,
                f"{w.rule}:{w.key}",
                f"baseline waiver matches nothing: {w.rule} {w.path} "
                f"{w.key}"))
    kept.sort(key=Violation.sort_key)
    waived.sort(key=lambda t: t[0].sort_key())
    return Report(kept, waived, [p.name for p in selected], len(files))


@dataclass
class Report:
    violations: List[Violation]
    waived: List[Tuple[Violation, str]]
    passes_run: List[str]
    files_scanned: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations

    def render_text(self, show_waived: bool = False) -> str:
        out = [v.render() for v in self.violations]
        if show_waived:
            out += [f"{v.render()}  [waived: {reason}]"
                    for v, reason in self.waived]
        tail = (f"{len(self.violations)} violation(s), "
                f"{len(self.waived)} waived, "
                f"{self.files_scanned} files, "
                f"passes: {', '.join(self.passes_run)}")
        return "\n".join(out + [tail])

    def render_json(self) -> str:
        # stable and sorted so CI diffs are reviewable
        return json.dumps({
            "violations": [v.as_dict() for v in self.violations],
            "waived": [{**v.as_dict(), "reason": r}
                       for v, r in self.waived],
            "passes": sorted(self.passes_run),
            "files_scanned": self.files_scanned,
            "ok": self.ok,
        }, indent=2, sort_keys=True)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m tools.graftcheck",
        description="repo-wide static analysis (see README 'Static "
                    "analysis'): lock discipline, resource pairing, "
                    "donation safety, flag/stat hygiene, exception "
                    "policy")
    ap.add_argument("roots", nargs="*", default=None,
                    help=f"directories/files to scan (default: "
                         f"{' '.join(DEFAULT_ROOTS)})")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only this pass or rule id (repeatable, "
                         "comma-separable)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="stable sorted JSON report on stdout")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="waiver file (empty string disables)")
    ap.add_argument("--show-waived", action="store_true",
                    help="also print waived findings with reasons")
    ap.add_argument("--list-rules", action="store_true",
                    help="list passes and their rule ids, then exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name, p in sorted(all_passes().items()):
            print(f"{name}: {', '.join(p.rules)}")
            if p.doc:
                print(f"    {p.doc}")
        return 0

    rules = None
    if args.rule:
        rules = [r for spec in args.rule for r in spec.split(",") if r]
    try:
        report = run(roots=args.roots or DEFAULT_ROOTS,
                     rule_filter=rules,
                     baseline_path=args.baseline or None)
    except (FileNotFoundError, ValueError) as e:
        print(f"graftcheck: {e}", file=sys.stderr)
        return 2
    sys.stdout.write((report.render_json() if args.as_json
                      else report.render_text(args.show_waived)) + "\n")
    return 0 if report.ok else 1
