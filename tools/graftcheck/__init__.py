"""graftcheck: unified AST-based static analysis for this repo.

One framework (``tools/graftcheck/core.py``), pluggable passes
(``tools/graftcheck/passes/``), one violation format
(``file:line rule-id message``), one waiver/baseline mechanism, one
CLI::

    python -m tools.graftcheck [--rule PASS-OR-RULE] [--json] [roots...]

Wired into tier-1 via ``tests/test_lint.py``; the full rule catalog
with triggering examples lives in README "Static analysis".
"""
from .core import (DEFAULT_ROOTS, Report, Violation, all_passes, main,  # noqa: F401
                   run)
