"""Flag hygiene: the FLAGS_* registry stays live and documented.

Three rules over the ``register_flag`` registry
(``paddle_tpu/flags.py``) and every flag-API call site:

``flag-undefined``
    A literal flag name passed to ``flag_value`` / ``get_flags`` /
    ``set_flags`` (dict keys) that no ``register_flag`` defines — the
    typo catch: the registry raises at runtime, but only on the code
    path that actually executes.

``flag-unused``
    A registered flag that no code anywhere (paddle_tpu/, tools/,
    tests/, bench.py, __graft_entry__.py) ever reads through the flag
    APIs — dead configuration surface an operator can set with no
    effect.  Reference-API-compat flags that are intentionally
    advisory carry baseline waivers.

``flag-undocumented``
    A registered flag whose backtick-quoted name does not appear in
    README.md — a knob that cannot be operated.  This subsumes the
    per-prefix serving/router/fleet README lints.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Set, Tuple

from ..core import (REPO, SourceFile, Violation, call_name,
                    register_pass, walk_files)

# extra roots consulted for read evidence (a flag only tests read is
# still read; violations are only ever attached to the registry file)
READ_EVIDENCE_ROOTS = ("tests", "bench.py", "__graft_entry__.py")
FLAG_READ_FUNCS = {"flag_value", "get_flags"}
# module-level so tests can point the pass at a fixture README
README_PATH = os.path.join(REPO, "README.md")
# read-evidence scans are pure functions of the evidence roots — cache
# per process so repeated core.run() calls (the test suite runs
# several) don't re-read+re-parse the ~100-file tests/ tree each time
_EVIDENCE_CACHE: dict = {}


def _literal_str(node: ast.AST):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


_fn_name = call_name


def scan_file(sf: SourceFile):
    """(defs, reads) from one file: defs = {name: line} from
    register_flag; reads = [(name, line)] from flag_value/get_flags/
    set_flags literal usage."""
    defs: Dict[str, int] = {}
    reads: List[Tuple[str, int]] = []
    if sf.tree is None:
        return defs, reads
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = _fn_name(node)
        if fn == "register_flag" and node.args:
            name = _literal_str(node.args[0])
            if name is not None:
                defs.setdefault(name, node.lineno)
        elif fn in FLAG_READ_FUNCS and node.args:
            name = _literal_str(node.args[0])
            if name is not None:
                reads.append((name, node.lineno))
            elif isinstance(node.args[0], (ast.List, ast.Tuple)):
                for e in node.args[0].elts:
                    nm = _literal_str(e)
                    if nm is not None:
                        reads.append((nm, e.lineno))
        elif fn == "set_flags" and node.args \
                and isinstance(node.args[0], ast.Dict):
            for k in node.args[0].keys:
                nm = _literal_str(k)
                if nm is not None and nm.startswith("FLAGS_"):
                    reads.append((nm, k.lineno))
    return defs, reads


@register_pass(
    "flag-hygiene", ("flag-undefined", "flag-unused",
                     "flag-undocumented"),
    doc="every FLAGS_* defined is read and README-documented; every "
        "FLAGS_* read is defined (typo catch)")
def run(files: List[SourceFile]) -> List[Violation]:
    out: List[Violation] = []
    defs: Dict[str, Tuple[str, int]] = {}   # name -> (path, line)
    reads: List[Tuple[str, str, int]] = []  # (name, path, line)

    scanned_paths = {sf.path for sf in files}
    for sf in files:
        d, r = scan_file(sf)
        for name, line in d.items():
            defs.setdefault(name, (sf.path, line))
        reads += [(n, sf.path, ln) for n, ln in r]

    # the registry file is ALWAYS consulted for definitions, even when
    # the scan roots exclude it — otherwise a subset-root run
    # (`graftcheck paddle_tpu/serving`) reports every real flag read
    # as flag-undefined (violations still attach only to scanned files)
    registry = os.path.join(REPO, "paddle_tpu", "flags.py")
    reg_rel = "paddle_tpu/flags.py"
    if reg_rel not in scanned_paths and os.path.exists(registry):
        sf = SourceFile(registry, reg_rel)
        d, r = scan_file(sf)
        for name, line in d.items():
            defs.setdefault(name, (sf.path, line))
        reads += [(n, sf.path, ln) for n, ln in r]

    # read evidence from tests/bench without attaching violations
    # there (absolute paths: the cwd-first root resolution must not
    # pick up some other project's tests/ directory)
    extra_roots = tuple(
        os.path.join(REPO, r) for r in READ_EVIDENCE_ROOTS
        if os.path.exists(os.path.join(REPO, r)))
    evidence = _EVIDENCE_CACHE.get(extra_roots)
    if evidence is None:
        evidence = []
        for sf in walk_files(extra_roots, repo=REPO):
            d, r = scan_file(sf)
            evidence.append((sf.path, d, r))
        _EVIDENCE_CACHE[extra_roots] = evidence
    for path, d, r in evidence:
        if path in scanned_paths:
            continue
        for name, line in d.items():
            defs.setdefault(name, (path, line))
        reads += [(n, path, ln) for n, ln in r]

    read_names: Set[str] = {n for n, _, _ in reads}

    # flag-undefined: a read of a name the registry never defines,
    # reported only in the scanned tree (tests mint fake flags freely)
    for name, path, line in sorted(set(reads)):
        if name.startswith("FLAGS_") and name not in defs \
                and path in scanned_paths:
            out.append(Violation(
                "flag-undefined", path, line, name,
                f"{name} is not registered in paddle_tpu/flags.py — "
                f"typo, or a flag that was removed"))

    # flag-unused / flag-undocumented, attached to the registration
    readme_path = README_PATH
    documented: Set[str] = set()
    if os.path.exists(readme_path):
        with open(readme_path, encoding="utf-8") as f:
            text = f.read()
        documented = {m for m in _backticked(text)}
    for name, (path, line) in sorted(defs.items()):
        if path not in scanned_paths:
            continue
        if name not in read_names:
            out.append(Violation(
                "flag-unused", path, line, name,
                f"{name} is registered but never read through "
                f"flag_value/get_flags anywhere (paddle_tpu, tools, "
                f"tests, bench) — dead knob; remove it or wire it up"))
        if name not in documented:
            out.append(Violation(
                "flag-undocumented", path, line, name,
                f"{name} is not documented (backtick-quoted) in "
                f"README.md — a knob that cannot be operated"))
    return out


def _backticked(text: str):
    import re
    return re.findall(r"`(FLAGS_[A-Za-z0-9_]+)`", text)
