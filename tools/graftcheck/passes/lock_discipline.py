"""Lock-discipline race detector + lock-acquisition-order checker.

Two rules over every class in the tree:

``lock-bare-access``
    Per class, infer which ``self._*`` attributes the author considers
    lock-protected: any attribute *written or mutated* inside a
    ``with self._lock:``-style block (outside construction).  Then
    flag every access (read, write, or mutation) of such an attribute
    performed with **no lock held** in another non-construction method
    — but only for classes that actually run threads (a
    ``threading.Thread(...)`` constructed anywhere in the class, or an
    explicit ``# graftcheck: threaded`` marker on the class line).
    Construction-phase methods (``__init__`` plus methods reachable
    *only* from construction-phase methods within the class) are
    exempt on both sides: nothing races before the first thread
    starts.  Accesses inside nested functions/lambdas are ignored on
    both sides (their execution context is unknowable statically).

``lock-order``
    Build the lock-acquisition graph: an edge A -> B for every site
    that acquires B while holding A — directly nested ``with`` blocks,
    plus one level of interprocedural closure inside the class (a call
    ``self.m()`` while holding A contributes edges to every lock ``m``
    acquires, transitively through intra-class calls).  Any edge that
    participates in a cycle is a deadlock-potential finding, as is a
    *directly nested* re-acquisition of the same non-reentrant
    ``threading.Lock``.

Lock identity is the *creation site* ``Class.attr`` (or a
module-level name), not the instance: two instances of the same class
interleaving A->B and B->A is exactly the deadlock this catches.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..core import SourceFile, Violation, register_pass

LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition",
              "Semaphore": "Semaphore",
              "BoundedSemaphore": "Semaphore"}
# reentrant kinds: directly nested re-acquisition is legal
REENTRANT = {"RLock", "Condition"}
MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
            "pop", "popleft", "popitem", "remove", "discard", "clear",
            "update", "add", "setdefault", "sort", "reverse",
            "move_to_end"}
THREADED_MARKER = "# graftcheck: threaded"
# pseudo-lock representing "the caller holds the class lock" (the
# `*_locked`-suffix method convention)
CALLER_HELD = "<caller-held>"


def _short(lock_id: str) -> str:
    """Human form of a path-qualified lock id for messages (the path
    is already in the violation's location)."""
    if lock_id == CALLER_HELD:
        return "a caller-held lock (*_locked convention)"
    return lock_id.split("::", 1)[-1]


def _lock_ctor_kind(node: ast.AST) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    return LOCK_CTORS.get(name or "")


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


@dataclass
class Access:
    attr: str
    kind: str            # "read" | "write" | "mutate"
    line: int
    held: Optional[frozenset]  # None = unknown context (nested func)
    method: str


@dataclass
class MethodInfo:
    name: str
    node: ast.AST
    accesses: List[Access] = field(default_factory=list)
    # locks acquired anywhere in the method body (own with-blocks)
    acquires: Set[str] = field(default_factory=set)
    # (held_lock, acquired_lock, line) nesting events
    nestings: List[Tuple[str, str, int]] = field(default_factory=list)
    # (held_locks_frozenset, callee_method_name, line)
    calls_while_held: List[Tuple[frozenset, str, int]] = \
        field(default_factory=list)
    intra_calls: Set[str] = field(default_factory=set)
    # directly nested same-lock re-acquisition sites
    self_nest: List[Tuple[str, int]] = field(default_factory=list)


@dataclass
class ClassInfo:
    name: str
    path: str
    line: int
    lock_attrs: Dict[str, str] = field(default_factory=dict)  # attr->kind
    methods: Dict[str, MethodInfo] = field(default_factory=dict)
    spawns_thread: bool = False
    marker: bool = False

    def init_phase(self) -> Set[str]:
        """Methods reachable ONLY from construction: ``__init__`` plus
        the fixpoint of methods all of whose intra-class callers are
        construction-phase.  A method nobody in the class calls is an
        entry point, never construction-phase."""
        callers: Dict[str, Set[str]] = {m: set() for m in self.methods}
        for m in self.methods.values():
            for callee in m.intra_calls:
                if callee in callers:
                    callers[callee].add(m.name)
        phase = {"__init__"} & set(self.methods)
        changed = True
        while changed:
            changed = False
            for name, cs in callers.items():
                if name in phase or not cs:
                    continue
                if cs <= phase:
                    phase.add(name)
                    changed = True
        return phase


class _MethodWalker:
    """Walks one method body tracking the set of held locks per
    statement; records attribute accesses, lock nestings, and
    intra-class calls."""

    def __init__(self, cls: ClassInfo, mi: MethodInfo,
                 module_locks: Dict[str, str]):
        self.cls = cls
        self.mi = mi
        self.module_locks = module_locks

    # -- lock resolution ----------------------------------------------------
    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        """The lock id a with-item acquires, or None if it is not a
        known lock.  Ids are PATH-qualified (``path::Class.X`` /
        ``path::NAME``) so two unrelated classes that happen to share
        a name never share lock-order graph nodes."""
        a = _self_attr(expr)
        if a is not None and a in self.cls.lock_attrs:
            return f"{self.cls.path}::{self.cls.name}.{a}"
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return f"{self.cls.path}::{expr.id}"
        return None

    def _lock_kind(self, lock_id: str) -> str:
        rest = lock_id.split("::", 1)[-1]
        if "." in rest:
            return self.cls.lock_attrs.get(rest.split(".", 1)[1], "Lock")
        return self.module_locks.get(rest, "Lock")

    # -- traversal ----------------------------------------------------------
    def walk(self):
        node = self.mi.node
        held0 = frozenset()
        if self.mi.name.endswith("_locked"):
            # repo convention: a `*_locked` method documents that its
            # CALLER holds the class lock — accesses inside are
            # lock-protected by contract, not bare
            held0 = frozenset({CALLER_HELD})
        self._stmts(node.body, held0)

    def _stmts(self, stmts, held: frozenset):
        for st in stmts:
            self._stmt(st, held)

    def _stmt(self, st: ast.stmt, held: frozenset):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            # nested scope: runs at an unknowable time/context
            for sub in ast.walk(st):
                a = _self_attr(sub)
                if a is not None:
                    self.mi.accesses.append(Access(
                        a, "read", sub.lineno, None, self.mi.name))
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in st.items:
                self._expr(item.context_expr, held, lock_of_with=True)
                lid = self._lock_id(item.context_expr)
                if item.optional_vars is not None:
                    self._expr(item.optional_vars, held)
                if lid is not None:
                    for h in held | frozenset(acquired):
                        if h == lid:
                            if self._lock_kind(lid) not in REENTRANT:
                                self.mi.self_nest.append(
                                    (lid, item.context_expr.lineno))
                        else:
                            self.mi.nestings.append(
                                (h, lid, item.context_expr.lineno))
                    acquired.append(lid)
                    self.mi.acquires.add(lid)
            self._stmts(st.body, held | frozenset(acquired))
            return
        if isinstance(st, ast.Try):
            self._stmts(st.body, held)
            for h in st.handlers:
                if h.type is not None:
                    self._expr(h.type, held)
                self._stmts(h.body, held)
            self._stmts(st.orelse, held)
            self._stmts(st.finalbody, held)
            return
        if isinstance(st, (ast.If, ast.While)):
            self._expr(st.test, held)
            self._stmts(st.body, held)
            self._stmts(st.orelse, held)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._assign_target(st.target, held)
            self._expr(st.iter, held)
            self._stmts(st.body, held)
            self._stmts(st.orelse, held)
            return
        if isinstance(st, ast.Assign):
            self._expr(st.value, held)
            for t in st.targets:
                self._assign_target(t, held)
            return
        if isinstance(st, ast.AugAssign):
            self._expr(st.value, held)
            # an augmented target is read AND written
            self._assign_target(st.target, held, aug=True)
            return
        if isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._expr(st.value, held)
            self._assign_target(st.target, held)
            return
        if isinstance(st, ast.Delete):
            for t in st.targets:
                self._assign_target(t, held)
            return
        if isinstance(st, (ast.Return, ast.Expr)):
            if st.value is not None:
                self._expr(st.value, held)
            return
        if isinstance(st, (ast.Raise,)):
            if st.exc is not None:
                self._expr(st.exc, held)
            if st.cause is not None:
                self._expr(st.cause, held)
            return
        if isinstance(st, ast.Assert):
            self._expr(st.test, held)
            if st.msg is not None:
                self._expr(st.msg, held)
            return
        # Pass/Break/Continue/Import/Global/Nonlocal: nothing to do
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._expr(child, held)
            elif isinstance(child, ast.stmt):
                self._stmt(child, held)

    def _assign_target(self, t: ast.expr, held: frozenset,
                       aug: bool = False):
        a = _self_attr(t)
        if a is not None:
            self.mi.accesses.append(Access(a, "write", t.lineno, held,
                                           self.mi.name))
            return
        if isinstance(t, ast.Subscript):
            base = _self_attr(t.value)
            if base is not None:
                self.mi.accesses.append(Access(base, "mutate", t.lineno,
                                               held, self.mi.name))
            else:
                self._expr(t.value, held)
            self._expr(t.slice, held)
            return
        if isinstance(t, ast.Attribute):
            self._expr(t.value, held)
            return
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._assign_target(e, held, aug=aug)
            return
        if isinstance(t, ast.Starred):
            self._assign_target(t.value, held, aug=aug)
            return
        self._expr(t, held)

    def _expr(self, e: ast.expr, held: frozenset,
              lock_of_with: bool = False):
        if e is None:
            return
        if isinstance(e, (ast.Lambda,)):
            for sub in ast.walk(e.body):
                a = _self_attr(sub)
                if a is not None:
                    self.mi.accesses.append(Access(
                        a, "read", sub.lineno, None, self.mi.name))
            return
        if isinstance(e, ast.Call):
            f = e.func
            handled_func = False
            if isinstance(f, ast.Attribute):
                base_attr = _self_attr(f.value)
                if base_attr is not None:
                    # self.X.method(...): mutation when method mutates
                    kind = "mutate" if f.attr in MUTATORS else "read"
                    self.mi.accesses.append(Access(
                        base_attr, kind, f.lineno, held, self.mi.name))
                    handled_func = True
                elif isinstance(f.value, ast.Name) and \
                        f.value.id == "self":
                    pass  # plain self.m(...) handled below
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id == "self":
                # intra-class call
                self.mi.intra_calls.add(f.attr)
                if held:
                    self.mi.calls_while_held.append(
                        (held, f.attr, e.lineno))
                handled_func = True
            if not handled_func:
                self._expr(f, held)
            for a in e.args:
                self._expr(a, held)
            for kw in e.keywords:
                self._expr(kw.value, held)
            return
        a = _self_attr(e)
        if a is not None:
            if not (lock_of_with and a in self.cls.lock_attrs):
                self.mi.accesses.append(Access(a, "read", e.lineno, held,
                                               self.mi.name))
            return
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self._expr(child, held)


def collect_module(sf: SourceFile):
    """(classes, module_locks) for one file."""
    module_locks: Dict[str, str] = {}
    classes: List[ClassInfo] = []
    if sf.tree is None:
        return classes, module_locks
    for node in sf.tree.body:
        if isinstance(node, ast.Assign):
            kind = _lock_ctor_kind(node.value)
            if kind:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        module_locks[t.id] = kind
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        ci = ClassInfo(node.name, sf.path, node.lineno)
        ci.marker = THREADED_MARKER in sf.line_text(node.lineno)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                kind = _lock_ctor_kind(sub.value)
                if kind:
                    for t in sub.targets:
                        a = _self_attr(t)
                        if a is not None:
                            ci.lock_attrs[a] = kind
            if isinstance(sub, ast.Call):
                f = sub.func
                nm = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else "")
                if nm == "Thread":
                    ci.spawns_thread = True
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mi = MethodInfo(item.name, item)
                ci.methods[item.name] = mi
                _MethodWalker(ci, mi, module_locks).walk()
        classes.append(ci)
    return classes, module_locks


def _transitive_acquires(ci: ClassInfo) -> Dict[str, Set[str]]:
    """For each method: the locks it can acquire, transitively through
    intra-class calls (fixpoint; recursion converges)."""
    acq = {m.name: set(m.acquires) for m in ci.methods.values()}
    changed = True
    while changed:
        changed = False
        for m in ci.methods.values():
            for callee in m.intra_calls:
                extra = acq.get(callee, set()) - acq[m.name]
                if extra:
                    acq[m.name] |= extra
                    changed = True
    return acq


@register_pass(
    "lock-discipline", ("lock-bare-access", "lock-order"),
    doc="per-class lock-protected attribute inference + cross-method "
        "bare-access race detection + lock-acquisition-order cycles")
def run(files: List[SourceFile]) -> List[Violation]:
    out: List[Violation] = []
    # global lock-order graph: edge -> first site observed
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    for sf in files:
        classes, _module_locks = collect_module(sf)
        for ci in classes:
            if not ci.lock_attrs and not any(
                    m.nestings or m.self_nest for m in ci.methods.values()):
                continue
            init_phase = ci.init_phase()
            threaded = ci.spawns_thread or ci.marker

            # --- protected-attribute inference + bare accesses -------------
            protected: Dict[str, Set[str]] = {}  # attr -> protecting locks
            for m in ci.methods.values():
                if m.name in init_phase:
                    continue
                for acc in m.accesses:
                    if acc.held and acc.kind in ("write", "mutate") \
                            and acc.attr not in ci.lock_attrs:
                        protected.setdefault(acc.attr, set()).update(
                            acc.held)
            if threaded and protected:
                seen = set()
                for m in ci.methods.values():
                    if m.name in init_phase:
                        continue
                    for acc in m.accesses:
                        if acc.attr not in protected \
                                or acc.held is None:
                            continue
                        # identity matters: holding an UNRELATED lock
                        # is not protection (reading under _n_lock an
                        # attr written under _cv is still a race);
                        # CALLER_HELD is a wildcard on either side
                        want = protected[acc.attr]
                        if acc.held & want or CALLER_HELD in acc.held \
                                or CALLER_HELD in want:
                            continue
                        dkey = (acc.attr, m.name)
                        if dkey in seen:
                            continue
                        seen.add(dkey)
                        locks = ", ".join(
                            _short(l) for l in sorted(want))
                        how = ("with no lock held" if not acc.held
                               else "holding only " + ", ".join(
                                   _short(l)
                                   for l in sorted(acc.held)))
                        out.append(Violation(
                            "lock-bare-access", sf.path, acc.line,
                            f"{ci.name}.{m.name}.{acc.attr}",
                            f"self.{acc.attr} is written under {locks} "
                            f"elsewhere in {ci.name} but accessed here "
                            f"{how} ({acc.kind}) — take the "
                            f"protecting lock or waive with a reason"))

            # --- lock-order graph ------------------------------------------
            tacq = _transitive_acquires(ci)
            for m in ci.methods.values():
                for held, acquired, line in m.nestings:
                    if CALLER_HELD not in (held, acquired):
                        edges.setdefault((held, acquired),
                                         (sf.path, line))
                for held, callee, line in m.calls_while_held:
                    for lid in tacq.get(callee, ()):  # interprocedural
                        for h in held:
                            if h != lid and h != CALLER_HELD:
                                edges.setdefault((h, lid),
                                                 (sf.path, line))
                for lid, line in m.self_nest:
                    out.append(Violation(
                        "lock-order", sf.path, line,
                        f"{_short(lid)}->{_short(lid)}",
                        f"non-reentrant {_short(lid)} re-acquired "
                        f"while already held (self-deadlock)"))

    # --- cycle detection over the global graph -----------------------------
    adj: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)

    def reaches(src: str, dst: str) -> bool:
        seen, stack = set(), [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(adj.get(n, ()))
        return False

    for (a, b), (path, line) in sorted(edges.items()):
        if reaches(b, a):
            back = edges.get((b, a))
            via = (f"(reverse edge at {back[0]}:{back[1]})" if back
                   else "(via intermediate locks)")
            out.append(Violation(
                "lock-order", path, line,
                f"{_short(a)}->{_short(b)}",
                f"acquiring {_short(b)} while holding {_short(a)} "
                f"conflicts with an observed opposite ordering {via} "
                f"— deadlock potential; pick one global order"))
    return out
