"""graftcheck passes.  Importing this package registers every pass
with :mod:`tools.graftcheck.core` (see ``register_pass``)."""
from . import (donation_safety, exception_policy, flag_hygiene,  # noqa: F401
               lock_discipline, resource_pairing, stat_catalog)
