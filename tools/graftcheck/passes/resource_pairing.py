"""Resource-pairing checker: begin/end pairs must balance on every
exit path of their owning scope, or ownership must visibly transfer.

Three rules:

``pair-span``
    Every ``span_begin(...)`` handle must be ``span_end(...)``-ed in
    the same function, or *escape* (stored on an object/container,
    returned, or passed to another call — ownership transferred).  A
    discarded handle (bare expression statement) can never be ended:
    the span leaks open and its trace is never recorded.

``pair-acquire``
    Every explicit ``<lock>.acquire()`` (on a lock-named receiver:
    ``*lock*``, ``*_cv*``, ``*sem*``, ``*slots*``) needs a matching
    ``<lock>.release()`` on the same receiver in the same function,
    and at least one such release must sit in a ``finally`` block —
    an exception between acquire and a straight-line release leaves
    the lock held forever (prefer ``with``).  Conditional acquires
    (``if not x.acquire(timeout=...)``) follow the same contract.

``pair-refcount``
    ``pool.alloc()`` / ``pool.incref(pages)`` bookkeeping: a
    discarded ``alloc()`` result leaks a page outright; an
    ``alloc()``/``incref()`` whose pages stay in a local that neither
    escapes nor is ``decref``-ed in the function leaks on every path.
    Class-level balance: a class that increfs/allocs must decref
    *somewhere* (a class that only ever takes references cannot give
    them back).

``pair-draft``
    Speculative-decode draft-page discipline: a function that calls
    ``_acquire_draft_pages`` (provisional KV pages for an unverified
    draft) must also call ``_rollback_draft_pages`` or
    ``_release_pages`` in the same function — a rejected draft whose
    pages are never rolled back (or a fault path that skips the
    slot-release) strands refcounts the pool can only leak.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ..core import SourceFile, Violation, call_name, register_pass

_LOCKISH_RE = re.compile(r"lock|_cv\b|cv$|sem|slots|mutex", re.I)
_POOLISH_RE = re.compile(r"pool", re.I)


def _recv_repr(node: ast.AST) -> str:
    """Canonical text of a call receiver ('self._lock', '_ring_lock',
    'slot.pages', ...) for same-receiver matching."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_recv_repr(node.value)}.{node.attr}"
    return ast.dump(node)


_func_name = call_name


def _functions(sf: SourceFile):
    """(qualname, node) for every function/method, outermost only
    (nested defs analyzed as their own scopes)."""
    if sf.tree is None:
        return
    stack: List[Tuple[str, ast.AST]] = [("", sf.tree)]
    while stack:
        prefix, node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}.{child.name}" if prefix else child.name
                yield qn, child
                stack.append((qn, child))
            elif isinstance(child, ast.ClassDef):
                qn = f"{prefix}.{child.name}" if prefix else child.name
                stack.append((qn, child))


def _own_nodes(fn: ast.AST):
    """AST nodes of this function, EXCLUDING nested function bodies
    (each nested scope is analyzed separately)."""
    todo = list(ast.iter_child_nodes(fn))
    while todo:
        n = todo.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        todo.extend(ast.iter_child_nodes(n))


def _in_finally_lines(fn: ast.AST) -> Set[int]:
    lines: Set[int] = set()
    for n in _own_nodes(fn):
        if isinstance(n, ast.Try):
            for st in n.finalbody:
                for sub in ast.walk(st):
                    if hasattr(sub, "lineno"):
                        lines.add(sub.lineno)
    return lines


def _name_escapes(fn: ast.AST, name: str, after_line: int,
                  skip_call_attrs: Tuple[str, ...] = ()) -> bool:
    """Does ``name`` visibly leave this scope after ``after_line``?
    Escape = used as a call argument (any call whose method is not in
    ``skip_call_attrs``), returned/yielded, stored into an attribute /
    subscript / container literal, or captured in a closure."""
    for n in _own_nodes(fn):
        line = getattr(n, "lineno", 0)
        if line < after_line:
            continue
        if isinstance(n, ast.Call):
            fname = _func_name(n)
            if fname in skip_call_attrs:
                continue
            for arg in list(n.args) + [kw.value for kw in n.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and sub.id == name:
                        return True
        if isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)) \
                and n.value is not None:
            for sub in ast.walk(n.value):
                if isinstance(sub, ast.Name) and sub.id == name:
                    return True
        if isinstance(n, ast.Assign):
            rhs_has = any(isinstance(s, ast.Name) and s.id == name
                          for s in ast.walk(n.value))
            if rhs_has and any(
                    not isinstance(t, ast.Name) for t in n.targets):
                return True
            if rhs_has and any(isinstance(t, ast.Name) and t.id != name
                               for t in n.targets):
                # aliased to another local: give up tracking, assume ok
                return True
    return False


@register_pass(
    "resource-pairing", ("pair-span", "pair-acquire", "pair-refcount",
                         "pair-draft"),
    doc="span_begin/span_end, lock acquire/release (exception-safe), "
        "PagePool alloc/incref/decref pairing, and speculative "
        "draft-page acquire/rollback pairing")
def run(files: List[SourceFile]) -> List[Violation]:
    out: List[Violation] = []
    for sf in files:
        if sf.tree is None:
            continue
        # cheap textual prefilter: most files contain none of the
        # paired APIs, and per-function AST walks are the hot path
        has_span = "span_begin" in sf.text
        has_acq = ".acquire(" in sf.text
        has_ref = "incref" in sf.text or ".alloc(" in sf.text
        has_draft = "_acquire_draft_pages" in sf.text
        if not (has_span or has_acq or has_ref or has_draft):
            continue
        for qn, fn in _functions(sf):
            if has_span:
                out += _check_spans(sf, qn, fn)
            if has_acq:
                out += _check_acquires(sf, qn, fn)
            if has_ref:
                out += _check_refcounts_fn(sf, qn, fn)
            if has_draft:
                out += _check_draft_pages(sf, qn, fn)
        if has_ref:
            out += _check_refcounts_class(sf)
    return out


# -- pair-span ---------------------------------------------------------------

def _check_spans(sf: SourceFile, qn: str, fn: ast.AST) -> List[Violation]:
    out: List[Violation] = []
    # name -> line of span_begin assignment
    begun: Dict[str, int] = {}
    ended: Set[str] = set()
    for n in _own_nodes(fn):
        if isinstance(n, ast.Expr) and isinstance(n.value, ast.Call) \
                and _func_name(n.value) == "span_begin":
            out.append(Violation(
                "pair-span", sf.path, n.lineno, f"{qn}:discard",
                "span_begin() handle discarded — nothing can ever "
                "span_end() it; keep the handle or use trace_span()"))
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                and _func_name(n.value) == "span_begin":
            t = n.targets[0]
            if isinstance(t, ast.Name):
                begun[t.id] = n.lineno
            # assignment to an attribute/subscript IS the escape
        if isinstance(n, ast.Call) and _func_name(n) == "span_end":
            for arg in n.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name):
                        ended.add(sub.id)
    for name, line in sorted(begun.items()):
        if name in ended:
            continue
        if _name_escapes(fn, name, line, skip_call_attrs=("span_begin",)):
            continue
        out.append(Violation(
            "pair-span", sf.path, line, f"{qn}:{name}",
            f"span handle {name!r} from span_begin() is neither "
            f"span_end()-ed nor handed off in this function — the "
            f"span leaks open"))
    return out


# -- pair-acquire ------------------------------------------------------------

def _check_acquires(sf: SourceFile, qn: str, fn: ast.AST) -> List[Violation]:
    out: List[Violation] = []
    acquires: List[Tuple[str, int]] = []
    releases: List[Tuple[str, int]] = []
    for n in _own_nodes(fn):
        if not isinstance(n, ast.Call) or \
                not isinstance(n.func, ast.Attribute):
            continue
        recv = _recv_repr(n.func.value)
        if not _LOCKISH_RE.search(recv):
            continue
        if n.func.attr == "acquire":
            acquires.append((recv, n.lineno))
        elif n.func.attr == "release":
            releases.append((recv, n.lineno))
    if not acquires:
        return out
    finally_lines = _in_finally_lines(fn)
    for recv, line in acquires:
        same = [ln for r, ln in releases if r == recv]
        if not same:
            out.append(Violation(
                "pair-acquire", sf.path, line, f"{qn}:{recv}",
                f"{recv}.acquire() has no matching {recv}.release() in "
                f"this function — use `with {recv}:` or pair it"))
        elif not any(ln in finally_lines for ln in same):
            out.append(Violation(
                "pair-acquire", sf.path, line, f"{qn}:{recv}",
                f"{recv}.release() is not on the exception path (no "
                f"finally) — an exception after acquire leaves "
                f"{recv} held forever; use `with` or try/finally"))
    return out


# -- pair-draft --------------------------------------------------------------

def _check_draft_pages(sf: SourceFile, qn: str,
                       fn: ast.AST) -> List[Violation]:
    """A caller of _acquire_draft_pages holds provisional page refs
    for a draft that may be rejected; without a _rollback_draft_pages
    (or a whole-slot _release_pages) in the same function there is no
    path that gives the rejected rows' pages back."""
    out: List[Violation] = []
    acquire_line = None
    has_rollback = False
    for n in _own_nodes(fn):
        if not isinstance(n, ast.Call):
            continue
        name = _func_name(n)
        if name == "_acquire_draft_pages":
            acquire_line = acquire_line or n.lineno
        elif name in ("_rollback_draft_pages", "_release_pages"):
            has_rollback = True
    if acquire_line is not None and not has_rollback \
            and getattr(fn, "name", "") != "_acquire_draft_pages":
        # the acquire helper itself rolls back internally on the
        # exhaustion path; every OTHER caller owes an explicit pair
        out.append(Violation(
            "pair-draft", sf.path, acquire_line, f"{qn}:draft-pages",
            "_acquire_draft_pages() without _rollback_draft_pages() "
            "or _release_pages() in this function — rejected-draft "
            "pages have no give-back path and leak refcounts"))
    return out


# -- pair-refcount -----------------------------------------------------------

def _check_refcounts_fn(sf: SourceFile, qn: str,
                        fn: ast.AST) -> List[Violation]:
    out: List[Violation] = []
    has_decref = any(isinstance(n, ast.Call)
                     and isinstance(n.func, ast.Attribute)
                     and n.func.attr in ("decref", "free")
                     for n in _own_nodes(fn))
    for n in _own_nodes(fn):
        # discarded alloc() on a pool-ish receiver
        if isinstance(n, ast.Expr) and isinstance(n.value, ast.Call) \
                and isinstance(n.value.func, ast.Attribute) \
                and n.value.func.attr == "alloc" \
                and _POOLISH_RE.search(_recv_repr(n.value.func.value)):
            out.append(Violation(
                "pair-refcount", sf.path, n.lineno, f"{qn}:alloc-discard",
                "pool.alloc() result discarded — the page's refcount "
                "is 1 with no holder; it leaks"))
        # p = pool.alloc() where p never escapes and no decref here
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                and isinstance(n.value.func, ast.Attribute) \
                and n.value.func.attr == "alloc" \
                and _POOLISH_RE.search(_recv_repr(n.value.func.value)):
            t = n.targets[0]
            if isinstance(t, ast.Name) and not has_decref and \
                    not _name_escapes(fn, t.id, n.lineno,
                                      skip_call_attrs=("alloc",)):
                out.append(Violation(
                    "pair-refcount", sf.path, n.lineno,
                    f"{qn}:{t.id}",
                    f"page handle {t.id!r} from alloc() neither "
                    f"escapes nor is decref'd in this function — "
                    f"leaks on every path"))
        # incref(name) with no decref and no ownership transfer
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "incref" and n.args:
            arg = n.args[0]
            if isinstance(arg, ast.Name) and not has_decref and \
                    not _name_escapes(fn, arg.id, n.lineno,
                                      skip_call_attrs=("incref",)):
                out.append(Violation(
                    "pair-refcount", sf.path, n.lineno,
                    f"{qn}:{arg.id}",
                    f"incref({arg.id}) without a decref or visible "
                    f"ownership transfer of {arg.id!r} in this "
                    f"function — the references leak"))
    return out


def _check_refcounts_class(sf: SourceFile) -> List[Violation]:
    """A class that takes references must be able to give them back."""
    out: List[Violation] = []
    if sf.tree is None:
        return out
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        takes = gives = None
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute):
                if sub.func.attr in ("incref", "alloc") and \
                        _poolish_call(sub):
                    takes = takes or sub.lineno
                if sub.func.attr in ("decref", "free"):
                    gives = gives or sub.lineno
        if takes and not gives:
            out.append(Violation(
                "pair-refcount", sf.path, takes,
                f"{node.name}:class-balance",
                f"class {node.name} increfs/allocs pool pages but "
                f"never decrefs anywhere — references can only leak"))
    return out


def _poolish_call(call: ast.Call) -> bool:
    if call.func.attr == "incref":
        return True
    return bool(_POOLISH_RE.search(_recv_repr(call.func.value)))
