"""Stat-catalog hygiene + the strict Prometheus exposition validator.

The graftcheck port of ``tools/check_stat_catalog.py`` (which remains
as a thin CLI shim importing from here).  Rule ``stat-undocumented``:
every *literal* metric name passed to the monitor / telemetry APIs
must appear backtick-quoted in the README's stat catalog — renamed
stats silently break every dashboard reading the old name.

This module also fronts the strict Prometheus text-format validation
(:func:`validate_exposition`): the implementation lives in
``paddle_tpu/promtext.py`` — the SAME module the fleet router's
federation scraper parses replica ``/metrics`` with, so the validator
and the scraper can never disagree about the format.  It is loaded
here by file path (never ``import paddle_tpu``): the lint must not
import the heavyweight package it is analyzing.
:func:`validate_exposition_violations` returns the findings as
:class:`~tools.graftcheck.core.Violation` records carrying
``file:line`` provenance — family-level errors (missing
``_sum``/``_count``, no ``+Inf`` bucket) anchor to the family's
``# TYPE`` line instead of printing a bare metric name.
"""
from __future__ import annotations

import ast
import importlib.util
import os
import re
import sys
from typing import List, Optional, Tuple

from ..core import REPO, SourceFile, Violation, register_pass


def _load_promtext():
    """The shared exposition module, WITHOUT importing the paddle_tpu
    package (promtext.py is stdlib-only by contract; an already-loaded
    runtime copy is reused so the two views share one module)."""
    mod = sys.modules.get("paddle_tpu.promtext")
    if mod is not None:
        return mod
    mod = sys.modules.get("_graftcheck_promtext")
    if mod is not None:
        return mod
    path = os.path.join(REPO, "paddle_tpu", "promtext.py")
    spec = importlib.util.spec_from_file_location("_graftcheck_promtext",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_graftcheck_promtext"] = mod
    spec.loader.exec_module(mod)
    return mod


promtext = _load_promtext()

BARE_FUNCS = {"stat_add", "stat_get", "gauge_set", "histogram_observe"}
TELEMETRY_ATTRS = {"gauge_set", "histogram_observe", "timer"}
REGISTRY_ATTRS = {"gauge", "histogram", "timer"}

CATALOG_MARKER = "**Stat catalog**"
# module-level so tests can point the pass at a fixture README
README_PATH = os.path.join(REPO, "README.md")


def _first_str_arg(node: ast.Call):
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def _value_id(node) -> str:
    """Best-effort identifier of an attribute's object ('telemetry',
    '_monitor', 'self._metrics' -> '_metrics', ...)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def extract_names_from_tree(tree: ast.AST) -> List[Tuple[str, int]]:
    """(name, lineno) for every literal metric name in a parsed tree."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        hit = False
        if isinstance(func, ast.Name) and func.id in BARE_FUNCS:
            hit = True
        elif isinstance(func, ast.Attribute):
            # exact-id match (modulo leading underscores for module
            # aliases like `_monitor`): a substring match would drag in
            # ordinary dict .get() calls on unrelated names
            vid = _value_id(func.value).lstrip("_")
            if func.attr == "get" and vid == "monitor":
                hit = True
            elif func.attr in TELEMETRY_ATTRS and vid == "telemetry":
                hit = True
            elif func.attr in REGISTRY_ATTRS and vid == "metrics":
                hit = True
        if not hit:
            continue
        name = _first_str_arg(node)
        if name is not None:
            out.append((name, node.lineno))
    return out


def extract_names(path: str):
    """(name, path, lineno) triples for one file — the historical
    shim-facing API."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, path)
    except SyntaxError as e:
        raise SystemExit(f"{path}:{e.lineno}: syntax error: {e.msg}")
    return [(n, path, ln) for n, ln in extract_names_from_tree(tree)]


def catalog_names(readme_path: str) -> set:
    """Backtick-quoted identifiers in the README's stat-catalog section
    (from the CATALOG_MARKER to the next `## ` heading).  Scoping to
    the catalog matters: a metric name that happens to collide with any
    backticked word elsewhere in the README (a flag, a heartbeat field)
    must not pass as documented.  Falls back to the whole file when the
    marker is absent (minimal/test READMEs)."""
    with open(readme_path, encoding="utf-8") as f:
        text = f.read()
    start = text.find(CATALOG_MARKER)
    if start >= 0:
        end = text.find("\n## ", start)
        text = text[start:end if end >= 0 else len(text)]
    return set(re.findall(r"`([A-Za-z_][A-Za-z0-9_]*)`", text))


@register_pass(
    "stat-catalog", ("stat-undocumented",),
    doc="every literal metric name used through the monitor/telemetry "
        "APIs must be in the README stat catalog")
def run(files: List[SourceFile]) -> List[Violation]:
    out: List[Violation] = []
    readme = README_PATH
    documented = catalog_names(readme) if os.path.exists(readme) else set()
    for sf in files:
        if sf.tree is None:
            continue
        for name, line in extract_names_from_tree(sf.tree):
            if name not in documented:
                out.append(Violation(
                    "stat-undocumented", sf.path, line, name,
                    f"metric {name!r} is not in the README stat "
                    f"catalog -- document it (backtick-quoted) or "
                    f"rename it to a documented one"))
    return out


# ---------------------------------------------------------------------------
# strict Prometheus text-exposition validation (shared implementation:
# paddle_tpu/promtext.py — see _load_promtext above)
# ---------------------------------------------------------------------------

# historical re-exports: tests and the check_stat_catalog shim import
# these names from here
PROM_NAME_RE = promtext.PROM_NAME_RE
PROM_TYPES = promtext.PROM_TYPES
_SAMPLE_RE = promtext.SAMPLE_RE
_LABELS_RE = promtext.LABELS_RE
_family_of = promtext._family_of


def _validate_exposition_impl(text: str) -> List[Tuple[int, str]]:
    """Strict Prometheus text-exposition validation; returns
    ``(lineno, message)`` pairs (see ``paddle_tpu/promtext.py`` for
    the enforced rules).  Family-level findings (missing ``+Inf``
    bucket / ``_sum`` / ``_count``) carry the family's ``# TYPE``
    line — provenance the bare-name messages used to lack."""
    return promtext.validate_lines(text)


def validate_exposition(text: str) -> List[str]:
    """Historical string API: ``["line N: problem", ...]`` (empty =
    valid) — what tests and the old CLI consume."""
    return [f"line {ln}: {msg}"
            for ln, msg in _validate_exposition_impl(text)]


def validate_exposition_violations(text: str,
                                   path: str = "<prom>"
                                   ) -> List[Violation]:
    """The same findings in the shared graftcheck violation format,
    each carrying ``file:line`` provenance."""
    return [Violation("prom-format", path, ln, f"prom@{ln}", msg)
            for ln, msg in _validate_exposition_impl(text)]
