"""Stat-catalog hygiene + the strict Prometheus exposition validator.

The graftcheck port of ``tools/check_stat_catalog.py`` (which remains
as a thin CLI shim importing from here).  Rule ``stat-undocumented``:
every *literal* metric name passed to the monitor / telemetry APIs
must appear backtick-quoted in the README's stat catalog — renamed
stats silently break every dashboard reading the old name.

This module also owns :func:`validate_exposition` (strict Prometheus
text-format validation).  :func:`validate_exposition_violations`
returns the same findings as :class:`~tools.graftcheck.core.Violation`
records carrying ``file:line`` provenance — family-level errors
(missing ``_sum``/``_count``, no ``+Inf`` bucket) anchor to the
family's ``# TYPE`` line instead of printing a bare metric name.
"""
from __future__ import annotations

import ast
import os
import re
from typing import List, Optional, Tuple

from ..core import REPO, SourceFile, Violation, register_pass

BARE_FUNCS = {"stat_add", "stat_get", "gauge_set", "histogram_observe"}
TELEMETRY_ATTRS = {"gauge_set", "histogram_observe", "timer"}
REGISTRY_ATTRS = {"gauge", "histogram", "timer"}

CATALOG_MARKER = "**Stat catalog**"
# module-level so tests can point the pass at a fixture README
README_PATH = os.path.join(REPO, "README.md")


def _first_str_arg(node: ast.Call):
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def _value_id(node) -> str:
    """Best-effort identifier of an attribute's object ('telemetry',
    '_monitor', 'self._metrics' -> '_metrics', ...)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def extract_names_from_tree(tree: ast.AST) -> List[Tuple[str, int]]:
    """(name, lineno) for every literal metric name in a parsed tree."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        hit = False
        if isinstance(func, ast.Name) and func.id in BARE_FUNCS:
            hit = True
        elif isinstance(func, ast.Attribute):
            # exact-id match (modulo leading underscores for module
            # aliases like `_monitor`): a substring match would drag in
            # ordinary dict .get() calls on unrelated names
            vid = _value_id(func.value).lstrip("_")
            if func.attr == "get" and vid == "monitor":
                hit = True
            elif func.attr in TELEMETRY_ATTRS and vid == "telemetry":
                hit = True
            elif func.attr in REGISTRY_ATTRS and vid == "metrics":
                hit = True
        if not hit:
            continue
        name = _first_str_arg(node)
        if name is not None:
            out.append((name, node.lineno))
    return out


def extract_names(path: str):
    """(name, path, lineno) triples for one file — the historical
    shim-facing API."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, path)
    except SyntaxError as e:
        raise SystemExit(f"{path}:{e.lineno}: syntax error: {e.msg}")
    return [(n, path, ln) for n, ln in extract_names_from_tree(tree)]


def catalog_names(readme_path: str) -> set:
    """Backtick-quoted identifiers in the README's stat-catalog section
    (from the CATALOG_MARKER to the next `## ` heading).  Scoping to
    the catalog matters: a metric name that happens to collide with any
    backticked word elsewhere in the README (a flag, a heartbeat field)
    must not pass as documented.  Falls back to the whole file when the
    marker is absent (minimal/test READMEs)."""
    with open(readme_path, encoding="utf-8") as f:
        text = f.read()
    start = text.find(CATALOG_MARKER)
    if start >= 0:
        end = text.find("\n## ", start)
        text = text[start:end if end >= 0 else len(text)]
    return set(re.findall(r"`([A-Za-z_][A-Za-z0-9_]*)`", text))


@register_pass(
    "stat-catalog", ("stat-undocumented",),
    doc="every literal metric name used through the monitor/telemetry "
        "APIs must be in the README stat catalog")
def run(files: List[SourceFile]) -> List[Violation]:
    out: List[Violation] = []
    readme = README_PATH
    documented = catalog_names(readme) if os.path.exists(readme) else set()
    for sf in files:
        if sf.tree is None:
            continue
        for name, line in extract_names_from_tree(sf.tree):
            if name not in documented:
                out.append(Violation(
                    "stat-undocumented", sf.path, line, name,
                    f"metric {name!r} is not in the README stat "
                    f"catalog -- document it (backtick-quoted) or "
                    f"rename it to a documented one"))
    return out


# ---------------------------------------------------------------------------
# strict Prometheus text-exposition validation
# ---------------------------------------------------------------------------

PROM_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
PROM_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"           # metric name
    r"(\{[^{}]*\})?"                          # optional {labels}
    r" (-?(?:[0-9.eE+-]+|\+?Inf|-Inf|NaN))"   # value (one space before)
    r"( [0-9]+)?$")                           # optional ms timestamp
_LABELS_RE = re.compile(
    r'^\{(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(?:,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*,?)?\}$')


def _family_of(name: str, typed: dict) -> str:
    """Map a histogram/summary component sample back to its family
    (``x_bucket``/``x_sum``/``x_count`` -> ``x`` when ``x`` is typed
    histogram or summary)."""
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if typed.get(base) in ("histogram", "summary"):
                return base
    return name


def _validate_exposition_impl(text: str) -> List[Tuple[int, str]]:
    """Strict Prometheus text-exposition validation; returns
    ``(lineno, message)`` pairs.  Family-level findings (missing
    ``+Inf`` bucket / ``_sum`` / ``_count``) carry the family's
    ``# TYPE`` line — provenance the bare-name messages used to lack.

    Enforced: every non-comment line is a well-formed sample
    (``name{labels} value [timestamp]``); metric names match the
    Prometheus charset; every sample's family carries ``# HELP`` and
    ``# TYPE`` lines that PRECEDE its samples; at most one HELP/TYPE
    per family; TYPE values are real Prometheus types; no duplicate
    series (same name + label set); histogram families expose
    ``_bucket``/``_sum``/``_count`` with a ``+Inf`` bucket."""
    errors: List[Tuple[int, str]] = []
    helped: dict = {}
    typed: dict = {}
    type_line: dict = {}
    sampled_families = set()
    seen_series: dict = {}
    bucket_infs: dict = {}

    for lineno, line in enumerate(text.splitlines(), 1):
        def err(msg):
            errors.append((lineno, f"{msg} -- {line[:80]!r}"))

        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            kind = parts[1] if len(parts) > 1 else ""
            if kind not in ("HELP", "TYPE"):
                continue  # free-form comment: allowed
            if len(parts) < 3:
                err(f"{kind} line without a metric name")
                continue
            name = parts[2]
            if not PROM_NAME_RE.match(name):
                err(f"bad metric name {name!r} in {kind} line")
                continue
            book = helped if kind == "HELP" else typed
            if name in book:
                err(f"duplicate # {kind} for {name}")
            if kind == "HELP":
                if len(parts) < 4 or not parts[3].strip():
                    err(f"HELP for {name} has empty docstring")
                helped.setdefault(name, lineno)
            else:
                t = parts[3].strip() if len(parts) > 3 else ""
                if t not in PROM_TYPES:
                    err(f"TYPE for {name} is {t!r}, not one of "
                        f"{sorted(PROM_TYPES)}")
                typed.setdefault(name, t)
                type_line.setdefault(name, lineno)
                if name in sampled_families:
                    err(f"# TYPE for {name} appears after its samples")
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            err("malformed sample line (want 'name{labels} value "
                "[timestamp]', single spaces)")
            continue
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        if labels and not _LABELS_RE.match(labels):
            err(f"malformed label set {labels!r}")
        try:
            float(value.replace("Inf", "inf").replace("NaN", "nan"))
        except ValueError:
            err(f"unparseable sample value {value!r}")
        series = (name, labels)
        if series in seen_series:
            err(f"duplicate series {name}{labels} (first at line "
                f"{seen_series[series]})")
        else:
            seen_series[series] = lineno
        fam = _family_of(name, typed)
        sampled_families.add(fam)
        if fam not in typed:
            err(f"sample for {name} with no preceding # TYPE {fam}")
        elif fam not in helped:
            err(f"sample for {name} with no # HELP {fam}")
        if typed.get(fam) == "histogram" and name == fam + "_bucket":
            if 'le="+Inf"' in labels:
                bucket_infs[fam] = True
            bucket_infs.setdefault(fam, False)

    for fam, has_inf in sorted(bucket_infs.items()):
        if not has_inf:
            errors.append((type_line.get(fam, 0),
                           f"histogram {fam} has no le=\"+Inf\" bucket"))
    for fam in sorted(f for f, t in typed.items() if t == "histogram"):
        if fam in sampled_families:
            for part in ("_sum", "_count"):
                if (fam + part, "") not in seen_series:
                    errors.append((type_line.get(fam, 0),
                                   f"histogram {fam} is missing "
                                   f"{fam}{part}"))
    return errors


def validate_exposition(text: str) -> List[str]:
    """Historical string API: ``["line N: problem", ...]`` (empty =
    valid) — what tests and the old CLI consume."""
    return [f"line {ln}: {msg}"
            for ln, msg in _validate_exposition_impl(text)]


def validate_exposition_violations(text: str,
                                   path: str = "<prom>"
                                   ) -> List[Violation]:
    """The same findings in the shared graftcheck violation format,
    each carrying ``file:line`` provenance."""
    return [Violation("prom-format", path, ln, f"prom@{ln}", msg)
            for ln, msg in _validate_exposition_impl(text)]
