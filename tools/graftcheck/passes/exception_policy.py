"""Exception-policy pass: no silent ``except ...: pass``.

The graftcheck port of ``tools/check_no_bare_pass.py`` (which remains
as a thin CLI shim).  A handler whose body is a lone ``pass`` swallows
the failure invisibly — the exact shape that once hid every storage
error behind checkpoint.py's orbax fallback.  Handlers must log, bump
a monitor stat, or carry the historical explicit waiver comment
``# ok: <reason>`` on the except/pass line (kept for compatibility;
``# gc-ok: bare-except-pass <reason>`` works too).

Rule id: ``bare-except-pass``.
"""
from __future__ import annotations

import ast
from typing import List

from ..core import SourceFile, Violation, register_pass

WAIVER = "# ok:"


def _walk_scoped(node: ast.AST, qual: str = ""):
    """(qualname, ExceptHandler) pairs — keys stay line-stable by
    anchoring to the enclosing def/class path, per the baseline
    contract."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
            sub = f"{qual}.{child.name}" if qual else child.name
            yield from _walk_scoped(child, sub)
        else:
            if isinstance(child, ast.ExceptHandler):
                yield qual, child
            yield from _walk_scoped(child, qual)


@register_pass(
    "exception-policy", ("bare-except-pass",),
    doc="`except ...: pass` must log, count, or carry an explicit "
        "`# ok: <reason>` waiver")
def run(files: List[SourceFile]) -> List[Violation]:
    out: List[Violation] = []
    for sf in files:
        if sf.tree is None:
            continue
        for qual, node in _walk_scoped(sf.tree):
            if len(node.body) == 1 and isinstance(node.body[0], ast.Pass):
                waived = any(
                    WAIVER in sf.line_text(ln)
                    for ln in (node.lineno, node.body[0].lineno))
                if not waived:
                    out.append(Violation(
                        "bare-except-pass", sf.path, node.lineno,
                        f"{qual or '<module>'}:except",
                        "`except: pass` swallows the failure -- log "
                        "it, bump a monitor stat, or waive with "
                        "`# ok: <reason>`"))
    return out
