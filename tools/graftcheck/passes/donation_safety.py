"""Donation-safety checker for buffer-aliasing ops.

The decode ops whose output aliases an input variable
(``kv_cache_write`` / ``kv_cache_insert`` / ``kv_pool_write`` — the
mutated-persistable contract in ``ops/decode_ops.py``) make the
executor *donate* the input buffer to XLA: after the call, the
Python-side variable the caller passed in refers to a buffer XLA has
already overwritten (or freed).  The only safe patterns are

* rebinding in the same statement::

      cache_k = layers.kv_cache_write(cache_k, k, positions)

* never touching the donated name again.

Rule ``donation-use-after-alias`` flags any *later read* of the
donated first argument in the same function (statement order by line
— an approximation of control flow, which is exactly right for the
straight-line graph-builder code these ops live in).  A re-assignment
of the name re-arms it.
"""
from __future__ import annotations

import ast
from typing import Dict, List

from ..core import SourceFile, Violation, call_name, register_pass
from .resource_pairing import _functions, _own_nodes

# op name -> index of the donated positional argument / keyword name
ALIAS_OPS: Dict[str, tuple] = {
    "kv_cache_write": (0, "cache"),
    "kv_cache_insert": (0, "cache"),
    "kv_pool_write": (0, "pool"),
}


_op_name = call_name


@register_pass(
    "donation-safety", ("donation-use-after-alias",),
    doc="a variable donated to an output-aliasing op (kv_cache_write "
        "et al.) must be rebound or never read again")
def run(files: List[SourceFile]) -> List[Violation]:
    out: List[Violation] = []
    for sf in files:
        if sf.tree is None:
            continue
        if not any(op in sf.text for op in ALIAS_OPS):
            continue  # cheap prefilter: few files touch aliasing ops
        for qn, fn in _functions(sf):
            out += _check_fn(sf, qn, fn)
    return out


def _check_fn(sf: SourceFile, qn: str, fn: ast.AST) -> List[Violation]:
    out: List[Violation] = []
    # every Store to each name, by line (rebinding re-arms the name)
    stores: Dict[str, List[int]] = {}
    loads: Dict[str, List[int]] = {}
    donations: List[tuple] = []  # (name, call_line, op, rebound_same_stmt)

    assigns = [n for n in _own_nodes(fn)
               if isinstance(n, (ast.Assign, ast.AnnAssign))]

    def _target_names(a) -> set:
        """Every Name bound by an assignment, through tuple/starred
        nesting (`cache_k, cache_v = ...` rebinds both)."""
        targets = a.targets if isinstance(a, ast.Assign) else [a.target]
        names = set()
        for t in targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    names.add(sub.id)
        return names

    for n in _own_nodes(fn):
        if isinstance(n, ast.Name):
            book = stores if isinstance(n.ctx, (ast.Store, ast.Del)) \
                else loads
            book.setdefault(n.id, []).append(n.lineno)
        if isinstance(n, ast.Call):
            op = _op_name(n)
            if op not in ALIAS_OPS:
                continue
            idx, kw_name = ALIAS_OPS[op]
            donated = None
            if len(n.args) > idx:
                donated = n.args[idx]
            else:
                for kw in n.keywords:
                    if kw.arg == kw_name:
                        donated = kw.value
            if not isinstance(donated, ast.Name):
                continue
            rebound = any(
                (a.value is not None
                 and (a.value is n or _contains(a.value, n)))
                and donated.id in _target_names(a)
                for a in assigns)
            donations.append((donated.id, n.lineno, op, rebound))

    for name, call_line, op, rebound in donations:
        if rebound:
            continue
        # a Store strictly after the call re-arms the name; any Load
        # after the call and before the next Store is use-after-alias
        next_store = min((ln for ln in stores.get(name, [])
                          if ln > call_line), default=None)
        for use in sorted(loads.get(name, [])):
            if use <= call_line:
                continue
            if next_store is not None and use >= next_store:
                break
            out.append(Violation(
                "donation-use-after-alias", sf.path, use,
                f"{qn}:{name}",
                f"{name!r} was donated to {op}() at line {call_line}; "
                f"its buffer is aliased/dead — rebind "
                f"(`{name} = {op}({name}, ...)`) or use the op's "
                f"output variable"))
            break  # one finding per donation is enough signal
    return out


def _contains(root: ast.AST, target: ast.AST) -> bool:
    return any(sub is target for sub in ast.walk(root))
