"""Donation-safety checker for buffer-aliasing ops.

The decode ops whose output aliases an input variable
(``kv_cache_write`` / ``kv_cache_insert`` / ``kv_pool_write`` — the
mutated-persistable contract in ``ops/decode_ops.py``) make the
executor *donate* the input buffer to XLA: after the call, the
Python-side variable the caller passed in refers to a buffer XLA has
already overwritten (or freed).  The only safe patterns are

* rebinding in the same statement::

      cache_k = layers.kv_cache_write(cache_k, k, positions)

* never touching the donated name again.

Rule ``donation-use-after-alias`` flags any *later read* of the
donated first argument in the same function (statement order by line
— an approximation of control flow, which is exactly right for the
straight-line graph-builder code these ops live in).  A re-assignment
of the name re-arms it.

The same rule also tracks *donating callables*: a name or attribute
assigned from ``jax.jit(..., donate_argnums=(...))`` — the verify
program's aliased pool arg and the segment-adoption scatter
(``self._adopt_scatter``) live behind exactly this pattern — donates
the listed positional arguments at every later call through it, with
the same rebind-or-never-read contract::

    self._adopt_scatter = jax.jit(lambda pool, i, r: ...,
                                  donate_argnums=(0,))
    pool = self._adopt_scatter(pool, idx, rows)   # ok: rebound
    self._adopt_scatter(pool, idx, rows)          # pool is now dead

Index harvesting is conservative: every int constant inside the
``donate_argnums`` expression counts (so ``(1,) if flag else ()``
tracks index 1 — MAY-donate is the safe reading).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set

from ..core import SourceFile, Violation, call_name, register_pass
from .resource_pairing import _functions, _own_nodes, _recv_repr

# op name -> index of the donated positional argument / keyword name
ALIAS_OPS: Dict[str, tuple] = {
    "kv_cache_write": (0, "cache"),
    "kv_cache_insert": (0, "cache"),
    "kv_pool_write": (0, "pool"),
}


_op_name = call_name


def _jit_donated_indices(call: ast.Call) -> Set[int]:
    """For a ``jax.jit(...)`` / ``jit(...)`` call, the positional
    indices its ``donate_argnums`` may donate (empty when absent).
    Conservative: harvests every non-negative int constant in the
    keyword's expression, so conditional specs still track."""
    if _op_name(call) != "jit":
        return set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return {sub.value for sub in ast.walk(kw.value)
                    if isinstance(sub, ast.Constant)
                    and isinstance(sub.value, int)
                    and not isinstance(sub.value, bool)
                    and sub.value >= 0}
    return set()


def _donating_callables(sf: SourceFile) -> Dict[str, Set[int]]:
    """File-level map of canonical assignment target ('fn',
    'self._adopt_scatter', ...) -> donated positional indices, for
    every target assigned a jit-with-donation callable anywhere in
    the file (the build site and the call sites are often different
    methods of the same class)."""
    donors: Dict[str, Set[int]] = {}
    for n in ast.walk(sf.tree):
        if not isinstance(n, (ast.Assign, ast.AnnAssign)):
            continue
        value = n.value
        if not isinstance(value, ast.Call):
            continue
        idxs = _jit_donated_indices(value)
        if not idxs:
            continue
        targets = n.targets if isinstance(n, ast.Assign) else [n.target]
        for t in targets:
            if isinstance(t, (ast.Name, ast.Attribute)):
                donors.setdefault(_recv_repr(t), set()).update(idxs)
    return donors


@register_pass(
    "donation-safety", ("donation-use-after-alias",),
    doc="a variable donated to an output-aliasing op (kv_cache_write "
        "et al.) or through a jax.jit(donate_argnums=...) callable "
        "must be rebound or never read again")
def run(files: List[SourceFile]) -> List[Violation]:
    out: List[Violation] = []
    for sf in files:
        if sf.tree is None:
            continue
        if not (any(op in sf.text for op in ALIAS_OPS)
                or "donate_argnums" in sf.text):
            continue  # cheap prefilter: few files touch aliasing ops
        donors = _donating_callables(sf)
        for qn, fn in _functions(sf):
            out += _check_fn(sf, qn, fn, donors)
    return out


def _check_fn(sf: SourceFile, qn: str, fn: ast.AST,
              donors: Dict[str, Set[int]] = {}) -> List[Violation]:
    out: List[Violation] = []
    # every Store to each name, by line (rebinding re-arms the name)
    stores: Dict[str, List[int]] = {}
    loads: Dict[str, List[int]] = {}
    donations: List[tuple] = []  # (name, call_line, op, rebound_same_stmt)

    assigns = [n for n in _own_nodes(fn)
               if isinstance(n, (ast.Assign, ast.AnnAssign))]

    def _target_names(a) -> set:
        """Every Name bound by an assignment, through tuple/starred
        nesting (`cache_k, cache_v = ...` rebinds both)."""
        targets = a.targets if isinstance(a, ast.Assign) else [a.target]
        names = set()
        for t in targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    names.add(sub.id)
        return names

    for n in _own_nodes(fn):
        if isinstance(n, ast.Name):
            book = stores if isinstance(n.ctx, (ast.Store, ast.Del)) \
                else loads
            book.setdefault(n.id, []).append(n.lineno)
        if isinstance(n, ast.Call):
            # (donated arg node, label) pairs this call consumes:
            # aliasing-op first args plus every donate_argnums index
            # of a tracked jit callable
            consumed = []
            op = _op_name(n)
            if op in ALIAS_OPS:
                idx, kw_name = ALIAS_OPS[op]
                donated = None
                if len(n.args) > idx:
                    donated = n.args[idx]
                else:
                    for kw in n.keywords:
                        if kw.arg == kw_name:
                            donated = kw.value
                consumed.append((donated, op))
            elif isinstance(n.func, (ast.Name, ast.Attribute)):
                callee = _recv_repr(n.func)
                for idx in sorted(donors.get(callee, ())):
                    if len(n.args) > idx:
                        consumed.append((n.args[idx], callee))
            for donated, label in consumed:
                if not isinstance(donated, ast.Name):
                    continue
                rebound = any(
                    (a.value is not None
                     and (a.value is n or _contains(a.value, n)))
                    and donated.id in _target_names(a)
                    for a in assigns)
                # the call's END line: a multi-line call's own
                # argument loads must not read as use-after-donation
                call_end = getattr(n, "end_lineno", None) or n.lineno
                donations.append((donated.id, call_end, label, rebound))

    for name, call_line, op, rebound in donations:
        if rebound:
            continue
        # a Store strictly after the call re-arms the name; any Load
        # after the call and before the next Store is use-after-alias
        next_store = min((ln for ln in stores.get(name, [])
                          if ln > call_line), default=None)
        for use in sorted(loads.get(name, [])):
            if use <= call_line:
                continue
            if next_store is not None and use >= next_store:
                break
            out.append(Violation(
                "donation-use-after-alias", sf.path, use,
                f"{qn}:{name}",
                f"{name!r} was donated to {op}() at line {call_line}; "
                f"its buffer is aliased/dead — rebind "
                f"(`{name} = {op}({name}, ...)`) or use the op's "
                f"output variable"))
            break  # one finding per donation is enough signal
    return out


def _contains(root: ast.AST, target: ast.AST) -> bool:
    return any(sub is target for sub in ast.walk(root))
