#!/usr/bin/env python
"""Closed- and open-loop load generator for the serving engine.

Drives a :class:`paddle_tpu.serving.ServingEngine` **in process** (the
engine's submit() API is the contract) — or, with ``--url``, a live
serving HTTP endpoint over real sockets (``POST /predict``; overload
503s count as sheds, and the report embeds a ``/statusz`` snapshot
instead of in-process engine stats) — and emits one JSON report:

    {"mode": "closed", "requests": N, "ok": N, "shed": N, "failed": N,
     "wall_s": ..., "qps": ..., "latency_ms": {"p50":..,"p95":..,"p99":..},
     "shed_rate": ..., "engine": {<ServingEngine.stats()>}}

* **closed loop** (``--mode closed``): ``--concurrency`` callers, each
  submit→wait→repeat until ``--requests`` total — measures saturated
  throughput (the batcher sees a standing queue, batches run full).
* **open loop** (``--mode open``): requests arrive on a fixed ``--qps``
  clock regardless of completions — measures latency at a target rate
  and shed behavior past capacity (arrival rate does not slow down when
  the engine does, so overload actually overloads).
* ``--mode both`` runs closed then open and nests the two reports.

**Traffic shapes** (``--traffic const|sine|burst|step``, or a bare
``--shape sine``): the open-loop clock follows a diurnal ``sine``,
periodic ``burst``, or capacity-cliff ``step`` profile
(:class:`TrafficShape`; ``--traffic-amplitude`` / ``--traffic-period``
/ ``--traffic-burst-frac`` size it).  The report gains a ``phases``
block — per-phase requests / qps / p99 / shed — and the SLO
assertions below are evaluated in EVERY phase, so overload at the
crest fails the run even when the trough averages it away.

**SLO assertions** (ROADMAP item 5 — capacity regressions fail
loudly): ``--slo-p99-ms X`` and/or ``--slo-shed-pct Y`` make the run
load-bearing — the report gains an ``"slo"`` block listing every
violation (p99 latency above X ms, shed rate above Y percent, or zero
completed requests) and the process **exits 1** when any sub-report
violates.  In ``--mode both`` each sub-report is checked.

Model: ``--model-dir`` (a ``save_inference_model`` export; give per-row
feed shapes as ``--shape name=d0,d1``) or ``--synthetic`` (an in-process
MLP — no files needed; ``--hidden/--depth/--feat`` size it).

**Sharded mode** (``--sharded``): drives a mesh-partitioned
:class:`paddle_tpu.serving.ReplicaGroupEngine` (``--groups``/``--mp``/
``--ep`` or a ``--mesh "dp=4,mp=2"`` spec).  Every sub-report embeds a
``groups`` block — per replica group batch/failure tallies, fill,
predict-latency percentiles, mesh + device ids, and ``status`` (``ok |
degraded | missing_shards``) — and the SLO check **fails** when any
group reports non-``ok`` (with ``--url``, group health is read from
the live ``/statusz`` instead): a load test that passes while a
replica group is down has measured the wrong capacity.

**Generation mode** (``--generate``): drives a slot-based
:class:`paddle_tpu.serving.GenerationEngine` instead of the one-shot
engine.  Each request draws its prompt length uniformly from
``[--gen-prompt-min, --gen-prompt-max]`` and its output length from
``--gen-out-dist`` (**geometric**, or a chat-style 75/25 short/long
**bimodal** mix; mean ``--gen-out-mean``, clamped to
``[1, --gen-out-max]``) — the long-tail shape real generation traffic
has, and exactly the workload where continuous batching beats static
batch-drain scheduling.  Closed loop measures saturated
``tokens_per_sec``; open loop (``--mode open``) paces request arrivals
on the ``--qps`` clock for latency/shed behavior at a target rate.
``--gen-static`` schedules FIFO head-run (batch drain) instead of
continuous slot reclaim — the A/B the bench leg publishes.
``--gen-paged`` (with ``--gen-page-tokens``/``--gen-pages``/
``--gen-prefill-chunk``) swaps in the block-paged KV cache,
``--gen-speculate``/``--gen-spec-tokens`` turn on speculative
decoding (the report embeds the measured acceptance rate;
``--slo-accept-rate`` floors it — unmeasured is a violation), and
``--gen-prompt-dist shared-prefix --gen-prefix-tokens N`` makes every
prompt one fixed N-token header + a random tail — the chat workload
where the paged engine's prefix index skips the header's prefill.
With ``--url`` the same workload posts ``/generate`` against a live
replica or fleet router and the report embeds the target's
``/statusz`` generation block (prefix-hit rate included).

**Recsys mode** (``--recsys``): drives the Wide&Deep recommender path
— zipfian int64 ``sparse_ids`` (``--rec-slots/--rec-vocab/--rec-zipf``
shape the skew; ~1.2 is recommender-hot, 0 is uniform/cache-hostile)
plus dense features, served through the ep-sharded embedding tier
(:mod:`paddle_tpu.serving.embedding`) behind a fan-in-bucketed engine.
The report embeds the tier's LIVE hot-row cache hit rate (top-level
``hit_rate`` + the full ``embedding`` stats block; with ``--url`` it
reads the target's ``/statusz``), and ``--slo-hit-rate`` floors it —
an unmeasured floor is a violation, matching the acceptance-rate
precedent.

Used by ``bench.py run_serving``/``run_decode``/``run_paged_decode``/
``run_recsys`` (the ``legs.serving``, ``legs.llama_decode``,
``legs.llama_paged_decode`` and ``legs.wide_deep_recsys`` entries),
``tests/test_serving.py``, ``tests/test_generation.py``,
``tests/test_paged_generation.py``, and
``tests/test_recsys_serving.py``.
"""
from __future__ import annotations

import argparse
import json
import os
import queue as queue_mod
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# model builders
# ---------------------------------------------------------------------------

def build_synthetic(feat: int = 64, hidden: int = 256, depth: int = 2,
                    classes: int = 8, seed: int = 0):
    """In-process MLP predictor (no model dir needed): returns
    ``(predictor, per_row_shapes)``."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.inference import Predictor

    main, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    startup.random_seed = main.random_seed = seed
    with pt.program_guard(main, startup):
        x = layers.data("x", [feat])
        h = x
        for i in range(depth):
            h = layers.fc(h, hidden, act="relu", name=f"lg_fc{i}")
        out = layers.fc(h, classes, name="lg_head")
    scope = pt.Scope()
    pt.Executor().run(startup, scope=scope)
    return Predictor(main, ["x"], [out], scope=scope), {"x": (feat,)}


def feed_maker(shapes: Dict[str, tuple], rows: int = 1,
               seed: int = 0) -> Callable[[int], dict]:
    """Deterministic per-request feed factory (a pool of distinct
    pre-generated feeds, cycled by request index — host RNG off the
    timed path)."""
    rng = np.random.RandomState(seed)
    pool = []
    for _ in range(16):
        pool.append({n: rng.rand(rows, *s).astype("float32")
                     for n, s in shapes.items()})
    return lambda i: pool[i % len(pool)]


def zipf_ids(rng, vocab: int, size, s: float) -> np.ndarray:
    """Bounded zipfian id sampler: ids 0..vocab-1 with
    P(rank k) ∝ 1/(k+1)^s via inverse-CDF — unlike np.random.zipf
    this is bounded to the vocab (no rejection loop), works for any
    s >= 0 (s=0 = uniform), and is deterministic under the seeded
    ``rng``.  The skew knob is what makes the hot-row cache testable:
    s≈1.2 concentrates most probability mass in a few hundred ids
    (recommender reality), s≈0 spreads it flat (cache-hostile)."""
    w = 1.0 / np.power(np.arange(1, vocab + 1, dtype=np.float64), s)
    cdf = np.cumsum(w)
    cdf /= cdf[-1]
    return np.searchsorted(cdf,
                           rng.random_sample(size)).astype(np.int64)


TENANT_HEADER = "X-PaddleTPU-Tenant"


def tenant_picker(n: int, dist: str = "zipf", seed: int = 0,
                  pool: int = 4096) -> Callable[[int], str]:
    """Deterministic request-index -> tenant-name assignment for
    multi-tenant runs (``--tenants N``): ``zipf`` concentrates most of
    the traffic on ``tenant-00`` (the noisy-neighbor shape the usage
    observatory exists to attribute), ``uniform`` spreads it evenly.
    Pre-sampled pool, cycled by request index — host RNG off the
    timed path, same run same assignment."""
    rng = np.random.RandomState(seed)
    if dist == "uniform":
        ids = rng.randint(0, n, size=pool)
    else:
        ids = zipf_ids(rng, n, pool, 1.2)
    names = [f"tenant-{i:02d}" for i in range(n)]
    return lambda i: names[int(ids[i % pool])]


def recsys_feed_maker(slots: int, dense: int, vocab: int,
                      zipf: float = 1.2, rows: int = 1, seed: int = 0,
                      pool_size: int = 64) -> Callable[[int], dict]:
    """Per-request recsys feed factory: zipfian int64 ``sparse_ids``
    (``[rows, slots]``) + uniform float32 ``dense_x`` (``[rows,
    dense]``), pre-generated and cycled like :func:`feed_maker`.  The
    pool is larger than the dense maker's (64 vs 16): the hit-rate
    measurement needs enough DISTINCT hot ids in flight that the cache
    is doing real work, not replaying 16 memoized feeds."""
    rng = np.random.RandomState(seed)
    pool = []
    for _ in range(pool_size):
        pool.append({
            "sparse_ids": zipf_ids(rng, vocab, (rows, slots), zipf),
            "dense_x": rng.rand(rows, dense).astype("float32")})
    return lambda i: pool[i % len(pool)]


# ---------------------------------------------------------------------------
# traffic shapes (open loop): diurnal / bursty offered-load profiles
# ---------------------------------------------------------------------------

TRAFFIC_SHAPES = ("const", "sine", "burst", "step")


class TrafficShape:
    """Time-varying offered load for the open loop.

    Real traffic is not a constant-qps clock: it swells and ebbs
    (diurnal), spikes (retry storms, cache stampedes), and steps
    (a feature launch).  ``rate(t)`` gives the instantaneous target
    qps at ``t`` seconds into the run and ``phase(t)`` labels the
    regime, so the report can show qps/p99/shed PER PHASE — overload
    behavior at the crest is visible instead of averaged away by the
    trough.

    * ``const`` — ``base`` throughout (phase ``steady``; the legacy
      behavior).
    * ``sine`` — ``base * (1 + A*sin(2πt/period))``: a compressed
      diurnal curve (phases ``crest`` / ``trough``); default period =
      the whole run (one cycle).
    * ``burst`` — ``base`` with ``base*(1+A)`` bursts for the first
      ``burst_frac`` of every period (phases ``burst`` / ``base``);
      default period = duration/4 (four bursts).
    * ``step`` — ``base`` for the first half, ``base*(1+A)`` after
      (phases ``low`` / ``high``): a capacity cliff.

    ``amplitude`` is relative: 1.0 doubles the rate at the peak."""

    def __init__(self, shape: str, base_qps: float, duration_s: float,
                 amplitude: float = 1.0,
                 period_s: Optional[float] = None,
                 burst_frac: float = 0.25):
        if shape not in TRAFFIC_SHAPES:
            raise ValueError(f"unknown traffic shape {shape!r}; "
                             f"one of {TRAFFIC_SHAPES}")
        self.shape = shape
        self.base = float(base_qps)
        self.duration = float(duration_s)
        self.amplitude = float(amplitude)
        if period_s is None:
            period_s = duration_s if shape == "sine" \
                else max(duration_s / 4.0, 1e-3)
        self.period = float(period_s)
        self.burst_frac = float(burst_frac)

    def rate(self, t: float) -> float:
        b, a = self.base, self.amplitude
        if self.shape == "sine":
            import math
            r = b * (1.0 + a * math.sin(2.0 * math.pi * t / self.period))
            return max(r, 0.05 * b)  # the trough still offers load
        if self.shape == "burst":
            return b * (1.0 + a) if (t % self.period) \
                < self.burst_frac * self.period else b
        if self.shape == "step":
            return b * (1.0 + a) if t >= self.duration / 2.0 else b
        return b

    def phase(self, t: float) -> str:
        if self.shape == "sine":
            import math
            return "crest" if math.sin(
                2.0 * math.pi * t / self.period) >= 0.0 else "trough"
        if self.shape == "burst":
            return "burst" if (t % self.period) \
                < self.burst_frac * self.period else "base"
        if self.shape == "step":
            return "high" if t >= self.duration / 2.0 else "low"
        return "steady"

    def describe(self) -> dict:
        return {"shape": self.shape, "base_qps": self.base,
                "amplitude": self.amplitude,
                "period_s": round(self.period, 3),
                "burst_frac": self.burst_frac
                if self.shape == "burst" else None}


def _arrival_clock(qps: float, duration_s: float,
                   traffic: Optional[TrafficShape] = None):
    """Paced arrival generator: yields ``(i, phase, now)`` at each
    arrival instant.  With ``traffic`` the inter-arrival gap follows
    the shape's instantaneous rate; without, a fixed ``1/qps`` clock
    (byte-identical to the legacy pacing)."""
    t0 = time.monotonic()
    end = t0 + duration_s
    next_at = t0
    n = 0
    while True:
        now = time.monotonic()
        if now >= end:
            return
        if now < next_at:
            time.sleep(min(next_at - now, 0.01))
            continue
        rel = next_at - t0
        rate = traffic.rate(rel) if traffic is not None else qps
        phase = traffic.phase(rel) if traffic is not None else None
        next_at += 1.0 / max(rate, 1e-6)
        yield n, phase, now
        n += 1


class _PhaseBook:
    """Per-phase tallies for a shaped open-loop run.

    Phase time is ACTIVE time — the sum of inter-arrival gaps spent
    inside each contiguous visit to the phase — not last-arrival minus
    first-arrival.  A periodic shape (`burst`, `sine`, multi-cycle
    `step`) re-enters a phase many times across the run; first-to-last
    would span every interval spent in the OTHER phases and dilute the
    reported qps/offered_qps by the duty cycle."""

    def __init__(self):
        self.phases: Dict[str, dict] = {}
        self._cur_phase: Optional[str] = None
        self._last_ts: Optional[float] = None

    def _get(self, phase: str) -> dict:
        ph = self.phases.get(phase)
        if ph is None:
            ph = self.phases[phase] = {
                "requests": 0, "ok": 0, "shed": 0, "failed": 0,
                "lat": [], "active_s": 0.0, "versions": {}}
        return ph

    def arrival(self, phase: str, now: float):
        ph = self._get(phase)
        ph["requests"] += 1
        if self._cur_phase == phase and self._last_ts is not None:
            ph["active_s"] += now - self._last_ts
        self._cur_phase = phase
        self._last_ts = now

    def outcome(self, phase: str, outcome: str,
                ms: Optional[float] = None,
                version: Optional[int] = None):
        ph = self._get(phase)
        ph[outcome] += 1
        if ms is not None:
            ph["lat"].append(ms)
        if outcome == "ok" and version is not None:
            # per-phase weights_version distribution: a hot swap
            # mid-run shows up as the old version draining out of one
            # phase and the new one taking over the next
            ph["versions"][str(version)] = \
                ph["versions"].get(str(version), 0) + 1

    def report(self) -> Dict[str, dict]:
        out = {}
        for name, ph in self.phases.items():
            wall = max(ph["active_s"], 1e-3)
            out[name] = {
                "requests": ph["requests"], "ok": ph["ok"],
                "shed": ph["shed"], "failed": ph["failed"],
                "qps": round(ph["ok"] / wall, 2),
                "offered_qps": round(ph["requests"] / wall, 2),
                "shed_rate": round(ph["shed"] / max(ph["requests"], 1),
                                   4),
                "latency_ms": _percentiles(ph["lat"]),
            }
            if ph["versions"]:
                out[name]["weights_versions"] = dict(ph["versions"])
        return out


# ---------------------------------------------------------------------------
# loops
# ---------------------------------------------------------------------------

def _percentiles(lat_ms: List[float]) -> dict:
    if not lat_ms:
        return {"count": 0}
    a = np.asarray(lat_ms)
    return {"count": len(lat_ms),
            "mean": round(float(a.mean()), 3),
            "p50": round(float(np.percentile(a, 50)), 3),
            "p95": round(float(np.percentile(a, 95)), 3),
            "p99": round(float(np.percentile(a, 99)), 3),
            "max": round(float(a.max()), 3)}


def _report(mode: str, n: int, ok: int, shed: int, failed: int,
            wall_s: float, lat_ms: List[float], engine) -> dict:
    return {"mode": mode, "requests": n, "ok": ok, "shed": shed,
            "failed": failed, "wall_s": round(wall_s, 4),
            "qps": round(ok / wall_s, 2) if wall_s > 0 else 0.0,
            "offered_qps": round(n / wall_s, 2) if wall_s > 0 else 0.0,
            "shed_rate": round(shed / max(n, 1), 4),
            "latency_ms": _percentiles(lat_ms),
            "engine": engine.stats() if engine is not None else None}


def run_closed_loop(engine, make_feed, n_requests: int,
                    concurrency: int, timeout_s: float = 60.0,
                    tenant_of: Optional[Callable[[int], str]] = None
                    ) -> dict:
    """``concurrency`` synchronous callers sharing a ticket counter."""
    from paddle_tpu.serving import OverloadedError, ServingError

    tickets = iter(range(n_requests))
    ticket_lock = threading.Lock()
    lat, lock = [], threading.Lock()
    counts = {"ok": 0, "shed": 0, "failed": 0}

    def caller():
        while True:
            with ticket_lock:
                i = next(tickets, None)
            if i is None:
                return
            feed = make_feed(i)
            t0 = time.monotonic()
            try:
                if tenant_of is None:
                    engine.predict(feed, timeout=timeout_s)
                else:
                    engine.submit(feed, tenant=tenant_of(i)) \
                        .result(timeout_s)
                ms = (time.monotonic() - t0) * 1e3
                with lock:
                    counts["ok"] += 1
                    lat.append(ms)
            except OverloadedError:
                with lock:
                    counts["shed"] += 1
            except (ServingError, TimeoutError):
                with lock:
                    counts["failed"] += 1

    threads = [threading.Thread(target=caller, daemon=True)
               for _ in range(concurrency)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    rep = _report("closed", n_requests, counts["ok"], counts["shed"],
                  counts["failed"], wall, lat, engine)
    rep["concurrency"] = concurrency
    return rep


def run_open_loop(engine, make_feed, qps: float, duration_s: float,
                  timeout_s: float = 60.0, collectors: int = 8,
                  traffic: Optional[TrafficShape] = None,
                  tenant_of: Optional[Callable[[int], str]] = None
                  ) -> dict:
    """Fixed-rate arrivals: one pacing thread submits on a ``1/qps``
    clock; a collector pool stamps completions.  Sheds at submit() count
    against the offered load (that IS the overload behavior under
    test).  ``traffic`` (a :class:`TrafficShape`) replaces the fixed
    clock with a diurnal/bursty profile and adds per-phase qps/p99/shed
    to the report."""
    from paddle_tpu.serving import OverloadedError, ServingError

    lat, lock = [], threading.Lock()
    counts = {"ok": 0, "shed": 0, "failed": 0}
    phases = _PhaseBook() if traffic is not None else None
    pending: queue_mod.Queue = queue_mod.Queue()

    def collector():
        while True:
            item = pending.get()
            if item is None:
                return
            fut, t0, phase = item
            try:
                fut.result(timeout_s)
                ms = (time.monotonic() - t0) * 1e3
                with lock:
                    counts["ok"] += 1
                    lat.append(ms)
                    if phases is not None:
                        phases.outcome(phase, "ok", ms)
            except OverloadedError:
                with lock:
                    counts["shed"] += 1
                    if phases is not None:
                        phases.outcome(phase, "shed")
            except (ServingError, TimeoutError):
                with lock:
                    counts["failed"] += 1
                    if phases is not None:
                        phases.outcome(phase, "failed")

    pool = [threading.Thread(target=collector, daemon=True)
            for _ in range(collectors)]
    for t in pool:
        t.start()

    n = 0
    t0 = time.monotonic()
    for i, phase, now in _arrival_clock(qps, duration_s, traffic):
        n = i + 1
        if phases is not None:
            with lock:
                phases.arrival(phase, now)
        try:
            kw = {"tenant": tenant_of(i)} if tenant_of is not None \
                else {}
            fut = engine.submit(make_feed(i), **kw)
            pending.put((fut, now, phase))
        except OverloadedError:
            with lock:
                counts["shed"] += 1
                if phases is not None:
                    phases.outcome(phase, "shed")
    for _ in pool:
        pending.put(None)
    for t in pool:
        t.join()
    wall = time.monotonic() - t0
    rep = _report("open", n, counts["ok"], counts["shed"],
                  counts["failed"], wall, lat, engine)
    rep["target_qps"] = qps
    if traffic is not None:
        rep["traffic"] = traffic.describe()
        rep["phases"] = phases.report()
    return rep


# ---------------------------------------------------------------------------
# generation loops (--generate: drive a GenerationEngine's slot scheduler)
# ---------------------------------------------------------------------------

def prompt_maker(vocab_size: int, prompt_min: int, prompt_max: int,
                 out_mean: float, out_max: int, seed: int = 0,
                 pool: int = 64,
                 dist: str = "geometric",
                 prompt_dist: str = "uniform",
                 prefix_tokens: int = 0,
                 long_frac: float = 0.25,
                 long_tokens: int = 0) -> Callable[[int], tuple]:
    """Deterministic per-request ``(prompt_ids, max_new_tokens)``
    factory.  Prompt lengths are uniform in [prompt_min, prompt_max];
    output lengths draw from ``dist`` with mean ``out_mean`` clamped to
    [1, out_max] — most sequences finish fast, a tail runs long, which
    is the shape that makes batch-drain scheduling strand slots (host
    RNG off the timed path: a fixed pool cycled by request index).

    ``dist="geometric"``: memoryless tail; a full slot grid's expected
    longest draw is only ~2.7x the mean, so the batch-drain penalty it
    exposes is bounded.  ``dist="bimodal"``: 75% short (mean/8) / 25%
    long (~3.3x mean, same overall mean) — the chat-style mix where
    most turns are brief and a quarter run long, driving the grid's
    longest sequence to ~3.3x the mean (the harsher, more realistic
    test of slot reclaim).

    ``prompt_dist="shared-prefix"``: every prompt is one fixed
    ``prefix_tokens``-token header (drawn once — the system prompt /
    few-shot preamble of a chat product) followed by a random
    [prompt_min, prompt_max]-token tail — the workload where the paged
    engine's prefix index turns the header's prefill into a page-table
    hit.  ``"uniform"`` keeps fully random prompts.

    ``prompt_dist="mixed"``: the **bimodal long-prompt/short-chat**
    traffic shape disaggregated serving exists to fix — a
    ``long_frac`` fraction of prompts are LONG (uniform in
    ``[3*long_tokens//4, long_tokens]``; compute-bound prefill bursts
    that wreck colocated decode p99) and the rest are short chat
    turns (uniform in [prompt_min, prompt_max]).  ``long_tokens`` is
    required; tune ``long_frac`` to sweep the mix."""
    rng = np.random.RandomState(seed)
    reqs = []
    if dist == "bimodal":
        p_long = 0.25
        short = max(1.0, out_mean / 8.0)
        long_ = (out_mean - (1.0 - p_long) * short) / p_long
    elif dist != "geometric":
        raise ValueError(f"unknown output-length dist {dist!r}")
    header = None
    if prompt_dist == "shared-prefix":
        if prefix_tokens < 1:
            raise ValueError("shared-prefix prompts need "
                             "prefix_tokens >= 1")
        header = rng.randint(1, vocab_size,
                             size=prefix_tokens).astype("int64")
    elif prompt_dist == "mixed":
        if long_tokens < max(1, prompt_max):
            raise ValueError(f"mixed prompts need long_tokens > the "
                             f"short prompt_max ({prompt_max}), got "
                             f"{long_tokens}")
        if not 0.0 < long_frac < 1.0:
            raise ValueError(f"mixed prompts need 0 < long_frac < 1, "
                             f"got {long_frac}")
    elif prompt_dist != "uniform":
        raise ValueError(f"unknown prompt dist {prompt_dist!r}")
    for _ in range(pool):
        if prompt_dist == "mixed" \
                and rng.random_sample() < long_frac:
            plen = int(rng.randint(max(prompt_min,
                                       3 * long_tokens // 4),
                                   long_tokens + 1))
        else:
            plen = int(rng.randint(prompt_min, prompt_max + 1))
        prompt = rng.randint(1, vocab_size, size=plen).astype("int64")
        if header is not None:
            prompt = np.concatenate([header, prompt])
        if dist == "bimodal":
            mean = long_ if rng.random_sample() < p_long else short
        else:
            mean = out_mean
        out_len = int(np.clip(rng.geometric(1.0 / max(mean, 1.0)),
                              1, out_max))
        reqs.append((prompt, out_len))
    return lambda i: reqs[i % len(reqs)]


def _gen_report(mode: str, n: int, ok: int, shed: int, failed: int,
                wall_s: float, lat_ms: List[float], tokens: int,
                engine, ttft_ms: Optional[List[float]] = None,
                itl_ms: Optional[List[float]] = None) -> dict:
    rep = _report(mode, n, ok, shed, failed, wall_s, lat_ms, engine)
    rep["generated_tokens"] = tokens
    rep["tokens_per_sec"] = round(tokens / wall_s, 2) if wall_s > 0 \
        else 0.0
    spec = (rep.get("engine") or {}).get("speculate") \
        if isinstance(rep.get("engine"), dict) else None
    if isinstance(spec, dict):
        # measured acceptance rate at report level, same spot the HTTP
        # loop embeds it from /statusz — check_slo's accept_rate input
        rep["spec_acceptance_rate"] = spec.get("acceptance_rate")
    if ttft_ms is not None:
        # CLIENT-side time-to-first-token: submit (or POST) instant to
        # the first token's arrival at the caller — queue wait,
        # prefix mapping, and chunked-prefill interleave all included,
        # because the user waits through all of them
        rep["ttft_ms"] = _percentiles(ttft_ms)
    if itl_ms is not None:
        # client-side inter-token gaps, pooled across requests: the
        # p99 is "how long does a token ever stall", the decode-smooth
        # number the chunked-prefill knob trades against
        rep["inter_token_ms"] = _percentiles(itl_ms)
    return rep


class _TokenClock:
    """Per-request token-arrival recorder for the in-process loops:
    the engine's ``on_token`` hook stamps arrivals on the caller's
    clock; :meth:`fold` reduces them to a TTFT and inter-token gaps."""

    __slots__ = ("t0", "arrivals")

    def __init__(self, t0: float):
        self.t0 = t0
        self.arrivals: List[float] = []

    def on_token(self, tok, ts):
        self.arrivals.append(time.monotonic())

    def fold(self) -> tuple:
        """-> (ttft_ms or None, [gap_ms, ...])."""
        if not self.arrivals:
            return None, []
        ttft = (self.arrivals[0] - self.t0) * 1e3
        gaps = [(b - a) * 1e3
                for a, b in zip(self.arrivals, self.arrivals[1:])]
        return ttft, gaps


def run_closed_loop_generate(engine, make_prompt, n_requests: int,
                             concurrency: int,
                             timeout_s: float = 120.0,
                             tenant_of: Optional[
                                 Callable[[int], str]] = None) -> dict:
    """Closed loop against a GenerationEngine: ``concurrency``
    synchronous callers submit→wait→repeat; the slot grid sees a
    standing queue, so the measured ``tokens_per_sec`` is the
    scheduler's saturated decode throughput."""
    from paddle_tpu.serving import OverloadedError, ServingError

    tickets = iter(range(n_requests))
    ticket_lock = threading.Lock()
    lat, lock = [], threading.Lock()
    ttfts: List[float] = []
    itls: List[float] = []
    counts = {"ok": 0, "shed": 0, "failed": 0, "tokens": 0}

    def caller():
        while True:
            with ticket_lock:
                i = next(tickets, None)
            if i is None:
                return
            prompt, out_len = make_prompt(i)
            t0 = time.monotonic()
            clock = _TokenClock(t0)
            try:
                kw = {"tenant": tenant_of(i)} \
                    if tenant_of is not None else {}
                res = engine.submit(prompt, out_len,
                                    on_token=clock.on_token,
                                    **kw).result(timeout_s)
                ms = (time.monotonic() - t0) * 1e3
                ttft, gaps = clock.fold()
                with lock:
                    counts["ok"] += 1
                    counts["tokens"] += len(res["tokens"])
                    lat.append(ms)
                    if ttft is not None:
                        ttfts.append(ttft)
                    itls.extend(gaps)
            except OverloadedError:
                with lock:
                    counts["shed"] += 1
            except (ServingError, TimeoutError, ValueError):
                # ValueError = a rejected prompt (over-long / bad
                # dtype): counted as failed, NOT raised — a dead
                # caller thread would silently undercount the report
                with lock:
                    counts["failed"] += 1

    threads = [threading.Thread(target=caller, daemon=True)
               for _ in range(concurrency)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    rep = _gen_report("closed", n_requests, counts["ok"],
                      counts["shed"], counts["failed"], wall, lat,
                      counts["tokens"], engine, ttft_ms=ttfts,
                      itl_ms=itls)
    rep["concurrency"] = concurrency
    return rep


def run_open_loop_generate(engine, make_prompt, qps: float,
                           duration_s: float, timeout_s: float = 120.0,
                           collectors: int = 8,
                           tenant_of: Optional[
                               Callable[[int], str]] = None) -> dict:
    """Open loop against a GenerationEngine: request arrivals on a
    fixed ``1/qps`` clock regardless of completions (offered load does
    not back off when the grid saturates — submit-time sheds ARE the
    overload signal under test); a collector pool stamps
    completions."""
    from paddle_tpu.serving import OverloadedError, ServingError

    lat, lock = [], threading.Lock()
    ttfts: List[float] = []
    itls: List[float] = []
    counts = {"ok": 0, "shed": 0, "failed": 0, "tokens": 0}
    pending: queue_mod.Queue = queue_mod.Queue()

    def collector():
        while True:
            item = pending.get()
            if item is None:
                return
            fut, t0, clock = item
            try:
                res = fut.result(timeout_s)
                ms = (time.monotonic() - t0) * 1e3
                ttft, gaps = clock.fold()
                with lock:
                    counts["ok"] += 1
                    counts["tokens"] += len(res["tokens"])
                    lat.append(ms)
                    if ttft is not None:
                        ttfts.append(ttft)
                    itls.extend(gaps)
            except OverloadedError:
                with lock:
                    counts["shed"] += 1
            except (ServingError, TimeoutError):
                with lock:
                    counts["failed"] += 1

    pool = [threading.Thread(target=collector, daemon=True)
            for _ in range(collectors)]
    for t in pool:
        t.start()

    period = 1.0 / qps
    n = 0
    t0 = time.monotonic()
    end = t0 + duration_s
    next_at = t0
    while True:
        now = time.monotonic()
        if now >= end:
            break
        if now < next_at:
            time.sleep(min(next_at - now, 0.01))
            continue
        next_at += period
        prompt, out_len = make_prompt(n)
        kw = {"tenant": tenant_of(n)} if tenant_of is not None else {}
        n += 1
        clock = _TokenClock(now)
        try:
            fut = engine.submit(prompt, out_len,
                                on_token=clock.on_token, **kw)
            pending.put((fut, now, clock))
        except OverloadedError:
            with lock:
                counts["shed"] += 1
        except ValueError:
            # rejected prompt: failed, not a crash of the arrival loop
            with lock:
                counts["failed"] += 1
    for _ in pool:
        pending.put(None)
    for t in pool:
        t.join()
    wall = time.monotonic() - t0
    rep = _gen_report("open", n, counts["ok"], counts["shed"],
                      counts["failed"], wall, lat, counts["tokens"],
                      engine, ttft_ms=ttfts, itl_ms=itls)
    rep["target_qps"] = qps
    return rep


# ---------------------------------------------------------------------------
# HTTP loops (--url: drive a live ServingServer over real sockets)
# ---------------------------------------------------------------------------

def _encode_bodies(make_feed, n: int = 16) -> List[bytes]:
    """Pre-serialize the feed pool to JSON bodies (host JSON encoding
    off the timed path, mirroring feed_maker's pre-generated arrays)."""
    return [json.dumps({"inputs": {k: np.asarray(v).tolist()
                                   for k, v in make_feed(i).items()}}
                       ).encode() for i in range(n)]


def _http_predict(url: str, body: bytes,
                  timeout_s: float,
                  tenant: Optional[str] = None) -> tuple:
    """One POST /predict -> ``('ok' | 'shed' | 'failed', version)``
    where ``version`` is the ``X-PaddleTPU-Weights-Version`` response
    header (replicas and the router both publish it; ``None`` when
    the server predates it or the connection died) — the rollout
    bench watches the distribution flip during a hot swap.

    Not every 503 is a shed: a replica's admission 503s (queue_full /
    deadline / draining) are explicit backpressure and count as shed,
    but the fleet router's ``no_ready_replicas`` 503 means ZERO
    routable replicas — total availability loss, the exact event the
    rolling-restart zero-non-shed-failure contract exists to catch —
    and must count as failed, never as an allowed shed."""
    headers = {"Content-Type": "application/json"}
    if tenant:
        headers[TENANT_HEADER] = tenant
    req = urllib.request.Request(url, data=body, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            r.read()
            v = r.headers.get("X-PaddleTPU-Weights-Version")
            return "ok", (int(v) if v else None)
    except urllib.error.HTTPError as e:
        try:
            payload = e.read()  # drain: keep-alive must not desync
        except OSError:
            payload = b""  # ok: error body gone with the connection
        if e.code != 503:
            return "failed", None
        try:
            reason = json.loads(payload).get("reason")
        except (ValueError, AttributeError):
            reason = None
        return ("failed" if reason == "no_ready_replicas"
                else "shed"), None
    except (OSError, TimeoutError, ValueError):
        return "failed", None


def _http_statusz(base_url: str, timeout_s: float = 10.0
                  ) -> Optional[dict]:
    try:
        with urllib.request.urlopen(base_url.rstrip("/") + "/statusz",
                                    timeout=timeout_s) as r:
            return json.loads(r.read())
    except (OSError, TimeoutError, ValueError):
        return None


def fetch_usagez(base_url: str, timeout_s: float = 10.0
                 ) -> Optional[dict]:
    """Pull the target's per-tenant ``/usagez`` breakdown (a replica
    endpoint).  A fleet router exposes no /usagez — fall back to the
    ``/fleetz`` per-tenant aggregate so a multi-tenant run through the
    router still embeds the fleet-level attribution (per-tenant
    latency summaries stay replica-only, so a tenant-p99 SLO bound
    against a router report violates as unmeasured, never passes
    vacuously).  Never raises."""
    base = base_url.rstrip("/")
    try:
        with urllib.request.urlopen(base + "/usagez",
                                    timeout=timeout_s) as r:
            return json.loads(r.read())
    except (OSError, TimeoutError, ValueError):
        pass  # ok: routers have no /usagez — the /fleetz fallback next
    try:
        with urllib.request.urlopen(base + "/fleetz",
                                    timeout=timeout_s) as r:
            doc = json.loads(r.read())
        agg = (doc.get("aggregate") or {}).get("tenants")
        if agg is not None:
            return {"fleet": True, "tenant_families": agg}
    except (OSError, TimeoutError, ValueError):
        pass  # ok: no usage endpoint at all — report embeds None and
        #     a tenant SLO bound then violates as unmeasured
    return None


def fetch_debugz(base_url: str, out_path: str,
                 timeout_s: float = 10.0) -> Optional[str]:
    """Pull the target's one-shot ``/debugz`` forensics bundle (statusz
    + tracez + metrics + blackbox ring in one doc) and save it to
    ``out_path``.  Called on SLO violation so the evidence of WHY the
    run failed is captured at the moment of failure, not re-derived
    later from a server that has since moved on.  Returns the saved
    path, or None when the target is unreachable or predates /debugz —
    never raises (the SLO verdict itself must not depend on this)."""
    try:
        with urllib.request.urlopen(base_url.rstrip("/") + "/debugz",
                                    timeout=timeout_s) as r:
            doc = json.loads(r.read())
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
        return out_path
    except (OSError, TimeoutError, ValueError):
        return None


def run_closed_loop_http(base_url: str, make_feed, n_requests: int,
                         concurrency: int,
                         timeout_s: float = 60.0,
                         tenant_of: Optional[
                             Callable[[int], str]] = None) -> dict:
    """Closed loop over HTTP: ``concurrency`` synchronous posters
    sharing a ticket counter against a live server."""
    url = base_url.rstrip("/") + "/predict"
    bodies = _encode_bodies(make_feed)
    tickets = iter(range(n_requests))
    ticket_lock = threading.Lock()
    lat, lock = [], threading.Lock()
    counts = {"ok": 0, "shed": 0, "failed": 0}

    def caller():
        while True:
            with ticket_lock:
                i = next(tickets, None)
            if i is None:
                return
            body = bodies[i % len(bodies)]
            t0 = time.monotonic()
            outcome, version = _http_predict(
                url, body, timeout_s,
                tenant=tenant_of(i) if tenant_of else None)
            ms = (time.monotonic() - t0) * 1e3
            with lock:
                counts[outcome] += 1
                if outcome == "ok":
                    lat.append(ms)
                    if version is not None:
                        versions[str(version)] = \
                            versions.get(str(version), 0) + 1

    versions: Dict[str, int] = {}
    threads = [threading.Thread(target=caller, daemon=True)
               for _ in range(concurrency)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    rep = _report("closed", n_requests, counts["ok"], counts["shed"],
                  counts["failed"], wall, lat, None)
    rep["concurrency"] = concurrency
    rep["url"] = base_url
    rep["statusz"] = _http_statusz(base_url)
    if versions:
        rep["weights_versions"] = versions
    return rep


def _http_generate(url: str, body: bytes, timeout_s: float,
                   tenant: Optional[str] = None) -> tuple:
    """One POST /generate -> ('ok'|'shed'|'failed', generated token
    count).  Same 503 taxonomy as :func:`_http_predict`."""
    headers = {"Content-Type": "application/json"}
    if tenant:
        headers[TENANT_HEADER] = tenant
    req = urllib.request.Request(url, data=body, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            doc = json.loads(r.read())
            return "ok", len(doc.get("tokens") or [])
    except urllib.error.HTTPError as e:
        try:
            payload = e.read()
        except OSError:
            payload = b""  # ok: error body gone with the connection
        if e.code != 503:
            return "failed", 0
        try:
            reason = json.loads(payload).get("reason")
        except (ValueError, AttributeError):
            reason = None
        return (("failed", 0) if reason == "no_ready_replicas"
                else ("shed", 0))
    except (OSError, TimeoutError, ValueError):
        return "failed", 0


def _http_generate_stream(url: str, body: bytes, timeout_s: float,
                          tenant: Optional[str] = None) -> tuple:
    """One streaming POST /generate: read the NDJSON line-by-line,
    stamping each token line's ARRIVAL on this client's clock — the
    honest TTFT/ITL measurement (a whole-response timer cannot see
    token pacing at all).  -> (outcome, token_count, ttft_ms or None,
    [inter-token gap ms, ...])."""
    headers = {"Content-Type": "application/json"}
    if tenant:
        headers[TENANT_HEADER] = tenant
    req = urllib.request.Request(url, data=body, headers=headers)
    t0 = time.monotonic()
    arrivals: List[float] = []
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            final = None
            for raw in r:
                now = time.monotonic()
                line = raw.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    return "failed", 0, None, []
                if doc.get("done"):
                    final = doc
                    break
                if "token" in doc:
                    arrivals.append(now)
            if final is None:
                # token count 0 like the non-stream path: a broken
                # stream's partial tokens must not inflate the report's
                # tokens_per_sec vs the identical non-stream run
                return "failed", 0, None, []
            if "error" in final:
                # the stream's final line carries what the non-stream
                # path says with an HTTP status: overloaded = explicit
                # backpressure = shed, anything else failed
                return (("shed" if final.get("error") == "overloaded"
                         else "failed"), 0, None, [])
    except urllib.error.HTTPError as e:
        try:
            payload = e.read()
        except OSError:
            payload = b""  # ok: error body gone with the connection
        if e.code != 503:
            return "failed", 0, None, []
        try:
            reason = json.loads(payload).get("reason")
        except (ValueError, AttributeError):
            reason = None
        return ("failed" if reason == "no_ready_replicas" else "shed",
                0, None, [])
    except (OSError, TimeoutError, ValueError):
        return "failed", 0, None, []
    ttft = (arrivals[0] - t0) * 1e3 if arrivals else None
    gaps = [(b_ - a_) * 1e3 for a_, b_ in zip(arrivals, arrivals[1:])]
    return "ok", len(arrivals), ttft, gaps


def run_closed_loop_generate_http(base_url: str, make_prompt,
                                  n_requests: int, concurrency: int,
                                  timeout_s: float = 120.0,
                                  stream: bool = False,
                                  tenant_of: Optional[
                                      Callable[[int], str]] = None
                                  ) -> dict:
    """Closed loop of ``POST /generate`` against a live server or
    fleet router: the shared-prefix workload drivable end-to-end.  The
    report embeds the target's ``/statusz`` generation block —
    including the paged cache's prefix-hit rate — so the prefix-reuse
    win is observable from the outside.  ``stream=True`` switches to
    the NDJSON streaming contract and measures per-token arrivals
    client-side (the report gains ``ttft_ms``/``inter_token_ms``
    percentile blocks)."""
    url = base_url.rstrip("/") + "/generate"
    tickets = iter(range(n_requests))
    ticket_lock = threading.Lock()
    lat, lock = [], threading.Lock()
    ttfts: List[float] = []
    itls: List[float] = []
    counts = {"ok": 0, "shed": 0, "failed": 0, "tokens": 0}

    def caller():
        while True:
            with ticket_lock:
                i = next(tickets, None)
            if i is None:
                return
            prompt, out_len = make_prompt(i)
            doc = {"prompt": np.asarray(prompt).tolist(),
                   "max_new_tokens": int(out_len)}
            if stream:
                doc["stream"] = True
            body = json.dumps(doc).encode()
            tenant = tenant_of(i) if tenant_of else None
            t0 = time.monotonic()
            if stream:
                outcome, tokens, ttft, gaps = _http_generate_stream(
                    url, body, timeout_s, tenant=tenant)
            else:
                outcome, tokens = _http_generate(url, body, timeout_s,
                                                 tenant=tenant)
                ttft, gaps = None, []
            ms = (time.monotonic() - t0) * 1e3
            with lock:
                counts[outcome] += 1
                counts["tokens"] += tokens
                if outcome == "ok":
                    lat.append(ms)
                    if ttft is not None:
                        ttfts.append(ttft)
                    itls.extend(gaps)

    threads = [threading.Thread(target=caller, daemon=True)
               for _ in range(concurrency)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    rep = _gen_report("closed", n_requests, counts["ok"],
                      counts["shed"], counts["failed"], wall, lat,
                      counts["tokens"], None,
                      ttft_ms=ttfts if stream else None,
                      itl_ms=itls if stream else None)
    rep["concurrency"] = concurrency
    rep["url"] = base_url
    rep["stream"] = stream
    sz = _http_statusz(base_url)
    rep["statusz"] = sz
    gen_stats = None
    if isinstance(sz, dict):
        gen_stats = ((sz.get("engine") or {}).get("generator")
                     or {}).get("stats")
    if isinstance(gen_stats, dict):
        rep["generation"] = gen_stats
        paged = gen_stats.get("paged")
        if isinstance(paged, dict):
            rep["prefix_hit_rate"] = paged.get("prefix_hit_rate")
        spec = gen_stats.get("speculate")
        if isinstance(spec, dict):
            # live acceptance rate from /statusz, like prefix_hit_rate
            # — the measured-or-violation input to check_slo
            rep["spec_acceptance_rate"] = spec.get("acceptance_rate")
    return rep


def run_open_loop_http(base_url: str, make_feed, qps: float,
                       duration_s: float, timeout_s: float = 60.0,
                       collectors: int = 16,
                       traffic: Optional[TrafficShape] = None,
                       tenant_of: Optional[
                           Callable[[int], str]] = None) -> dict:
    """Open loop over HTTP: one pacing thread enqueues request bodies
    on a ``1/qps`` clock; a poster pool sends them.  Arrivals stay on
    the clock regardless of completions (the client-side queue absorbs
    a slow server, so offered load does not back off), though with
    every poster busy the in-flight concurrency caps at the pool
    size.  ``traffic`` shapes the clock (diurnal/bursty) and adds
    per-phase qps/p99/shed to the report."""
    url = base_url.rstrip("/") + "/predict"
    bodies = _encode_bodies(make_feed)
    lat, lock = [], threading.Lock()
    counts = {"ok": 0, "shed": 0, "failed": 0}
    versions: Dict[str, int] = {}
    phases = _PhaseBook() if traffic is not None else None
    pending: queue_mod.Queue = queue_mod.Queue()

    def poster():
        while True:
            item = pending.get()
            if item is None:
                return
            body, t0, phase, tenant = item
            outcome, version = _http_predict(url, body, timeout_s,
                                             tenant=tenant)
            ms = (time.monotonic() - t0) * 1e3
            with lock:
                counts[outcome] += 1
                if outcome == "ok":
                    lat.append(ms)
                    if version is not None:
                        versions[str(version)] = \
                            versions.get(str(version), 0) + 1
                if phases is not None:
                    phases.outcome(phase, outcome,
                                   ms if outcome == "ok" else None,
                                   version=version)

    pool = [threading.Thread(target=poster, daemon=True)
            for _ in range(collectors)]
    for t in pool:
        t.start()

    n = 0
    t0 = time.monotonic()
    for i, phase, now in _arrival_clock(qps, duration_s, traffic):
        n = i + 1
        if phases is not None:
            with lock:
                phases.arrival(phase, now)
        pending.put((bodies[i % len(bodies)], now, phase,
                     tenant_of(i) if tenant_of else None))
    for _ in pool:
        pending.put(None)
    for t in pool:
        t.join()
    wall = time.monotonic() - t0
    rep = _report("open", n, counts["ok"], counts["shed"],
                  counts["failed"], wall, lat, None)
    rep["target_qps"] = qps
    rep["url"] = base_url
    rep["statusz"] = _http_statusz(base_url)
    if versions:
        rep["weights_versions"] = versions
    if traffic is not None:
        rep["traffic"] = traffic.describe()
        rep["phases"] = phases.report()
    return rep


# ---------------------------------------------------------------------------
# SLO assertions
# ---------------------------------------------------------------------------

def check_slo(report: dict, p99_ms: Optional[float] = None,
              shed_pct: Optional[float] = None,
              fail_degraded: bool = False,
              ttft_ms: Optional[float] = None,
              itl_ms: Optional[float] = None,
              expect_version: Optional[int] = None,
              accept_rate: Optional[float] = None,
              hit_rate: Optional[float] = None,
              tenant_p99_ms: Optional[float] = None) -> dict:
    """Evaluate the SLO against one report (recursing into the nested
    closed/open halves of ``--mode both``).  Returns
    ``{"p99_ms_limit", "shed_pct_limit", "violations": [...], "ok"}``;
    a sub-report with zero completed requests is itself a violation
    (a fully-shed run must not pass on a vacuous p99).  With
    ``fail_degraded`` (the ``--sharded`` contract) any replica group
    reporting non-``ok`` status — ``degraded`` failure streak or
    ``missing_shards`` — in the report's ``groups`` block (or the
    embedded ``statusz.groups`` when driving a live server) is a
    violation: a load test that "passed" while a group was down
    measured the wrong capacity.  ``ttft_ms`` / ``itl_ms`` bound the
    generation report's client-measured p99 time-to-first-token and
    inter-token gap — a bound given against a report that never
    measured them (no per-token clock) is itself a violation, never a
    vacuous pass.  ``expect_version`` asserts that EVERY completed
    request carried that ``weights_version`` response header (the
    post-rollout check: a stale version answering means a replica was
    skipped or silently reverted); a report that never observed any
    version against the bound is again a violation, not a vacuous
    pass.  ``accept_rate`` floors the speculative-decoding acceptance
    rate the report embedded from the engine's live stats
    (``spec_acceptance_rate``); a bound given against a report that
    never measured it (speculation off, or a server without the
    stats block) is a violation — never a vacuous pass.  ``hit_rate``
    floors the hot-row cache hit rate a ``--recsys`` run embedded
    from the embedding tier's live stats (in-process engine stats, or
    the target's ``/statusz`` embedding block over HTTP); exactly the
    acceptance-rate precedent — an unmeasured bound is a violation."""
    violations = []

    def _versions(rep: dict, label: str):
        if expect_version is None:
            return
        dist = rep.get("weights_versions")
        if not dist:
            if not rep.get("ok"):
                return  # zero completions already violates via p99
            violations.append(
                f"{label}: --expect-version {expect_version} given "
                f"but no response carried a weights_version header "
                f"(server predates the rollout layer?)")
            return
        stale = {v: n for v, n in dist.items()
                 if v != str(expect_version)}
        if stale:
            violations.append(
                f"{label}: {sum(stale.values())} response(s) carried "
                f"weights_version {sorted(stale)} != expected "
                f"{expect_version}")

    def _one_phase(ph: dict, label: str):
        lat = ph.get("latency_ms") or {}
        if p99_ms is not None:
            p99 = lat.get("p99")
            if p99 is None:
                violations.append(f"{label}: no completed requests — "
                                  f"p99 unmeasurable")
            elif p99 > p99_ms:
                violations.append(f"{label}: p99 {p99}ms > SLO "
                                  f"{p99_ms}ms")
        if shed_pct is not None:
            rate = ph.get("shed_rate")
            if rate is not None and rate * 100.0 > shed_pct:
                violations.append(
                    f"{label}: shed rate {rate * 100.0:.2f}% > SLO "
                    f"{shed_pct}%")

    def _one(rep: dict, label: str):
        lat = rep.get("latency_ms") or {}
        if p99_ms is not None:
            p99 = lat.get("p99")
            if p99 is None:
                violations.append(f"{label}: no completed requests — "
                                  f"p99 unmeasurable")
            elif p99 > p99_ms:
                violations.append(f"{label}: p99 {p99}ms > SLO "
                                  f"{p99_ms}ms")
        if shed_pct is not None:
            rate = rep.get("shed_rate")
            if rate is not None and rate * 100.0 > shed_pct:
                violations.append(
                    f"{label}: shed rate {rate * 100.0:.2f}% > SLO "
                    f"{shed_pct}%")
        for bound, key, label_ in ((ttft_ms, "ttft_ms", "TTFT"),
                                   (itl_ms, "inter_token_ms",
                                    "inter-token")):
            if bound is None:
                continue
            blk = rep.get(key)
            p99 = (blk or {}).get("p99")
            if p99 is None:
                if "latency_ms" in rep:  # a leaf report, not "both"
                    violations.append(
                        f"{label}: no per-token measurements — "
                        f"{label_} p99 unmeasurable (run --generate "
                        f"with token timing / --gen-stream)")
            elif p99 > bound:
                violations.append(f"{label}: {label_} p99 {p99}ms > "
                                  f"SLO {bound}ms")
        if accept_rate is not None:
            rate = rep.get("spec_acceptance_rate")
            if rate is None:
                if "latency_ms" in rep:  # a leaf report, not "both"
                    violations.append(
                        f"{label}: --slo-accept-rate {accept_rate} "
                        f"given but no measured acceptance rate in "
                        f"the report (speculation off, or the server "
                        f"exposes no speculate stats block)")
            elif rate < accept_rate:
                violations.append(
                    f"{label}: spec acceptance rate {rate} < SLO "
                    f"floor {accept_rate}")
        if hit_rate is not None:
            rate = rep.get("hit_rate")
            if rate is None:
                if "latency_ms" in rep:  # a leaf report, not "both"
                    violations.append(
                        f"{label}: --slo-hit-rate {hit_rate} given "
                        f"but no measured hot-row hit rate in the "
                        f"report (not a --recsys run, or the server "
                        f"exposes no embedding stats block)")
            elif rate < hit_rate:
                violations.append(
                    f"{label}: hot-row hit rate {rate} < SLO floor "
                    f"{hit_rate}")
        _versions(rep, label)
        # shaped-traffic runs: the SLO binds in EVERY phase — a crest
        # that sheds half its load must not pass on the run's average
        for name, ph in (rep.get("phases") or {}).items():
            if not ph.get("requests"):
                continue  # a phase the clock never entered
            _one_phase(ph, f"{label}[{name}]")
        if fail_degraded:
            st = rep.get("statusz") or {}
            # in-process reports carry `groups` flat; a live /statusz
            # nests the engine block (statusz.engine.groups)
            groups = (rep.get("groups") or st.get("groups")
                      or (st.get("engine") or {}).get("groups") or [])
            for g in groups:
                status = g.get("status", "ok")
                if status != "ok":
                    violations.append(
                        f"{label}: replica group {g.get('worker')} "
                        f"(mesh {g.get('mesh')}, devices "
                        f"{g.get('devices')}) reports {status}")

    if report.get("mode") == "both":
        _one(report["closed"], "closed")
        _one(report["open"], "open")
    else:
        _one(report, report.get("mode", "report"))
    if tenant_p99_ms is not None:
        # the per-tenant latency SLO binds on the report's embedded
        # /usagez breakdown — one bound, EVERY tenant.  A tenant whose
        # latency was never measured (all sheds, a router-only fetch
        # with no replica histograms, usage disabled) is a violation,
        # never a vacuous pass: an SLO that skips unmeasured tenants
        # is exactly how a noisy neighbor's victims go unnoticed.
        tenants = (report.get("usage") or {}).get("tenants") or {}
        if not tenants:
            violations.append(
                f"usage: --slo-tenant-p99-ms {tenant_p99_ms} given "
                f"but the report embeds no per-tenant usage breakdown "
                f"(FLAGS_usage=0 target, router without replica "
                f"/usagez, or a run without --tenants)")
        for t, blk in sorted(tenants.items()):
            p99 = ((blk or {}).get("request_ms") or {}).get("p99")
            if p99 is None:
                violations.append(
                    f"usage[{t}]: no measured request p99 — tenant "
                    f"latency unmeasurable against SLO "
                    f"{tenant_p99_ms}ms")
            elif p99 > tenant_p99_ms:
                violations.append(
                    f"usage[{t}]: p99 {p99}ms > tenant SLO "
                    f"{tenant_p99_ms}ms")
    out = {"p99_ms_limit": p99_ms, "shed_pct_limit": shed_pct,
           "violations": violations, "ok": not violations}
    if ttft_ms is not None:
        out["ttft_ms_limit"] = ttft_ms
    if itl_ms is not None:
        out["itl_ms_limit"] = itl_ms
    if expect_version is not None:
        out["expect_version"] = expect_version
    if accept_rate is not None:
        out["accept_rate_limit"] = accept_rate
    if hit_rate is not None:
        out["hit_rate_limit"] = hit_rate
    if tenant_p99_ms is not None:
        out["tenant_p99_ms_limit"] = tenant_p99_ms
    if fail_degraded:
        out["fail_degraded"] = True
    return out


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _parse_shapes(specs: List[str]) -> Dict[str, tuple]:
    out = {}
    for spec in specs or []:
        name, _, dims = spec.partition("=")
        out[name] = tuple(int(d) for d in dims.split(",") if d)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group()
    src.add_argument("--model-dir", help="save_inference_model export")
    src.add_argument("--synthetic", action="store_true",
                     help="in-process MLP (default)")
    src.add_argument("--url", help="drive a live serving HTTP endpoint "
                                   "(http://host:port) instead of an "
                                   "in-process engine; feed shapes come "
                                   "from --shape (default: x=<feat>)")
    ap.add_argument("--shape", action="append", metavar="name=d0,d1",
                    help="per-row feed shape (required with --model-dir)")
    ap.add_argument("--feat", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--rows", type=int, default=1,
                    help="rows per request")
    ap.add_argument("--mode", choices=["closed", "open", "both"],
                    default="closed")
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--qps", type=float, default=200.0)
    ap.add_argument("--duration", type=float, default=2.0)
    ap.add_argument("--traffic", choices=TRAFFIC_SHAPES, default=None,
                    help="open-loop offered-load profile: const (fixed "
                         "clock), sine (diurnal), burst (periodic "
                         "spikes), step (capacity cliff); also "
                         "accepted as a bare --shape value.  The "
                         "report gains per-phase qps/p99/shed and the "
                         "SLO is asserted in every phase")
    ap.add_argument("--traffic-amplitude", type=float, default=1.0,
                    help="relative swing: 1.0 doubles the rate at the "
                         "peak")
    ap.add_argument("--traffic-period", type=float, default=None,
                    help="shape period in seconds (default: the whole "
                         "run for sine, duration/4 for burst)")
    ap.add_argument("--traffic-burst-frac", type=float, default=0.25,
                    help="fraction of each burst period spent at the "
                         "spiked rate")
    ap.add_argument("--workers", type=int, default=None)
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--max-delay-ms", type=float, default=None)
    ap.add_argument("--queue-cap", type=int, default=None)
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--sharded", action="store_true",
                    help="drive a mesh-partitioned ReplicaGroupEngine "
                         "(paddle_tpu/serving/sharded.py) instead of "
                         "the single-chip pool; --groups/--mp/--ep/"
                         "--mesh size the topology (default: fill the "
                         "device set with 1-device groups).  The "
                         "report embeds per-group health and the SLO "
                         "check FAILS when any replica group reports "
                         "degraded or missing shards — with --url, the "
                         "group health comes from the live /statusz")
    ap.add_argument("--groups", type=int, default=None,
                    help="dp replica-group count (sharded mode)")
    ap.add_argument("--mp", type=int, default=None,
                    help="model-parallel width per group (sharded)")
    ap.add_argument("--ep", type=int, default=None,
                    help="expert-parallel width per group (sharded)")
    ap.add_argument("--mesh", default=None, metavar="dp=4,mp=2",
                    help="serving-mesh spec (sharded mode; explicit "
                         "--groups/--mp/--ep win)")
    ap.add_argument("--recsys", action="store_true",
                    help="drive the Wide&Deep recsys path: zipfian "
                         "sparse_ids + dense_x feeds through the "
                         "ep-sharded embedding tier (in-process via "
                         "build_recsys_predictor, or POST the same "
                         "bodies at a --url target); the report "
                         "embeds the live hot-row hit rate "
                         "(--slo-hit-rate floors it)")
    ap.add_argument("--rec-slots", type=int, default=26,
                    help="sparse slots per example (Criteo: 26)")
    ap.add_argument("--rec-dense", type=int, default=13,
                    help="dense features per example (Criteo: 13)")
    ap.add_argument("--rec-vocab", type=int, default=100000,
                    help="embedding vocab (rows in the sharded table)")
    ap.add_argument("--rec-dim", type=int, default=8,
                    help="deep embedding dim (the wide column rides "
                         "fused in the same table)")
    ap.add_argument("--rec-zipf", type=float, default=1.2,
                    help="zipf skew of the sparse-id distribution: "
                         "~1.2 = recommender-hot (cache-friendly), "
                         "0 = uniform (cache-hostile)")
    ap.add_argument("--rec-hidden", default="64,32",
                    help="comma-separated deep MLP widths "
                         "(in-process --recsys)")
    ap.add_argument("--rec-shards", type=int, default=None,
                    help="embedding shard count (default "
                         "FLAGS_embedding_shards; 0 = one per device)")
    ap.add_argument("--rec-cache-rows", type=int, default=None,
                    help="hot-row cache capacity (default "
                         "FLAGS_embedding_cache_rows)")
    ap.add_argument("--generate", action="store_true",
                    help="drive a slot-based GenerationEngine "
                         "(autoregressive decode) instead of the "
                         "one-shot engine; --gen-* flags size it")
    ap.add_argument("--gen-vocab", type=int, default=128)
    ap.add_argument("--gen-hidden", type=int, default=64)
    ap.add_argument("--gen-layers", type=int, default=2)
    ap.add_argument("--gen-heads", type=int, default=4)
    ap.add_argument("--gen-kv-heads", type=int, default=None)
    ap.add_argument("--gen-intermediate", type=int, default=128)
    ap.add_argument("--gen-slots", type=int, default=4,
                    help="decode-slot grid size")
    ap.add_argument("--gen-max-seq", type=int, default=64,
                    help="per-slot KV-cache capacity")
    ap.add_argument("--gen-prompt-min", type=int, default=4)
    ap.add_argument("--gen-prompt-max", type=int, default=16)
    ap.add_argument("--gen-out-mean", type=float, default=8.0,
                    help="mean of the output-length distribution")
    ap.add_argument("--gen-out-max", type=int, default=32,
                    help="per-request output-length clamp")
    ap.add_argument("--gen-out-dist", choices=("geometric", "bimodal"),
                    default="geometric",
                    help="output-length distribution: memoryless "
                         "geometric, or a 75/25 short/long chat-style "
                         "mix at the same mean (heavier tail)")
    ap.add_argument("--gen-static", action="store_true",
                    help="FIFO head-run (batch drain) scheduling "
                         "instead of continuous slot reclaim")
    ap.add_argument("--gen-prompt-dist",
                    choices=("uniform", "shared-prefix", "mixed"),
                    default="uniform",
                    help="prompt shape: fully random; a fixed "
                         "--gen-prefix-tokens system-prompt header + "
                         "random tail (the chat workload where the "
                         "paged engine's prefix index skips the "
                         "header's prefill); or 'mixed' — the bimodal "
                         "long-prompt/short-chat blend (--gen-long-"
                         "frac long prompts of ~--gen-long-tokens, "
                         "the rest short chat turns) that "
                         "disaggregated prefill/decode exists to fix")
    ap.add_argument("--gen-prefix-tokens", type=int, default=32,
                    help="shared-prefix mode: tokens in the common "
                         "header every prompt starts with")
    ap.add_argument("--gen-long-frac", type=float, default=0.25,
                    help="mixed mode: fraction of prompts that are "
                         "long (tunable burst ratio)")
    ap.add_argument("--gen-long-tokens", type=int, default=0,
                    help="mixed mode: long-prompt length (drawn "
                         "uniform in [3/4*N, N]); default 0 = the "
                         "in-process engine's max prompt length, or "
                         "half of --gen-max-seq for a remote --url "
                         "target")
    ap.add_argument("--gen-paged", action="store_true",
                    help="block-paged KV cache (page pool + per-slot "
                         "block tables + prefix reuse) instead of the "
                         "dense per-slot reservation "
                         "(FLAGS_serving_paged for a live replica)")
    ap.add_argument("--gen-page-tokens", type=int, default=None,
                    help="paged: tokens per KV page (default "
                         "FLAGS_serving_kv_page_tokens)")
    ap.add_argument("--gen-pages", type=int, default=None,
                    help="paged: physical pages in the pool (default "
                         "auto-size to the dense capacity)")
    ap.add_argument("--gen-prefill-chunk", type=int, default=None,
                    help="paged: chunked-prefill slice size (0 = "
                         "whole-prompt prefill; default "
                         "FLAGS_serving_prefill_chunk)")
    ap.add_argument("--gen-speculate", action="store_true",
                    help="speculative decoding on the in-process "
                         "engine (n-gram self-drafts, one-chunk "
                         "verify, bit-exact acceptance; implies "
                         "--gen-paged) — the report embeds the "
                         "measured acceptance rate")
    ap.add_argument("--gen-spec-tokens", type=int, default=None,
                    help="speculative: max draft tokens per verify "
                         "(default FLAGS_serving_spec_tokens)")
    ap.add_argument("--out", help="also write the JSON report here")
    ap.add_argument("--slo-p99-ms", type=float, default=None,
                    help="assert p99 latency <= this (ms); violation "
                         "exits 1 with an 'slo' block in the report")
    ap.add_argument("--slo-shed-pct", type=float, default=None,
                    help="assert shed rate <= this (percent); "
                         "violation exits 1")
    ap.add_argument("--slo-ttft-ms", type=float, default=None,
                    help="assert client-measured p99 time-to-first-"
                         "token <= this (ms); needs a --generate run "
                         "with per-token timing (in-process loops "
                         "always have it; --url needs --gen-stream)")
    ap.add_argument("--slo-itl-ms", type=float, default=None,
                    help="assert client-measured p99 inter-token gap "
                         "<= this (ms); same measurement requirement "
                         "as --slo-ttft-ms")
    ap.add_argument("--gen-stream", action="store_true",
                    help="--url --generate: use the NDJSON streaming "
                         "/generate contract and record each token's "
                         "client-side arrival (enables ttft_ms / "
                         "inter_token_ms report blocks over HTTP)")
    ap.add_argument("--slo-accept-rate", type=float, default=None,
                    help="assert the speculative-decoding acceptance "
                         "rate >= this floor (0..1), read from the "
                         "report's embedded engine stats; a run with "
                         "no measured acceptance rate (speculation "
                         "off) violates too, never a vacuous pass")
    ap.add_argument("--slo-hit-rate", type=float, default=None,
                    help="assert the hot-row cache hit rate >= this "
                         "floor (0..1), read from the --recsys "
                         "report's embedded embedding stats (live "
                         "/statusz with --url); a run with no "
                         "measured hit rate violates too, never a "
                         "vacuous pass")
    ap.add_argument("--tenants", type=int, default=0,
                    help="multi-tenant run: assign each request one of "
                         "N tenant identities (tenant-00..) via the "
                         "X-PaddleTPU-Tenant header (--url) or the "
                         "submit(tenant=) kwarg (in-process); the "
                         "report embeds the target's /usagez per-"
                         "tenant breakdown")
    ap.add_argument("--tenant-dist", choices=("zipf", "uniform"),
                    default="zipf",
                    help="tenant traffic mix: zipf concentrates most "
                         "load on tenant-00 (noisy-neighbor shape), "
                         "uniform spreads it evenly")
    ap.add_argument("--slo-tenant-p99-ms", type=float, default=None,
                    help="assert EVERY tenant's p99 request latency "
                         "<= this (ms), read from the report's "
                         "embedded /usagez breakdown; a tenant with "
                         "no measured latency violates too, never a "
                         "vacuous pass")
    ap.add_argument("--expect-version", type=int, default=None,
                    help="assert every completed request carried this "
                         "weights_version response header (the post-"
                         "rollout convergence check); a run that never "
                         "observed the header violates too, never a "
                         "vacuous pass")
    args = ap.parse_args(argv)
    # `--shape sine` convenience: a bare traffic-shape name given via
    # --shape (which otherwise takes name=d0,d1 feed specs) selects
    # the traffic profile — the spelling the fleet runbooks use
    if args.shape:
        feeds = []
        for spec in args.shape:
            if spec in TRAFFIC_SHAPES and "=" not in spec:
                args.traffic = spec
            else:
                feeds.append(spec)
        args.shape = feeds
    traffic = None
    if args.traffic:
        traffic = TrafficShape(args.traffic, args.qps, args.duration,
                               amplitude=args.traffic_amplitude,
                               period_s=args.traffic_period,
                               burst_frac=args.traffic_burst_frac)
    tenant_of = tenant_picker(args.tenants, args.tenant_dist) \
        if args.tenants > 0 else None
    if args.sharded and args.generate:
        # the generate branch would silently drive a plain single-mesh
        # GenerationEngine while the report claimed a sharded health
        # check ran — refuse instead (GenerationEngine(mesh=...) is the
        # in-process API for mesh-partitioned generation)
        ap.error("--sharded cannot combine with --generate")
    if traffic is not None and args.traffic != "const":
        # shapes only exist on the one-shot open loop: running anyway
        # would print a report with no phases block while the operator
        # believes the crest was survived — refuse instead of
        # silently measuring a constant clock
        if args.generate:
            ap.error("--traffic shapes are not supported by the "
                     "--generate loops yet; drop --traffic or "
                     "--generate")
        if args.mode == "closed":
            ap.error("--traffic shapes apply to the open loop; use "
                     "--mode open or --mode both")

    def finish(report: dict) -> int:
        rc = 0
        if args.tenants or args.slo_tenant_p99_ms is not None:
            # embed the per-tenant attribution next to the latency
            # report — check_slo's tenant bound reads it, operators
            # diff it against the client-side mix
            if args.url:
                report["usage"] = fetch_usagez(args.url)
            else:
                try:
                    from paddle_tpu.serving import usage as usage_mod
                    led = usage_mod.peek_ledger()
                    report["usage"] = led.usagez() \
                        if led is not None else None
                except Exception:  # noqa: BLE001 — report must print
                    report["usage"] = None
        if args.slo_p99_ms is not None or args.slo_shed_pct is not None \
                or args.slo_ttft_ms is not None \
                or args.slo_itl_ms is not None or args.sharded \
                or args.expect_version is not None \
                or args.slo_accept_rate is not None \
                or args.slo_hit_rate is not None \
                or args.slo_tenant_p99_ms is not None:
            slo = check_slo(report, args.slo_p99_ms, args.slo_shed_pct,
                            fail_degraded=args.sharded,
                            ttft_ms=args.slo_ttft_ms,
                            itl_ms=args.slo_itl_ms,
                            expect_version=args.expect_version,
                            accept_rate=args.slo_accept_rate,
                            hit_rate=args.slo_hit_rate,
                            tenant_p99_ms=args.slo_tenant_p99_ms)
            report["slo"] = slo
            if not slo["ok"]:
                for v in slo["violations"]:
                    print(f"SLO VIOLATION: {v}", file=sys.stderr)
                rc = 1
                if args.url:
                    # grab the target's forensics bundle while the
                    # violating state is still live on the server
                    base = (os.path.splitext(args.out)[0]
                            if args.out else
                            os.path.join(tempfile.gettempdir(),
                                         f"loadgen-{os.getpid()}"))
                    path = fetch_debugz(args.url,
                                        base + ".debugz.json")
                    slo["debugz"] = path
                    if path:
                        print(f"SLO VIOLATION: /debugz bundle saved "
                              f"to {path}", file=sys.stderr)
        text = json.dumps(report)
        print(text)
        if args.out:
            with open(args.out, "w") as f:
                f.write(text + "\n")
        return rc

    if args.url and args.generate:
        # remote generation target (replica or fleet router): paced
        # POST /generate; prefix-hit rate rides in from /statusz
        if args.mode != "closed":
            ap.error("--url --generate supports --mode closed only")
        make_prompt = prompt_maker(
            args.gen_vocab, args.gen_prompt_min, args.gen_prompt_max,
            args.gen_out_mean, args.gen_out_max,
            dist=args.gen_out_dist, prompt_dist=args.gen_prompt_dist,
            prefix_tokens=args.gen_prefix_tokens
            if args.gen_prompt_dist == "shared-prefix" else 0,
            long_frac=args.gen_long_frac,
            # remote default: half the replica's cache capacity
            # (--gen-max-seq describes the target) — guaranteed under
            # its largest prefill bucket, unlike a prompt_max multiple
            long_tokens=args.gen_long_tokens
            or max(args.gen_prompt_max + 1, args.gen_max_seq // 2))
        report = run_closed_loop_generate_http(
            args.url, make_prompt, args.requests, args.concurrency,
            stream=args.gen_stream, tenant_of=tenant_of)
        return finish(report)

    if args.url:
        # remote target: no model, no engine — just paced HTTP traffic
        if args.recsys:
            make_feed = recsys_feed_maker(
                args.rec_slots, args.rec_dense, args.rec_vocab,
                zipf=args.rec_zipf, rows=args.rows)

            def _with_hit_rate(rep: dict) -> dict:
                # live hot-row hit rate off the target's /statusz
                # embedding block — the measurement --slo-hit-rate
                # floors (a router target exposes no embedding block;
                # the floor then violates, never passes vacuously)
                emb = ((rep.get("statusz") or {}).get("engine")
                       or {}).get("embedding") or {}
                if emb.get("hit_rate") is not None:
                    rep["hit_rate"] = emb["hit_rate"]
                    rep["embedding"] = emb
                return rep
        else:
            shapes = _parse_shapes(args.shape) or {"x": (args.feat,)}
            make_feed = feed_maker(shapes, rows=args.rows)

            def _with_hit_rate(rep: dict) -> dict:
                return rep
        if args.mode == "both":
            report = {"mode": "both",
                      "closed": _with_hit_rate(run_closed_loop_http(
                          args.url, make_feed, args.requests,
                          args.concurrency, tenant_of=tenant_of)),
                      "open": _with_hit_rate(run_open_loop_http(
                          args.url, make_feed, args.qps,
                          args.duration, traffic=traffic,
                          tenant_of=tenant_of))}
        elif args.mode == "closed":
            report = _with_hit_rate(run_closed_loop_http(
                args.url, make_feed, args.requests, args.concurrency,
                tenant_of=tenant_of))
        else:
            report = _with_hit_rate(run_open_loop_http(
                args.url, make_feed, args.qps, args.duration,
                traffic=traffic, tenant_of=tenant_of))
        return finish(report)

    if args.generate:
        from paddle_tpu.serving import GenerationEngine

        model = dict(vocab_size=args.gen_vocab, hidden=args.gen_hidden,
                     num_layers=args.gen_layers, num_heads=args.gen_heads,
                     num_kv_heads=args.gen_kv_heads,
                     intermediate=args.gen_intermediate)
        paged_kw = {}
        if args.gen_paged or args.gen_speculate:
            # speculation verifies against the slot's pages: it
            # implies the paged cache
            paged_kw = dict(paged=True,
                            page_tokens=args.gen_page_tokens,
                            num_pages=args.gen_pages,
                            prefill_chunk=args.gen_prefill_chunk)
        if args.gen_speculate:
            paged_kw.update(speculate=True,
                            spec_tokens=args.gen_spec_tokens)
        gen = GenerationEngine(
            model, num_slots=args.gen_slots, max_seq_len=args.gen_max_seq,
            max_new_tokens=args.gen_out_max,
            continuous=not args.gen_static,
            queue_cap=args.queue_cap or 4 * args.requests,
            deadline_ms=args.deadline_ms or 600000.0, **paged_kw)
        gen.warmup()
        shared = args.gen_prompt_dist == "shared-prefix"
        prefix = args.gen_prefix_tokens if shared else 0
        tail_max = min(args.gen_prompt_max,
                       max(1, gen.max_prompt_len - prefix))
        make_prompt = prompt_maker(args.gen_vocab, args.gen_prompt_min,
                                   tail_max,
                                   args.gen_out_mean, args.gen_out_max,
                                   dist=args.gen_out_dist,
                                   prompt_dist=args.gen_prompt_dist,
                                   prefix_tokens=prefix,
                                   long_frac=args.gen_long_frac,
                                   long_tokens=min(
                                       args.gen_long_tokens
                                       or gen.max_prompt_len,
                                       gen.max_prompt_len))
        try:
            if args.mode == "both":
                report = {"mode": "both",
                          "closed": run_closed_loop_generate(
                              gen, make_prompt, args.requests,
                              args.concurrency, tenant_of=tenant_of),
                          "open": run_open_loop_generate(
                              gen, make_prompt, args.qps,
                              args.duration, tenant_of=tenant_of)}
            elif args.mode == "closed":
                report = run_closed_loop_generate(gen, make_prompt,
                                                  args.requests,
                                                  args.concurrency,
                                                  tenant_of=tenant_of)
            else:
                report = run_open_loop_generate(gen, make_prompt,
                                                args.qps, args.duration,
                                                tenant_of=tenant_of)
        finally:
            gen.close()
        return finish(report)

    from paddle_tpu.serving import ServingEngine

    if args.recsys:
        # in-process recsys: the sharded embedding tier + dense
        # remainder behind a fan-in-bucketed engine — the same build
        # a --recsys replica process does
        from paddle_tpu.flags import flag_value
        from paddle_tpu.serving import batcher, build_recsys_predictor

        if args.sharded:
            ap.error("--recsys cannot combine with --sharded (the "
                     "embedding tier shards itself)")
        predictor, shapes = build_recsys_predictor(
            num_sparse=args.rec_slots, num_dense=args.rec_dense,
            vocab=args.rec_vocab, embed_dim=args.rec_dim,
            hidden=tuple(int(h) for h in args.rec_hidden.split(",")
                         if h),
            shards=args.rec_shards, cache_rows=args.rec_cache_rows)
        max_batch = args.max_batch or int(
            flag_value("FLAGS_serving_recsys_max_batch") or 64)
        engine = ServingEngine(
            predictor, workers=args.workers, max_delay_ms=args.max_delay_ms,
            queue_cap=args.queue_cap, deadline_ms=args.deadline_ms,
            warmup_shapes=shapes,
            buckets=batcher.fanin_bucket_sizes(max_batch)
            if flag_value("FLAGS_serving_recsys_fanin")
            else batcher.bucket_sizes(max_batch))
        make_feed = recsys_feed_maker(
            args.rec_slots, args.rec_dense, args.rec_vocab,
            zipf=args.rec_zipf, rows=args.rows)

        def _with_embedding(rep: dict) -> dict:
            # the tier's live stats: hit_rate top-level (the
            # --slo-hit-rate measurement) + the full block
            emb = predictor.embedding_stats()
            rep["hit_rate"] = emb["hit_rate"]
            rep["embedding"] = emb
            return rep

        try:
            if args.mode == "both":
                report = {"mode": "both",
                          "closed": _with_embedding(
                              run_closed_loop(engine, make_feed,
                                              args.requests,
                                              args.concurrency,
                                              tenant_of=tenant_of)),
                          "open": _with_embedding(
                              run_open_loop(engine, make_feed,
                                            args.qps, args.duration,
                                            traffic=traffic,
                                            tenant_of=tenant_of))}
            elif args.mode == "closed":
                report = _with_embedding(
                    run_closed_loop(engine, make_feed, args.requests,
                                    args.concurrency,
                                    tenant_of=tenant_of))
            else:
                report = _with_embedding(
                    run_open_loop(engine, make_feed, args.qps,
                                  args.duration, traffic=traffic,
                                  tenant_of=tenant_of))
        finally:
            engine.close()
        return finish(report)

    if args.model_dir:
        from paddle_tpu.inference import Predictor
        shapes = _parse_shapes(args.shape)
        if not shapes:
            ap.error("--model-dir needs at least one --shape name=dims")
        predictor = Predictor(args.model_dir)
    else:
        predictor, shapes = build_synthetic(args.feat, args.hidden,
                                            args.depth)
    engine_kw = dict(max_batch=args.max_batch,
                     max_delay_ms=args.max_delay_ms,
                     queue_cap=args.queue_cap,
                     deadline_ms=args.deadline_ms,
                     warmup_shapes=shapes)
    if args.sharded:
        from paddle_tpu.serving import ReplicaGroupEngine
        engine = ReplicaGroupEngine(predictor, groups=args.groups,
                                    mp=args.mp, ep=args.ep,
                                    mesh_spec=args.mesh, **engine_kw)
    else:
        engine = ServingEngine(predictor, workers=args.workers,
                               **engine_kw)
    make_feed = feed_maker(shapes, rows=args.rows)

    def _with_groups(rep: dict) -> dict:
        # --sharded report block: per-group health captured while the
        # engine is live (check_slo reads it for the degraded gate)
        if args.sharded:
            rep["groups"] = engine.worker_health()
            rep["replica_groups"] = engine.introspect()["replica_groups"]
        return rep

    try:
        if args.mode == "both":
            report = {"mode": "both",
                      "closed": _with_groups(
                          run_closed_loop(engine, make_feed,
                                          args.requests,
                                          args.concurrency,
                                          tenant_of=tenant_of)),
                      "open": _with_groups(
                          run_open_loop(engine, make_feed, args.qps,
                                        args.duration,
                                        traffic=traffic,
                                        tenant_of=tenant_of))}
        elif args.mode == "closed":
            report = _with_groups(
                run_closed_loop(engine, make_feed, args.requests,
                                args.concurrency,
                                tenant_of=tenant_of))
        else:
            report = _with_groups(
                run_open_loop(engine, make_feed, args.qps,
                              args.duration, traffic=traffic,
                              tenant_of=tenant_of))
    finally:
        engine.close()

    return finish(report)


if __name__ == "__main__":
    import sys
    sys.exit(main())
