#!/usr/bin/env python
"""Perf regression gate: fresh bench/op-bench reports vs the committed
trajectory, with noise-aware tolerances.

Makes the numbers load-bearing (ROADMAP item 5): a perf PR runs the
bench, then this gate compares the fresh report against the checked-in
``BENCH_r*.json`` baselines (and optionally an ``op_bench.py`` report
against ``tools/op_bench_baseline.json``) and **exits nonzero on
regression** — a capacity or step-time regression fails loudly instead
of shipping silently.

Noise model: the shared chip drifts ±10% between runs with
byte-identical programs (bench.py module docstring), and every bench
leg records its own window spread as ``stats.p10``/``stats.p90``.  The
per-leg tolerance is therefore::

    tol = max(--floor-tol,                     # cross-run chip drift
              (base.p90 - base.p10) / base.median,   # baseline's noise
              (new.p90  - new.p10)  / new.median)    # fresh run's noise

and a leg regresses when ``new.median < base.median * (1 - tol)``.
Legs are only compared on matching ``device_kind`` (a CPU smoke run
against a TPU baseline is a skip, not a pass or fail), and legs the
baseline flagged ``anomaly`` are skipped (a garbage baseline must not
gate anything).

Usage::

    python tools/perf_gate.py --report fresh.json --baseline BENCH_r05.json
        [--baseline BENCH_r04.json ...]     # trajectory: last match wins
        [--op-report ops.json [--op-baseline tools/op_bench_baseline.json]]
        [--floor-tol 0.10] [--op-threshold 1.5]
    python tools/perf_gate.py --smoke       # self-test on committed
        fixtures (no benchmark run) — wired into tier-1 via
        tests/test_lint.py
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FLOOR_TOL = 0.10        # cross-run chip drift floor (bench.py docstring)
OP_THRESHOLD = 1.5      # per-op regression ratio (check_op_bench.py)


def load_report(path: str) -> dict:
    """Load a bench JSON; unwrap the driver's capture envelope
    (``{"n", "cmd", "rc", "tail", "parsed": {...}}``) when present."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if "parsed" in doc and isinstance(doc["parsed"], dict) \
            and "value" in doc["parsed"]:
        return doc["parsed"]
    return doc


def extract_legs(doc: dict) -> Dict[str, dict]:
    """Flatten a bench report into ``{leg_name: leg_dict}``: the
    top-level flagship plus everything under ``legs``.  Legs that
    errored (``{"error": ...}``) or carry no ``value`` are dropped."""
    legs = {}
    if isinstance(doc.get("value"), (int, float)):
        legs["flagship"] = doc
    for name, leg in (doc.get("legs") or {}).items():
        if isinstance(leg, dict) and isinstance(leg.get("value"),
                                                (int, float)):
            legs[name] = leg
    return legs


def _noise(leg: dict) -> float:
    """Relative window spread from the leg's own recorded p10/p90
    (0 when the leg publishes no stats — e.g. the serving leg)."""
    st = leg.get("stats") or {}
    med = st.get("median") or 0.0
    p10, p90 = st.get("p10"), st.get("p90")
    if not med or p10 is None or p90 is None:
        return 0.0
    return max(float(p90) - float(p10), 0.0) / float(med)


def _median_of(leg: dict) -> float:
    st = leg.get("stats") or {}
    return float(st.get("median") or leg["value"])


def compare_leg(name: str, new: dict, base: dict,
                floor_tol: float) -> dict:
    """One leg's verdict: ``status`` in ``ok | regression | skipped``
    (+ the numbers behind it)."""
    res = {"leg": name}
    # sharded-serving correctness rule, checked before EVERY skip
    # (device-kind mismatch, anomalous baseline, anomalous fresh run):
    # mp=2 weight-sharded serving is bit-exact by construction, so a
    # False is a regression on any host — core contention or a garbage
    # baseline can hide throughput, never flip bytes
    if new.get("mp2_bit_exact") is False:
        res.update(status="regression",
                   reason="mp2 weight-sharded serving no longer "
                          "bit-exact vs the unsharded predictor")
        return res
    # router rollout-availability rule, also checked before every
    # skip: the rolling-restart contract is ZERO non-shed request
    # failures across the window — a failure is a correctness break
    # (drain or retry stopped working), which core contention can
    # slow down but never cause
    rollout = new.get("rollout")
    if isinstance(rollout, dict):
        failed = rollout.get("failed")
        if failed is None:
            # the window measured nothing (traffic thread died/hung):
            # a vacuous pass must not satisfy the zero-failure contract
            res.update(status="regression",
                       reason="rolling-restart window has no measured "
                              "failure count (traffic produced no "
                              "report)")
            return res
        if failed > 0:
            res.update(status="regression",
                       reason=f"rolling restart saw {failed} non-shed "
                              f"request failure(s) (contract: zero)")
            return res
        # torn-version rule (hard, like the failure rule above): a
        # response carrying an older weights_version after a newer one
        # was already visible on the same replica means the atomic
        # flip tore mid-swap.  The dedicated rollout leg must MEASURE
        # the count — missing there is a vacuous pass; plain
        # rolling-restart windows predate the check and simply don't
        # carry the key
        torn = rollout.get("torn_responses",
                           None if name == "rollout" else 0)
        if torn is None:
            res.update(status="regression",
                       reason="rollout leg has no measured torn-"
                              "version count (vacuous hot-swap "
                              "window)")
            return res
        if torn > 0:
            res.update(status="regression",
                       reason=f"hot swap served {torn} torn-version "
                              f"response(s) — an older weights_version "
                              f"after a newer one was visible "
                              f"(contract: zero)")
            return res
    # canary rollout rules, also checked before every skip: a CLEAN
    # canary that reverted means the burn-rate judge convicted a good
    # checkpoint (false positive — rollouts become un-shippable), and
    # a BAD canary whose revert took longer than the bound means the
    # judge is too slow to protect traffic.  Core contention can slow
    # a soak, never fabricate burn on a clean version
    canary = new.get("canary")
    if isinstance(canary, dict):
        fr = canary.get("false_reverts")
        if fr is None:
            res.update(status="regression",
                       reason="canary leg has no measured false-"
                              "revert count (vacuous soak: the clean "
                              "canary never ran)")
            return res
        if fr > 0:
            res.update(status="regression",
                       reason=f"{fr} clean canary rollout(s) were "
                              f"auto-reverted (burn-rate false "
                              f"positive; contract: zero)")
            return res
        lat = canary.get("revert_latency_s")
        bound = canary.get("revert_latency_bound_s")
        if canary.get("reverts"):
            # a bad canary was injected: the revert must be measured
            # and inside the leg's own bound
            if lat is None:
                res.update(status="regression",
                           reason="canary auto-revert happened but "
                                  "its latency went unmeasured "
                                  "(vacuous revert evidence)")
                return res
            if bound is not None and lat > bound:
                res.update(status="regression",
                           reason=f"canary auto-revert took "
                                  f"{lat:.1f}s, past the "
                                  f"{bound:.1f}s bound — the judge "
                                  f"is too slow to protect traffic")
                return res
    # chaos fault-containment rules, also checked before every skip:
    # a collateral (non-injected) failure or a poisoned request served
    # 200 is a correctness break — core contention can slow recovery,
    # never cause either
    if "collateral_failures" in new:
        cf = new.get("collateral_failures")
        if cf is None:
            res.update(status="regression",
                       reason="chaos run measured no collateral-"
                              "failure count (vacuous window)")
            return res
        if cf > 0:
            res.update(status="regression",
                       reason=f"chaos saw {cf} collateral (non-"
                              f"injected) request failure(s) "
                              f"(contract: zero)")
            return res
        leaks = new.get("poison_leaks")
        if leaks is None:
            # like the collateral rule: a dropped field must not read
            # as "zero leaks"
            res.update(status="regression",
                       reason="chaos run measured no poison-leak "
                              "count (vacuous window)")
            return res
        if leaks > 0:
            res.update(status="regression",
                       reason=f"{leaks} poisoned request(s) answered "
                              f"200 instead of failing (bisection "
                              f"containment leak)")
            return res
        # burn-rate alert contract (observability hard rule, like the
        # two above — no anomaly flag shields it): a fault window the
        # alert missed, a recovery it never cleared after, or a clean
        # scenario it paged on.  None is allowed — captures predate
        # the alerting layer
        alert_errors = new.get("alert_errors")
        if alert_errors:
            res.update(status="regression",
                       reason=f"chaos saw {alert_errors} burn-rate "
                              f"alert-contract violation(s) (missed "
                              f"fire / missed clear / false positive)")
            return res
        # disagg page-pool leak rule (hard, like collateral/leaks):
        # a live page surviving the drained storm means a refcount
        # path (export / adopt / failure) lost a decref — core
        # contention can slow the drain, never leak a page.  None is
        # allowed: captures predate the disagg scenario
        leaked_pages = new.get("leaked_pages")
        if leaked_pages:
            res.update(status="regression",
                       reason=f"chaos disagg_crash left "
                              f"{leaked_pages} KV page(s) live after "
                              f"the storm drained (refcount leak)")
            return res
        # embedding pin-leak rule (hard, like leaked_pages): a hot
        # row still pinned after the recsys storm drained means a
        # lookup path lost its unpin — core contention can slow the
        # drain, never leak a pin.  None is allowed: captures predate
        # the embedding_shard_crash scenario
        leaked_rows = new.get("leaked_rows")
        if leaked_rows:
            res.update(status="regression",
                       reason=f"chaos embedding_shard_crash left "
                              f"{leaked_rows} hot row(s) pinned after "
                              f"the storm drained (refcount leak)")
            return res
        # crash-forensics rule (hard, like collateral/leaks): every
        # induced death must be harvested and attributed — a death
        # the supervisor cannot explain means the flight recorder,
        # the kill-mark path, or the harvest broke.  Present-but-None
        # is a vacuous verdict (a death was never even booked) and
        # fails too; the key absent is allowed — captures predate the
        # forensics layer
        if "unexplained_deaths" in new:
            ud = new.get("unexplained_deaths")
            if ud is None:
                res.update(status="regression",
                           reason="chaos run measured no unexplained-"
                                  "death count (vacuous forensics: an "
                                  "induced death was never booked)")
                return res
            if ud > 0:
                res.update(status="regression",
                           reason=f"chaos saw {ud} unexplained replica "
                                  f"death(s) — died rc>0 with no "
                                  f"postmortem artifact (contract: "
                                  f"zero)")
                return res
        # usage-conservation rule (hard, like collateral/leaks): the
        # per-tenant cost vectors must sum EXACTLY to the global
        # counters — tolerance 0, through a SIGKILL-respawn.  Present-
        # but-None is a vacuous verdict (the scenario ran but could
        # not measure conservation) and fails too; the key absent is
        # allowed — captures predate the usage observatory
        if "usage_conservation_delta" in new:
            ucd = new.get("usage_conservation_delta")
            if ucd is None:
                res.update(status="regression",
                           reason="chaos run measured no usage-"
                                  "conservation delta (vacuous: per-"
                                  "tenant attribution never verified)")
                return res
            if ucd != 0:
                res.update(status="regression",
                           reason=f"per-tenant usage does not conserve:"
                                  f" delta {ucd} against the global "
                                  f"counters (contract: exactly zero)")
                return res
        # noisy-neighbor attribution floor (hard): the hog tenant's
        # booked cost share must be at least 90% of its client-side
        # share — a tenant header dropped on any hop folds the hog
        # into the default tenant and collapses this ratio.  None is
        # vacuous (unmeasured) and fails; absent is allowed
        if "hog_attribution_ratio" in new:
            har = new.get("hog_attribution_ratio")
            if har is None:
                res.update(status="regression",
                           reason="chaos noisy_neighbor measured no "
                                  "hog attribution ratio (vacuous: "
                                  "excess cost never attributed)")
                return res
            if har < 0.9:
                res.update(status="regression",
                           reason=f"hog attribution ratio {har} below "
                                  f"the 0.9 floor — excess cost was "
                                  f"not booked to the noisy tenant")
                return res
        # heavy-hitter sketch memory bound (hard): no replica may ever
        # hold more than top_k tracked vectors (+1 for ~other) no
        # matter the tenant cardinality.  None is vacuous and fails;
        # absent is allowed
        if "sketch_violations" in new:
            sv = new.get("sketch_violations")
            if sv is None:
                res.update(status="regression",
                           reason="chaos run measured no sketch-bound "
                                  "verdict (vacuous: memory bound "
                                  "never checked)")
                return res
            if sv > 0:
                res.update(status="regression",
                           reason=f"{sv} replica(s) violated the "
                                  f"heavy-hitter sketch memory bound "
                                  f"(contract: <= top_k + 1 vectors)")
                return res
        # the harness's own verdict: a scenario that errored (watchdog
        # never fired, no poisoned request reached a model, victim
        # never respawned) means a containment mechanism went
        # unexercised or dead — counts alone can pass vacuously
        if new.get("harness_ok") is False or new.get("errors"):
            detail = new.get("errors") or "harness_ok=false"
            res.update(status="regression",
                       reason=f"chaos harness reported scenario "
                              f"errors: {detail}")
            return res
    # disagg vacuous-A/B rule, also checked before every skip: a leg
    # that carries the ratio key but measured None means the A/B's
    # decode grid never stepped — an empty measurement must not read
    # as "no regression" on any host
    if "disagg_vs_colocated_p99" in new \
            and new.get("disagg_vs_colocated_p99") is None:
        res.update(status="regression",
                   reason="disagg leg has no measured decode-step "
                          "p99 ratio (vacuous A/B: the decode grid "
                          "never stepped)")
        return res
    # speculative-decode hard rules, also checked before every skip:
    # core contention can slow the verify chunk (the tokens/sec ratio
    # honestly sits under 1.0 on core-bound hosts — that is what the
    # anomaly flag and the baseline-armed collapse rule are for), but
    # it can never leak a page, unbalance the rollback counters, or
    # lower greedy-argmax acceptance on a deterministic workload
    if "spec_tokens_proposed" in new:
        sl = new.get("leaked_pages")
        if sl is None:
            res.update(status="regression",
                       reason="spec leg measured no leaked-page count "
                              "(vacuous drain: the pool was never "
                              "checked after rejected drafts)")
            return res
        if sl > 0:
            res.update(status="regression",
                       reason=f"spec decode left {sl} KV page(s) live "
                              f"after drain (rejected-draft rollback "
                              f"refcount leak)")
            return res
        prop = new.get("spec_tokens_proposed")
        acc = new.get("spec_tokens_accepted")
        drafts = new.get("spec_drafts")
        rb = new.get("spec_rollbacks")
        if None in (prop, acc, drafts, rb):
            res.update(status="regression",
                       reason="spec leg is missing draft/accept/"
                              "rollback counters (vacuous speculation "
                              "window)")
            return res
        if acc > prop:
            res.update(status="regression",
                       reason=f"spec accepted {acc} draft tokens out "
                              f"of {prop} proposed — the acceptance "
                              f"bookkeeping overcounts")
            return res
        if rb > drafts:
            res.update(status="regression",
                       reason=f"spec rolled back {rb} drafts but only "
                              f"{drafts} were issued — the rollback "
                              f"bookkeeping overcounts")
            return res
        ar = new.get("acceptance_rate")
        ar_floor = new.get("acceptance_floor")
        if ar_floor is not None:
            if ar is None:
                res.update(status="regression",
                           reason="spec leg declares an acceptance "
                                  "floor but measured no acceptance "
                                  "rate (vacuous: the drafter never "
                                  "fired)")
                return res
            if ar < float(ar_floor):
                res.update(status="regression",
                           reason=f"spec acceptance rate {ar} under "
                                  f"the {ar_floor} floor on the "
                                  f"repetition-heavy workload (the "
                                  f"drafter or verifier broke)")
                return res
    # recsys embedding-tier hard rules, also checked before every
    # skip: the clean bench keeps every shard alive, so a degraded
    # lookup is a correctness break (a gather failed mid-leg), and a
    # present-but-None count is a vacuous window — core contention
    # can slow lookups, never degrade them.  The hot-row hit-rate
    # floor rides the leg (like prefix_hit_floor): under it the cache
    # is dead (hashing/eviction broke) even when throughput keeps up,
    # and no anomaly flag shields either rule
    if "degraded_lookups" in new:
        dl = new.get("degraded_lookups")
        if dl is None:
            res.update(status="regression",
                       reason="recsys leg measured no degraded-lookup "
                              "count (vacuous window: the embedding "
                              "tier never booked its counters)")
            return res
        if dl > 0:
            res.update(status="regression",
                       reason=f"recsys bench saw {dl} degraded "
                              f"lookup(s) with every shard alive "
                              f"(contract: zero)")
            return res
        hr_floor = new.get("hit_floor")
        if hr_floor is not None:
            hr = (new.get("hit_rate") or {}).get("hot")
            if hr is None:
                res.update(status="regression",
                           reason="recsys leg declares a hot-row hit-"
                                  "rate floor but measured no hot-"
                                  "phase hit rate (vacuous: the cache "
                                  "was never probed)")
                return res
            if hr < float(hr_floor):
                res.update(status="regression",
                           reason=f"recsys hot-row hit rate {hr} "
                                  f"under the {hr_floor} floor on the "
                                  f"zipfian hot workload (the hot-row "
                                  f"cache is dead)")
                return res
    nk, bk = new.get("device_kind"), base.get("device_kind")
    if nk is not None and bk is not None and nk != bk:
        res.update(status="skipped",
                   reason=f"device_kind {nk!r} != baseline {bk!r}")
        return res
    if base.get("anomaly"):
        res.update(status="skipped",
                   reason=f"baseline flagged anomalous: "
                          f"{base['anomaly']}")
        return res
    new_med, base_med = _median_of(new), _median_of(base)
    tol = max(floor_tol, _noise(base), _noise(new))
    threshold = base_med * (1.0 - tol)
    res.update(base_median=round(base_med, 2),
               new_median=round(new_med, 2),
               ratio=round(new_med / base_med, 4) if base_med else None,
               tolerance=round(tol, 4),
               threshold=round(threshold, 2))
    if new.get("anomaly"):
        # an anomalous fresh number can't prove health — but it also
        # must not fail the gate on chip contention; surface it loudly
        res.update(status="skipped",
                   reason=f"fresh run flagged anomalous: "
                          f"{new['anomaly']}")
        return res
    res["status"] = "regression" if new_med < threshold else "ok"
    # decode-leg extra: the leg's headline is continuous-batching
    # tokens/sec, but the scheduler's reason to exist is beating its
    # own FIFO static baseline — if the fresh speedup drops below 1.0
    # while the baseline had the win, the fast path regressed even when
    # raw tokens/sec kept up (e.g. the static path got faster because
    # the continuous path stopped reclaiming slots)
    sp_new = new.get("speedup_vs_static")
    sp_base = base.get("speedup_vs_static")
    if res["status"] == "ok" and sp_new is not None \
            and sp_base is not None and sp_new < 1.0 <= sp_base:
        res.update(status="regression",
                   reason=f"speedup_vs_static collapsed to {sp_new} "
                          f"(baseline {sp_base})")
    # sharded-serving extras: the replica-group engine's contract is
    # dp=4 at >= 2x the single-chip qps AT NO WORSE p99 — raw qps can
    # keep up (e.g. the single-chip baseline got slower too) while the
    # dp win quietly collapses, so both ratios gate explicitly when the
    # baseline proved them on this device kind
    sg_new = new.get("speedup_vs_single")
    sg_base = base.get("speedup_vs_single")
    if res["status"] == "ok" and sg_new is not None \
            and sg_base is not None and sg_new < 2.0 <= sg_base:
        res.update(status="regression",
                   reason=f"speedup_vs_single fell to {sg_new} "
                          f"(< 2x dp contract; baseline {sg_base})")
    p99r_new = new.get("p99_vs_single")
    p99r_base = base.get("p99_vs_single")
    if res["status"] == "ok" and p99r_new is not None \
            and p99r_base is not None \
            and p99r_new > 1.0 + tol >= p99r_base:
        res.update(status="regression",
                   reason=f"dp p99 now {p99r_new}x the single-chip "
                          f"p99 (was {p99r_base}x; tol {tol})")
    # router-leg extra: the fleet tier's contract is >= 2x closed-loop
    # qps at 4 replicas vs 1 — raw qps can track the baseline while
    # the scaling itself quietly collapses (e.g. the router started
    # serializing on one replica), so the ratio gates explicitly when
    # the baseline proved it on this device kind
    s4_new = new.get("speedup_4v1")
    s4_base = base.get("speedup_4v1")
    if res["status"] == "ok" and s4_new is not None \
            and s4_base is not None and s4_new < 2.0 <= s4_base:
        res.update(status="regression",
                   reason=f"speedup_4v1 fell to {s4_new} (< 2x fleet "
                          f"scaling contract; baseline {s4_base})")
    # chaos-leg extra: availability under fault must clear the
    # committed floor.  Unlike the collateral rule this respects the
    # anomaly skip above — a core-bound host genuinely slows recovery
    # windows, which honestly costs availability
    floor = new.get("availability_floor")
    if res["status"] == "ok" and floor is not None \
            and new_med < float(floor):
        res.update(status="regression",
                   reason=f"availability {new_med}% under the "
                          f"{floor}% chaos budget")
    # paged-decode extras: the paged cache's reason to exist is
    # holding >= 2x the concurrent sequences per GB of KV pool (ISSUE
    # 11 acceptance bar) — raw tokens/sec can track the baseline while
    # the memory win quietly collapses (e.g. pages leak and the pool
    # saturates), so the ratio gates explicitly when the baseline
    # proved it on this device kind
    spg_new = new.get("seq_per_gb_vs_dense")
    spg_base = base.get("seq_per_gb_vs_dense")
    if res["status"] == "ok" and spg_new is not None \
            and spg_base is not None and spg_new < 2.0 <= spg_base:
        res.update(status="regression",
                   reason=f"seq_per_gb_vs_dense fell to {spg_new} "
                          f"(< 2x paged memory contract; baseline "
                          f"{spg_base})")
    # ...and a paged tokens/sec win, once proven on a device kind,
    # must not collapse below the dense fallback (compute-saturated
    # CPU smoke hosts capture < 1.0 honestly — the rule arms only
    # where the baseline had the win, like the other speedup rules)
    pvd_new = new.get("paged_vs_dense_tokens")
    pvd_base = base.get("paged_vs_dense_tokens")
    if res["status"] == "ok" and pvd_new is not None \
            and pvd_base is not None and pvd_new < 1.0 <= pvd_base:
        res.update(status="regression",
                   reason=f"paged_vs_dense_tokens collapsed to "
                          f"{pvd_new} (baseline {pvd_base}: paged "
                          f"beat dense)")
    # ...and on the shared-system-prompt workload the prefix index
    # must actually fire: a hit rate under the committed floor means
    # the reuse machinery is dead (hashing broke, registration
    # stopped, eviction runs wild) even if throughput looks fine
    phr = new.get("prefix_hit_rate")
    phr_floor = new.get("prefix_hit_floor")
    if res["status"] == "ok" and phr is not None \
            and phr_floor is not None and phr < float(phr_floor):
        res.update(status="regression",
                   reason=f"prefix hit rate {phr} under the "
                          f"{phr_floor} floor on the shared-prompt "
                          f"workload")
    # spec-decode extra: once a baseline proved speculative decode
    # beats the plain grid step on a device kind, a fresh ratio under
    # 1.0 means the speedup collapsed (verify got slower than the K+1
    # steps it replaces) even when raw tokens/sec keeps up — arms only
    # where the baseline had the win, like paged_vs_dense_tokens
    # (core-bound CPU smoke captures honestly sit under 1.0)
    svp_new = new.get("spec_vs_plain_tokens")
    svp_base = base.get("spec_vs_plain_tokens")
    if res["status"] == "ok" and svp_new is not None \
            and svp_base is not None and svp_new < 1.0 <= svp_base:
        res.update(status="regression",
                   reason=f"spec_vs_plain_tokens collapsed to "
                          f"{svp_new} (baseline {svp_base}: "
                          f"speculation beat the plain grid step)")
    # disagg-leg extras: the disaggregated pipeline's reason to exist
    # is decode-step p99 under the mixed workload.  (a) A leg that
    # carries the key but measured nothing is vacuous — the A/B's
    # decode grid never stepped, which no skip may shield; (b) once a
    # baseline proved the p99 win (ratio <= 1.0) on this device kind,
    # a fresh ratio collapsing past 1.0+tol is a regression even when
    # raw tokens/sec keeps up (mirrors the dp p99 rule)
    dvp = new.get("disagg_vs_colocated_p99")
    if dvp is not None:
        dvp_base = base.get("disagg_vs_colocated_p99")
        # arm strictly on dvp_base <= 1.0 (the baseline PROVED the
        # win), not <= 1.0+tol — a baseline inside the noise gap
        # never proved anything and must not flap the gate
        if res["status"] == "ok" and dvp_base is not None \
                and dvp > 1.0 + tol and dvp_base <= 1.0:
            res.update(status="regression",
                       reason=f"disagg decode-step p99 now {dvp}x "
                              f"colocated (was {dvp_base}x; tol "
                              f"{tol}) — the handoff stopped paying "
                              f"for itself")
    return res


def compare_bench(new_doc: dict, base_docs: List[dict],
                  floor_tol: float = FLOOR_TOL) -> dict:
    """Gate a fresh bench report against the baseline trajectory.

    For each leg in the fresh report, the baseline is the LAST given
    document carrying that leg (pass baselines oldest→newest); earlier
    medians are reported as ``trajectory`` context.  A leg present in
    a baseline but missing from the fresh report is a regression (a
    silently-vanished leg must not pass)."""
    new_legs = extract_legs(new_doc)
    results = []
    seen = set()
    base_legsets = [extract_legs(d) for d in base_docs]
    for name, new_leg in new_legs.items():
        base_leg, trajectory = None, []
        for legs in base_legsets:
            if name in legs:
                base_leg = legs[name]
                trajectory.append(_median_of(legs[name]))
        if base_leg is None:
            results.append({"leg": name, "status": "new",
                            "new_median": round(_median_of(new_leg), 2)})
            continue
        seen.add(name)
        res = compare_leg(name, new_leg, base_leg, floor_tol)
        if len(trajectory) > 1:
            res["trajectory"] = [round(t, 2) for t in trajectory]
        results.append(res)
    for legs in base_legsets:
        for name in legs:
            if name not in new_legs and name not in seen:
                seen.add(name)
                results.append({"leg": name, "status": "regression",
                                "reason": "leg missing from fresh "
                                          "report"})
    ok = all(r["status"] != "regression" for r in results)
    return {"ok": ok, "floor_tol": floor_tol, "legs": results}


def compare_ops(new: dict, base: dict,
                threshold: float = OP_THRESHOLD) -> dict:
    """Per-op gate (same policy as tools/check_op_bench.py): fail on
    ratio > threshold or a newly-failing op; skip entirely on a
    device_kind mismatch."""
    if new.get("device_kind") != base.get("device_kind"):
        return {"ok": True, "skipped": True,
                "reason": f"device_kind {new.get('device_kind')!r} != "
                          f"baseline {base.get('device_kind')!r}"}
    regressions, missing = [], []
    for name, b_us in (base.get("ops") or {}).items():
        r_us = (new.get("ops") or {}).get(name)
        if r_us is None:
            missing.append(name)
            continue
        ratio = r_us / b_us if b_us else 0.0
        if ratio > threshold:
            regressions.append({"op": name, "base_us": b_us,
                                "new_us": r_us,
                                "ratio": round(ratio, 3)})
    return {"ok": not regressions and not missing,
            "threshold": threshold, "regressions": regressions,
            "missing": missing}


# ---------------------------------------------------------------------------
# smoke mode: prove the gate logic on committed fixtures (no bench run)
# ---------------------------------------------------------------------------

def _degrade(doc: dict, factor: float) -> dict:
    """A synthetically slower copy of a bench report: every leg's value
    and window stats scaled by ``factor``."""
    out = json.loads(json.dumps(doc))
    for leg in extract_legs(out).values():
        leg["value"] = leg["value"] * factor
        for k in ("median", "p10", "p90", "min", "max"):
            if k in (leg.get("stats") or {}):
                leg["stats"][k] = leg["stats"][k] * factor
    return out


def run_smoke() -> int:
    """Assert the gate's pass/fail behavior against the checked-in
    BENCH_r0*.json + op_bench_baseline.json fixtures.  Returns 0 when
    every assertion holds (tier-1 wires this via tests/test_lint.py)."""
    fixtures = sorted(glob.glob(os.path.join(REPO, "BENCH_r0*.json")))
    if not fixtures:
        print("smoke: no BENCH_r0*.json fixtures found")
        return 1
    docs = [load_report(p) for p in fixtures]
    latest = docs[-1]
    checks = []

    def check(name, cond, detail=""):
        checks.append((name, bool(cond), detail))

    # unchanged tree: the latest capture gated against the full
    # trajectory (itself last) must pass
    r = compare_bench(latest, docs)
    check("unchanged-tree passes", r["ok"],
          json.dumps([x for x in r["legs"]
                      if x["status"] == "regression"]))
    # a 30% slowdown must fail (far past the 10% drift floor + spread)
    r = compare_bench(_degrade(latest, 0.70), docs)
    check("30%-degraded fails", not r["ok"])
    # a 3% wiggle is inside the noise floor: must NOT flap
    r = compare_bench(_degrade(latest, 0.97), docs)
    check("3%-wiggle passes", r["ok"],
          json.dumps([x for x in r["legs"]
                      if x["status"] == "regression"]))
    # a vanished leg must fail
    pruned = json.loads(json.dumps(latest))
    if pruned.get("legs"):
        pruned["legs"].pop(sorted(pruned["legs"])[0], None)
        r = compare_bench(pruned, docs)
        check("missing-leg fails", not r["ok"])
    # device-kind mismatch must skip, not fail
    other = json.loads(json.dumps(latest))
    for leg in extract_legs(other).values():
        leg["device_kind"] = "TPU v9000"
    r = compare_bench(other, docs)
    check("device-mismatch skips", r["ok"] and any(
        x["status"] == "skipped" for x in r["legs"]))

    # decode leg (synthetic until a BENCH_r* capture carries it): the
    # generic noise-aware gate applies, plus the speedup-collapse rule
    decode_leg = {
        "metric": "llama_decode_tokens_per_sec_per_chip",
        "value": 2500.0, "unit": "tokens/sec/chip",
        "device_kind": "cpu",
        "stats": {"rounds": 3, "median": 2500.0, "p10": 2300.0,
                  "p90": 2700.0, "min": 2250.0, "max": 2750.0},
        "speedup_vs_static": 2.4,
    }
    with_decode = json.loads(json.dumps(latest))
    with_decode.setdefault("legs", {})["llama_decode"] = decode_leg
    r = compare_bench(with_decode, docs + [with_decode])
    check("decode self-compare passes", r["ok"],
          json.dumps([x for x in r["legs"]
                      if x["status"] == "regression"]))
    r = compare_bench(_degrade(with_decode, 0.70), docs + [with_decode])
    check("decode 30%-degraded fails", not r["ok"])
    collapsed = json.loads(json.dumps(with_decode))
    collapsed["legs"]["llama_decode"]["speedup_vs_static"] = 0.8
    r = compare_bench(collapsed, docs + [with_decode])
    check("decode speedup-collapse fails", not r["ok"] and any(
        x["status"] == "regression" and "speedup" in x.get("reason", "")
        for x in r["legs"]))

    # paged-decode leg (synthetic until a BENCH_r* capture carries
    # it): generic noise gate + the seq-per-GB memory contract + the
    # paged-vs-dense tokens collapse rule + the prefix-hit-rate floor
    paged_leg = {
        "metric": "llama_paged_decode_tokens_per_sec_per_chip",
        "value": 2100.0, "unit": "tokens/sec/chip",
        "device_kind": "cpu",
        "stats": {"rounds": 3, "median": 2100.0, "p10": 1950.0,
                  "p90": 2250.0, "min": 1900.0, "max": 2300.0},
        "dense_tokens_per_sec": 1800.0,
        "paged_vs_dense_tokens": 1.17,
        "seq_per_gb": 16000.0, "dense_seq_per_gb": 4100.0,
        "seq_per_gb_vs_dense": 3.9,
        "prefix_hit_rate": 0.75, "prefix_hit_floor": 0.3,
    }
    with_paged = json.loads(json.dumps(latest))
    with_paged.setdefault("legs", {})["llama_paged_decode"] = paged_leg
    r = compare_bench(with_paged, docs + [with_paged])
    check("paged self-compare passes", r["ok"],
          json.dumps([x for x in r["legs"]
                      if x["status"] == "regression"]))
    r = compare_bench(_degrade(with_paged, 0.70), docs + [with_paged])
    check("paged 30%-degraded fails", not r["ok"])
    mem_collapse = json.loads(json.dumps(with_paged))
    mem_collapse["legs"]["llama_paged_decode"]["seq_per_gb_vs_dense"] \
        = 1.4
    r = compare_bench(mem_collapse, docs + [with_paged])
    check("paged seq-per-GB collapse fails", not r["ok"] and any(
        x["status"] == "regression"
        and "seq_per_gb" in x.get("reason", "") for x in r["legs"]))
    tok_collapse = json.loads(json.dumps(with_paged))
    tok_collapse["legs"]["llama_paged_decode"]["paged_vs_dense_tokens"] \
        = 0.8
    r = compare_bench(tok_collapse, docs + [with_paged])
    check("paged tokens-collapse fails", not r["ok"] and any(
        x["status"] == "regression"
        and "paged_vs_dense_tokens" in x.get("reason", "")
        for x in r["legs"]))
    # ...but a sub-1.0 ratio must NOT flap when the baseline never
    # proved the win (compute-saturated CPU smoke captures)
    never_won = json.loads(json.dumps(with_paged))
    never_won["legs"]["llama_paged_decode"]["paged_vs_dense_tokens"] \
        = 0.9
    r = compare_bench(tok_collapse, docs + [never_won])
    check("paged sub-1.0 tokens vs sub-1.0 baseline passes", r["ok"],
          json.dumps([x for x in r["legs"]
                      if x["status"] == "regression"]))
    dead_index = json.loads(json.dumps(with_paged))
    dead_index["legs"]["llama_paged_decode"]["prefix_hit_rate"] = 0.1
    r = compare_bench(dead_index, docs + [with_paged])
    check("paged dead-prefix-index fails", not r["ok"] and any(
        x["status"] == "regression"
        and "prefix hit rate" in x.get("reason", "")
        for x in r["legs"]))

    # disagg leg (synthetic until a BENCH_r* capture carries it):
    # generic noise gate + the decode-step p99 collapse rule (arms
    # only where the baseline proved the < 1.0 win) + the
    # vacuous-None hard rule
    disagg_leg = {
        "metric": "llama_disagg_tokens_per_sec",
        "value": 1900.0, "unit": "tokens/sec",
        "device_kind": "cpu",
        "stats": {"rounds": 3, "median": 1900.0, "p10": 1780.0,
                  "p90": 2050.0, "min": 1750.0, "max": 2100.0},
        "colocated_tokens_per_sec": 1850.0,
        "disagg_vs_colocated_tokens": 1.03,
        "disagg_vs_colocated_p99": 0.62,
        "p99_step_ms": 3.1, "colocated_p99_step_ms": 5.0,
        "handoffs": 48,
    }
    with_disagg = json.loads(json.dumps(latest))
    with_disagg.setdefault("legs", {})["llama_disagg"] = disagg_leg
    r = compare_bench(with_disagg, docs + [with_disagg])
    check("disagg self-compare passes", r["ok"],
          json.dumps([x for x in r["legs"]
                      if x["status"] == "regression"]))
    r = compare_bench(_degrade(with_disagg, 0.70),
                      docs + [with_disagg])
    check("disagg 30%-degraded fails", not r["ok"])
    p99_collapse = json.loads(json.dumps(with_disagg))
    p99_collapse["legs"]["llama_disagg"]["disagg_vs_colocated_p99"] \
        = 1.6
    r = compare_bench(p99_collapse, docs + [with_disagg])
    check("disagg p99-collapse fails", not r["ok"] and any(
        x["status"] == "regression"
        and "decode-step p99" in x.get("reason", "")
        for x in r["legs"]))
    # ...but a > 1.0 ratio must NOT flap when the baseline never
    # proved the win (core-bound CPU smoke captures) — 1.05 sits in
    # the (1.0, 1.0+tol] noise gap, the sharpest non-proof
    never_won_d = json.loads(json.dumps(with_disagg))
    never_won_d["legs"]["llama_disagg"]["disagg_vs_colocated_p99"] \
        = 1.05
    r = compare_bench(p99_collapse, docs + [never_won_d])
    check("disagg >1.0 p99 vs >1.0 baseline passes", r["ok"],
          json.dumps([x for x in r["legs"]
                      if x["status"] == "regression"]))
    vacuous_d = json.loads(json.dumps(with_disagg))
    vacuous_d["legs"]["llama_disagg"]["disagg_vs_colocated_p99"] = None
    r = compare_bench(vacuous_d, docs + [with_disagg])
    check("disagg vacuous-None fails", not r["ok"] and any(
        x["status"] == "regression"
        and "vacuous A/B" in x.get("reason", "") for x in r["legs"]))

    # spec-decode leg (synthetic capable-host fixture, like the
    # sharded one: core-bound CPU captures flag the speedup anomalous,
    # so the >1.0 ratio is proven on fixture numbers): generic noise
    # gate + the acceptance floor / rollback balance / leaked pages
    # hard rules (which no anomaly flag shields) + the
    # spec-vs-plain collapse rule (which arms only where the baseline
    # proved the win)
    spec_leg = {
        "metric": "llama_spec_decode_tokens_per_sec_per_chip",
        "value": 2600.0, "unit": "tokens/sec/chip",
        "device_kind": "cpu",
        "stats": {"rounds": 3, "median": 2600.0, "p10": 2450.0,
                  "p90": 2750.0, "min": 2400.0, "max": 2800.0},
        "plain_tokens_per_sec": 1900.0,
        "spec_vs_plain_tokens": 1.37,
        "acceptance_rate": 0.62, "acceptance_floor": 0.3,
        "spec_drafts": 400, "spec_tokens_proposed": 1500,
        "spec_tokens_accepted": 930, "spec_rollbacks": 210,
        "leaked_pages": 0,
    }
    with_spec = json.loads(json.dumps(latest))
    with_spec.setdefault("legs", {})["llama_spec_decode"] = spec_leg
    r = compare_bench(with_spec, docs + [with_spec])
    check("spec self-compare passes", r["ok"],
          json.dumps([x for x in r["legs"]
                      if x["status"] == "regression"]))
    r = compare_bench(_degrade(with_spec, 0.70), docs + [with_spec])
    check("spec 30%-degraded fails", not r["ok"])
    low_accept = json.loads(json.dumps(with_spec))
    low_accept["legs"]["llama_spec_decode"]["acceptance_rate"] = 0.05
    # an anomaly flag must NOT shield a dead drafter
    low_accept["legs"]["llama_spec_decode"]["anomaly"] = \
        "core-bound host"
    r = compare_bench(low_accept, docs + [with_spec])
    check("spec acceptance-floor breach fails even when anomalous",
          not r["ok"] and any(
              x["status"] == "regression"
              and "acceptance rate" in x.get("reason", "")
              for x in r["legs"]))
    vac_accept = json.loads(json.dumps(with_spec))
    vac_accept["legs"]["llama_spec_decode"]["acceptance_rate"] = None
    r = compare_bench(vac_accept, docs + [with_spec])
    check("spec vacuous-acceptance fails", not r["ok"] and any(
        x["status"] == "regression"
        and "vacuous" in x.get("reason", "") for x in r["legs"]))
    spec_collapse = json.loads(json.dumps(with_spec))
    spec_collapse["legs"]["llama_spec_decode"]["spec_vs_plain_tokens"] \
        = 0.8
    r = compare_bench(spec_collapse, docs + [with_spec])
    check("spec slower-than-plain collapse fails", not r["ok"] and any(
        x["status"] == "regression"
        and "spec_vs_plain_tokens" in x.get("reason", "")
        for x in r["legs"]))
    # ...but a sub-1.0 ratio must NOT flap when the baseline never
    # proved the win (core-bound CPU smoke captures)
    never_won_s = json.loads(json.dumps(with_spec))
    never_won_s["legs"]["llama_spec_decode"]["spec_vs_plain_tokens"] \
        = 0.9
    r = compare_bench(spec_collapse, docs + [never_won_s])
    check("spec sub-1.0 vs sub-1.0 baseline passes", r["ok"],
          json.dumps([x for x in r["legs"]
                      if x["status"] == "regression"]))
    imbalance = json.loads(json.dumps(with_spec))
    imbalance["legs"]["llama_spec_decode"]["spec_tokens_accepted"] \
        = 1600
    imbalance["legs"]["llama_spec_decode"]["anomaly"] = \
        "core-bound host"
    r = compare_bench(imbalance, docs + [with_spec])
    check("spec accept>propose imbalance fails even when anomalous",
          not r["ok"] and any(
              x["status"] == "regression"
              and "overcounts" in x.get("reason", "")
              for x in r["legs"]))
    rb_imbalance = json.loads(json.dumps(with_spec))
    rb_imbalance["legs"]["llama_spec_decode"]["spec_rollbacks"] = 500
    r = compare_bench(rb_imbalance, docs + [with_spec])
    check("spec rollback>draft imbalance fails", not r["ok"] and any(
        x["status"] == "regression"
        and "rollback bookkeeping" in x.get("reason", "")
        for x in r["legs"]))
    page_leak_s = json.loads(json.dumps(with_spec))
    page_leak_s["legs"]["llama_spec_decode"]["leaked_pages"] = 2
    page_leak_s["legs"]["llama_spec_decode"]["anomaly"] = \
        "core-bound host"
    r = compare_bench(page_leak_s, docs + [with_spec])
    check("spec leaked-pages fails even when anomalous",
          not r["ok"] and any(
              x["status"] == "regression"
              and "refcount leak" in x.get("reason", "")
              for x in r["legs"]))
    vac_leak = json.loads(json.dumps(with_spec))
    vac_leak["legs"]["llama_spec_decode"]["leaked_pages"] = None
    r = compare_bench(vac_leak, docs + [with_spec])
    check("spec vacuous-leak-count fails", not r["ok"] and any(
        x["status"] == "regression"
        and "vacuous drain" in x.get("reason", "")
        for x in r["legs"]))

    # recsys leg (synthetic until a BENCH_r* capture carries it):
    # generic noise gate + the degraded-lookup hard zero + the hot-row
    # hit-rate floor (both of which no anomaly flag shields)
    recsys_leg = {
        "metric": "recsys_closed_loop_qps",
        "value": 1800.0, "unit": "requests/sec", "device_kind": "cpu",
        "stats": {"rounds": 3, "median": 1800.0, "p10": 1700.0,
                  "p90": 1900.0, "min": 1650.0, "max": 1950.0},
        "p99_ms": 18.0,
        "hit_rate": {"hot": 0.82, "cold": 0.41}, "hit_floor": 0.5,
        "degraded_lookups": 0,
    }
    with_rec = json.loads(json.dumps(latest))
    with_rec.setdefault("legs", {})["wide_deep_recsys"] = recsys_leg
    r = compare_bench(with_rec, docs + [with_rec])
    check("recsys self-compare passes", r["ok"],
          json.dumps([x for x in r["legs"]
                      if x["status"] == "regression"]))
    r = compare_bench(_degrade(with_rec, 0.70), docs + [with_rec])
    check("recsys 30%-degraded fails", not r["ok"])
    degraded_rec = json.loads(json.dumps(with_rec))
    degraded_rec["legs"]["wide_deep_recsys"]["degraded_lookups"] = 3
    # an anomaly flag must NOT shield a degraded-lookup break
    degraded_rec["legs"]["wide_deep_recsys"]["anomaly"] = \
        "core-bound host"
    r = compare_bench(degraded_rec, docs + [with_rec])
    check("recsys degraded-lookups fails even when anomalous",
          not r["ok"] and any(
              x["status"] == "regression"
              and "degraded lookup" in x.get("reason", "")
              for x in r["legs"]))
    vac_degraded = json.loads(json.dumps(with_rec))
    vac_degraded["legs"]["wide_deep_recsys"]["degraded_lookups"] = None
    r = compare_bench(vac_degraded, docs + [with_rec])
    check("recsys vacuous-degraded-count fails", not r["ok"] and any(
        x["status"] == "regression"
        and "vacuous window" in x.get("reason", "")
        for x in r["legs"]))
    dead_cache = json.loads(json.dumps(with_rec))
    dead_cache["legs"]["wide_deep_recsys"]["hit_rate"]["hot"] = 0.3
    r = compare_bench(dead_cache, docs + [with_rec])
    check("recsys dead-hot-row-cache fails", not r["ok"] and any(
        x["status"] == "regression"
        and "hot-row hit rate" in x.get("reason", "")
        for x in r["legs"]))
    vac_hit = json.loads(json.dumps(with_rec))
    vac_hit["legs"]["wide_deep_recsys"]["hit_rate"]["hot"] = None
    r = compare_bench(vac_hit, docs + [with_rec])
    check("recsys vacuous-hit-rate fails", not r["ok"] and any(
        x["status"] == "regression"
        and "never probed" in x.get("reason", "") for x in r["legs"]))
    # chaos embedding pin-leak rule rides the chaos leg's counters
    # (synthetic leg: no checked-in capture carries one yet)
    chaos_rec = json.loads(json.dumps(latest))
    chaos_rec.setdefault("legs", {})["chaos"] = {
        "metric": "chaos_availability_pct", "value": 100.0,
        "unit": "percent", "device_kind": "cpu",
        "stats": {"rounds": 1, "median": 100.0, "p10": 100.0,
                  "p90": 100.0, "min": 100.0, "max": 100.0},
        "collateral_failures": 0, "poison_leaks": 0,
        "leaked_rows": 2,
    }
    r = compare_bench(chaos_rec, docs + [chaos_rec])
    check("chaos leaked-rows fails", not r["ok"] and any(
        x["status"] == "regression"
        and "pinned after" in x.get("reason", "")
        for x in r["legs"]))

    # sharded-serving leg (synthetic capable-host fixture: the 2-core
    # CI sim flags its own captures anomalous, so the >=2x dp contract
    # is proven here on fixture numbers): generic noise gate + the
    # speedup-vs-single floor + the p99 rule + the bit-exactness rule
    sharded_leg = {
        "metric": "sharded_serving_dp4_closed_loop_qps",
        "value": 4000.0, "unit": "requests/sec", "device_kind": "cpu",
        "n_devices": 8,
        "stats": {"rounds": 3, "median": 4000.0, "p10": 3800.0,
                  "p90": 4200.0, "min": 3750.0, "max": 4250.0},
        "p99_ms": 14.0, "single_qps": 1540.0, "single_p99_ms": 15.0,
        "speedup_vs_single": 2.6, "p99_vs_single": 0.93,
        "mp2_bit_exact": True,
    }
    with_sharded = json.loads(json.dumps(latest))
    with_sharded.setdefault("legs", {})["sharded_serving"] = sharded_leg
    r = compare_bench(with_sharded, docs + [with_sharded])
    check("sharded self-compare passes", r["ok"],
          json.dumps([x for x in r["legs"]
                      if x["status"] == "regression"]))
    r = compare_bench(_degrade(with_sharded, 0.70),
                      docs + [with_sharded])
    check("sharded 30%-degraded fails", not r["ok"])
    collapsed = json.loads(json.dumps(with_sharded))
    collapsed["legs"]["sharded_serving"]["speedup_vs_single"] = 1.4
    r = compare_bench(collapsed, docs + [with_sharded])
    check("sharded dp-speedup-collapse fails", not r["ok"] and any(
        x["status"] == "regression"
        and "speedup_vs_single" in x.get("reason", "")
        for x in r["legs"]))
    worse_p99 = json.loads(json.dumps(with_sharded))
    worse_p99["legs"]["sharded_serving"]["p99_vs_single"] = 1.8
    r = compare_bench(worse_p99, docs + [with_sharded])
    check("sharded worse-p99 fails", not r["ok"] and any(
        x["status"] == "regression" and "p99" in x.get("reason", "")
        for x in r["legs"]))
    inexact = json.loads(json.dumps(with_sharded))
    inexact["legs"]["sharded_serving"]["mp2_bit_exact"] = False
    # an anomaly flag must NOT shield a bit-exactness break
    inexact["legs"]["sharded_serving"]["anomaly"] = "core-bound host"
    r = compare_bench(inexact, docs + [with_sharded])
    check("sharded bit-exactness-break fails", not r["ok"] and any(
        x["status"] == "regression"
        and "bit-exact" in x.get("reason", "") for x in r["legs"]))
    # ...nor must an anomalous BASELINE (e.g. every capture from a
    # core-bound CI host) or a device-kind mismatch shield it
    anom_base = json.loads(json.dumps(with_sharded))
    anom_base["legs"]["sharded_serving"]["anomaly"] = "core-bound host"
    r = compare_bench(inexact, docs + [anom_base])
    check("sharded bit-exactness-break fails past anomalous baseline",
          not r["ok"])
    other_kind = json.loads(json.dumps(inexact))
    other_kind["legs"]["sharded_serving"]["device_kind"] = "TPU v9000"
    r = compare_bench(other_kind, docs + [with_sharded])
    check("sharded bit-exactness-break fails past device mismatch",
          not r["ok"])
    core_bound = json.loads(json.dumps(with_sharded))
    core_bound["legs"]["sharded_serving"]["anomaly"] = \
        "host has 2 cores for a 8-virtual-device CPU sim"
    core_bound["legs"]["sharded_serving"]["speedup_vs_single"] = 1.2
    r = compare_bench(core_bound, docs + [with_sharded])
    check("sharded core-bound capture skips", r["ok"] and any(
        x["leg"] == "sharded_serving" and x["status"] == "skipped"
        for x in r["legs"]))

    # router leg (synthetic capable-host fixture, like the sharded
    # one: the 2-core CI host flags its own captures anomalous, so the
    # >=2x-at-4-replicas and zero-rollout-failure contracts are proven
    # on fixture numbers): generic noise gate + the speedup_4v1 floor
    # + the rollout-failure rule (which no anomaly/mismatch shields)
    router_leg = {
        "metric": "router_fleet4_closed_loop_qps",
        "value": 3600.0, "unit": "requests/sec", "device_kind": "cpu",
        "stats": {"rounds": 3, "median": 3600.0, "p10": 3450.0,
                  "p90": 3750.0, "min": 3400.0, "max": 3800.0},
        "p99_ms": 16.0, "direct_qps": 1000.0, "direct_p99_ms": 15.0,
        "qps_by_replicas": {"1": 950.0, "2": 1880.0, "4": 3600.0},
        "speedup_4v1": 3.79, "p99_vs_direct": 1.07,
        "rollout": {"requests": 600, "ok": 588, "shed": 12,
                    "failed": 0, "rollout_s": 9.5},
    }
    with_router = json.loads(json.dumps(latest))
    with_router.setdefault("legs", {})["router"] = router_leg
    r = compare_bench(with_router, docs + [with_router])
    check("router self-compare passes", r["ok"],
          json.dumps([x for x in r["legs"]
                      if x["status"] == "regression"]))
    r = compare_bench(_degrade(with_router, 0.70), docs + [with_router])
    check("router 30%-degraded fails", not r["ok"])
    collapsed = json.loads(json.dumps(with_router))
    collapsed["legs"]["router"]["speedup_4v1"] = 1.5
    r = compare_bench(collapsed, docs + [with_router])
    check("router scaling-collapse fails", not r["ok"] and any(
        x["status"] == "regression"
        and "speedup_4v1" in x.get("reason", "") for x in r["legs"]))
    broken_rollout = json.loads(json.dumps(with_router))
    broken_rollout["legs"]["router"]["rollout"]["failed"] = 3
    # an anomaly flag must NOT shield a rollout-availability break
    broken_rollout["legs"]["router"]["anomaly"] = "core-bound host"
    r = compare_bench(broken_rollout, docs + [with_router])
    check("router rollout-failure fails", not r["ok"] and any(
        x["status"] == "regression"
        and "rolling restart" in x.get("reason", "")
        for x in r["legs"]))
    anom_router_base = json.loads(json.dumps(with_router))
    anom_router_base["legs"]["router"]["anomaly"] = "core-bound host"
    r = compare_bench(broken_rollout, docs + [anom_router_base])
    check("router rollout-failure fails past anomalous baseline",
          not r["ok"])
    vacuous = json.loads(json.dumps(with_router))
    vacuous["legs"]["router"]["rollout"] = {
        "requests": None, "ok": None, "shed": None, "failed": None,
        "error": "rollout traffic produced no report"}
    r = compare_bench(vacuous, docs + [with_router])
    check("router vacuous-rollout fails", not r["ok"] and any(
        x["status"] == "regression"
        and "no measured failure count" in x.get("reason", "")
        for x in r["legs"]))
    core_bound_router = json.loads(json.dumps(with_router))
    core_bound_router["legs"]["router"]["anomaly"] = \
        "host has 2 cores for 4 replica processes"
    core_bound_router["legs"]["router"]["speedup_4v1"] = 1.1
    r = compare_bench(core_bound_router, docs + [with_router])
    check("router core-bound capture skips", r["ok"] and any(
        x["leg"] == "router" and x["status"] == "skipped"
        for x in r["legs"]))

    # chaos leg (synthetic fixture like the router/sharded ones): the
    # generic noise gate applies, plus the collateral-failures /
    # poison-leak hard rules (which no anomaly or device mismatch
    # shields) and the availability floor (which the anomaly skip DOES
    # shield — core contention honestly slows recovery windows)
    chaos_leg = {
        "metric": "chaos_availability_pct",
        "value": 99.8, "unit": "%", "device_kind": "cpu",
        "stats": {"rounds": 1, "median": 99.8, "p10": 99.6,
                  "p90": 100.0, "min": 99.6, "max": 100.0},
        "availability_floor": 99.0,
        "collateral_failures": 0, "injected_failures": 9,
        "poison_leaks": 0, "p99_under_fault_ms": 45.0,
        "unexplained_deaths": 0,
        "usage_conservation_delta": 0,
        "hog_attribution_ratio": 0.97,
        "sketch_violations": 0,
        "requests": 960,
    }
    with_chaos = json.loads(json.dumps(latest))
    with_chaos.setdefault("legs", {})["chaos"] = chaos_leg
    r = compare_bench(with_chaos, docs + [with_chaos])
    check("chaos self-compare passes", r["ok"],
          json.dumps([x for x in r["legs"]
                      if x["status"] == "regression"]))
    collateral = json.loads(json.dumps(with_chaos))
    collateral["legs"]["chaos"]["collateral_failures"] = 1
    # an anomaly flag must NOT shield a containment break
    collateral["legs"]["chaos"]["anomaly"] = "core-bound host"
    r = compare_bench(collateral, docs + [with_chaos])
    check("chaos collateral-failure fails", not r["ok"] and any(
        x["status"] == "regression"
        and "collateral" in x.get("reason", "") for x in r["legs"]))
    anom_chaos_base = json.loads(json.dumps(with_chaos))
    anom_chaos_base["legs"]["chaos"]["anomaly"] = "core-bound host"
    r = compare_bench(collateral, docs + [anom_chaos_base])
    check("chaos collateral-failure fails past anomalous baseline",
          not r["ok"])
    vacuous_chaos = json.loads(json.dumps(with_chaos))
    vacuous_chaos["legs"]["chaos"]["collateral_failures"] = None
    r = compare_bench(vacuous_chaos, docs + [with_chaos])
    check("chaos vacuous-collateral fails", not r["ok"] and any(
        x["status"] == "regression"
        and "vacuous" in x.get("reason", "") for x in r["legs"]))
    leaked = json.loads(json.dumps(with_chaos))
    leaked["legs"]["chaos"]["poison_leaks"] = 2
    r = compare_bench(leaked, docs + [with_chaos])
    check("chaos poison-leak fails", not r["ok"] and any(
        x["status"] == "regression"
        and "poison" in x.get("reason", "") for x in r["legs"]))
    no_leak_field = json.loads(json.dumps(with_chaos))
    del no_leak_field["legs"]["chaos"]["poison_leaks"]
    r = compare_bench(no_leak_field, docs + [with_chaos])
    check("chaos missing-leak-count fails", not r["ok"] and any(
        x["status"] == "regression"
        and "poison-leak" in x.get("reason", "") for x in r["legs"]))
    page_leak = json.loads(json.dumps(with_chaos))
    page_leak["legs"]["chaos"]["leaked_pages"] = 3
    page_leak["legs"]["chaos"]["anomaly"] = "core-bound host"
    r = compare_bench(page_leak, docs + [with_chaos])
    check("chaos leaked-pages fails even when anomalous",
          not r["ok"] and any(
              x["status"] == "regression"
              and "refcount leak" in x.get("reason", "")
              for x in r["legs"]))
    alert_err = json.loads(json.dumps(with_chaos))
    alert_err["legs"]["chaos"]["alert_errors"] = 2
    alert_err["legs"]["chaos"]["anomaly"] = "core-bound host"
    r = compare_bench(alert_err, docs + [with_chaos])
    check("chaos alert-contract violation fails even when anomalous",
          not r["ok"] and any(
              x["status"] == "regression"
              and "burn-rate" in x.get("reason", "")
              for x in r["legs"]))
    unexplained = json.loads(json.dumps(with_chaos))
    unexplained["legs"]["chaos"]["unexplained_deaths"] = 1
    # forensics is a containment contract: no anomaly flag shields it
    unexplained["legs"]["chaos"]["anomaly"] = "core-bound host"
    r = compare_bench(unexplained, docs + [with_chaos])
    check("chaos unexplained-death fails even when anomalous",
          not r["ok"] and any(
              x["status"] == "regression"
              and "unexplained" in x.get("reason", "")
              for x in r["legs"]))
    vacuous_deaths = json.loads(json.dumps(with_chaos))
    vacuous_deaths["legs"]["chaos"]["unexplained_deaths"] = None
    r = compare_bench(vacuous_deaths, docs + [with_chaos])
    check("chaos vacuous-forensics fails", not r["ok"] and any(
        x["status"] == "regression"
        and "vacuous forensics" in x.get("reason", "")
        for x in r["legs"]))
    # usage-observatory hard rules: conservation hard-zeroes (and a
    # vacuous None fails), the hog attribution ratio has a 0.9 floor,
    # and the sketch memory bound hard-zeroes — none shielded by an
    # anomaly flag (attribution is a correctness contract, not perf)
    unconserved = json.loads(json.dumps(with_chaos))
    unconserved["legs"]["chaos"]["usage_conservation_delta"] = 3
    unconserved["legs"]["chaos"]["anomaly"] = "core-bound host"
    r = compare_bench(unconserved, docs + [with_chaos])
    check("chaos usage-conservation break fails even when anomalous",
          not r["ok"] and any(
              x["status"] == "regression"
              and "conserve" in x.get("reason", "")
              for x in r["legs"]))
    vacuous_usage = json.loads(json.dumps(with_chaos))
    vacuous_usage["legs"]["chaos"]["usage_conservation_delta"] = None
    r = compare_bench(vacuous_usage, docs + [with_chaos])
    check("chaos vacuous usage-conservation fails",
          not r["ok"] and any(
              x["status"] == "regression"
              and "vacuous" in x.get("reason", "")
              and "attribution" in x.get("reason", "")
              for x in r["legs"]))
    misattributed = json.loads(json.dumps(with_chaos))
    misattributed["legs"]["chaos"]["hog_attribution_ratio"] = 0.4
    r = compare_bench(misattributed, docs + [with_chaos])
    check("chaos hog-attribution floor fails", not r["ok"] and any(
        x["status"] == "regression"
        and "0.9 floor" in x.get("reason", "") for x in r["legs"]))
    vacuous_attr = json.loads(json.dumps(with_chaos))
    vacuous_attr["legs"]["chaos"]["hog_attribution_ratio"] = None
    r = compare_bench(vacuous_attr, docs + [with_chaos])
    check("chaos vacuous hog-attribution fails", not r["ok"] and any(
        x["status"] == "regression"
        and "never attributed" in x.get("reason", "")
        for x in r["legs"]))
    sketch_burst = json.loads(json.dumps(with_chaos))
    sketch_burst["legs"]["chaos"]["sketch_violations"] = 2
    r = compare_bench(sketch_burst, docs + [with_chaos])
    check("chaos sketch-bound violation fails", not r["ok"] and any(
        x["status"] == "regression"
        and "sketch" in x.get("reason", "") for x in r["legs"]))
    harness_err = json.loads(json.dumps(with_chaos))
    harness_err["legs"]["chaos"]["harness_ok"] = False
    harness_err["legs"]["chaos"]["errors"] = {
        "hang": "liveness watchdog never SIGKILLed the hung replica"}
    harness_err["legs"]["chaos"]["anomaly"] = "core-bound host"
    r = compare_bench(harness_err, docs + [with_chaos])
    check("chaos harness-error fails even when anomalous",
          not r["ok"] and any(
              x["status"] == "regression"
              and "harness" in x.get("reason", "") for x in r["legs"]))
    low_avail = json.loads(json.dumps(with_chaos))
    low_avail["legs"]["chaos"]["value"] = 98.2
    low_avail["legs"]["chaos"]["stats"] = {
        "rounds": 1, "median": 98.2, "p10": 98.0, "p90": 98.4}
    r = compare_bench(low_avail, docs + [with_chaos])
    check("chaos availability-floor fails", not r["ok"] and any(
        x["status"] == "regression"
        and "budget" in x.get("reason", "") for x in r["legs"]))
    low_avail_anom = json.loads(json.dumps(low_avail))
    low_avail_anom["legs"]["chaos"]["anomaly"] = "core-bound host"
    r = compare_bench(low_avail_anom, docs + [with_chaos])
    check("chaos core-bound low availability skips", r["ok"] and any(
        x["leg"] == "chaos" and x["status"] == "skipped"
        for x in r["legs"]))

    # rollout leg (synthetic fixture like the chaos one): generic
    # noise gate + the torn-version / false-revert / revert-latency
    # hard rules, which no anomaly flag or device mismatch shields
    rollout_leg = {
        "metric": "rollout_availability_pct",
        "value": 99.9, "unit": "%", "device_kind": "cpu",
        "stats": {"rounds": 1, "median": 99.9, "p10": 99.7,
                  "p90": 100.0, "min": 99.7, "max": 100.0},
        "availability_floor": 99.0,
        "rollout": {"failed": 0, "torn_responses": 0,
                    "swaps": 3, "converged": True},
        "canary": {"false_reverts": 0, "reverts": 1,
                   "revert_latency_s": 0.8,
                   "revert_latency_bound_s": 6.0,
                   "promotions": 1},
    }
    with_rollout = json.loads(json.dumps(latest))
    with_rollout.setdefault("legs", {})["rollout"] = rollout_leg
    r = compare_bench(with_rollout, docs + [with_rollout])
    check("rollout self-compare passes", r["ok"],
          json.dumps([x for x in r["legs"]
                      if x["status"] == "regression"]))
    torn = json.loads(json.dumps(with_rollout))
    torn["legs"]["rollout"]["rollout"]["torn_responses"] = 1
    torn["legs"]["rollout"]["anomaly"] = "core-bound host"
    r = compare_bench(torn, docs + [with_rollout])
    check("rollout torn-version fails even when anomalous",
          not r["ok"] and any(
              x["status"] == "regression"
              and "torn-version" in x.get("reason", "")
              for x in r["legs"]))
    no_torn = json.loads(json.dumps(with_rollout))
    del no_torn["legs"]["rollout"]["rollout"]["torn_responses"]
    r = compare_bench(no_torn, docs + [with_rollout])
    check("rollout missing-torn-count fails", not r["ok"] and any(
        x["status"] == "regression"
        and "vacuous" in x.get("reason", "") for x in r["legs"]))
    false_rev = json.loads(json.dumps(with_rollout))
    false_rev["legs"]["rollout"]["canary"]["false_reverts"] = 1
    false_rev["legs"]["rollout"]["anomaly"] = "core-bound host"
    r = compare_bench(false_rev, docs + [with_rollout])
    check("canary false-revert fails even when anomalous",
          not r["ok"] and any(
              x["status"] == "regression"
              and "false positive" in x.get("reason", "")
              for x in r["legs"]))
    vac_canary = json.loads(json.dumps(with_rollout))
    vac_canary["legs"]["rollout"]["canary"]["false_reverts"] = None
    r = compare_bench(vac_canary, docs + [with_rollout])
    check("canary vacuous-soak fails", not r["ok"] and any(
        x["status"] == "regression"
        and "vacuous soak" in x.get("reason", "") for x in r["legs"]))
    slow_rev = json.loads(json.dumps(with_rollout))
    slow_rev["legs"]["rollout"]["canary"]["revert_latency_s"] = 9.5
    r = compare_bench(slow_rev, docs + [with_rollout])
    check("canary slow-revert fails", not r["ok"] and any(
        x["status"] == "regression"
        and "too slow" in x.get("reason", "") for x in r["legs"]))
    unmeasured_rev = json.loads(json.dumps(with_rollout))
    unmeasured_rev["legs"]["rollout"]["canary"]["revert_latency_s"] \
        = None
    r = compare_bench(unmeasured_rev, docs + [with_rollout])
    check("canary unmeasured-revert fails", not r["ok"] and any(
        x["status"] == "regression"
        and "unmeasured" in x.get("reason", "") for x in r["legs"]))

    # op gate on its own committed baseline
    op_base_path = os.path.join(REPO, "tools", "op_bench_baseline.json")
    with open(op_base_path, encoding="utf-8") as f:
        op_base = json.load(f)
    check("op self-compare passes", compare_ops(op_base, op_base)["ok"])
    op_bad = json.loads(json.dumps(op_base))
    first = sorted(op_bad["ops"])[0]
    op_bad["ops"][first] *= 2.0
    check("op 2x-regression fails",
          not compare_ops(op_bad, op_base)["ok"])
    op_missing = json.loads(json.dumps(op_base))
    op_missing["ops"].pop(first)
    check("op newly-failing fails",
          not compare_ops(op_missing, op_base)["ok"])
    op_other = json.loads(json.dumps(op_base))
    op_other["device_kind"] = "TPU v9000"
    check("op device-mismatch skips",
          compare_ops(op_other, op_base).get("skipped") is True)

    failed = [c for c in checks if not c[1]]
    for name, okay, detail in checks:
        print(f"  [{'ok' if okay else 'FAIL'}] {name}"
              + (f" -- {detail}" if detail and not okay else ""))
    print(f"smoke: {len(checks) - len(failed)}/{len(checks)} gate-logic "
          f"checks passed")
    return 1 if failed else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--report", help="fresh bench.py JSON report")
    ap.add_argument("--baseline", action="append", default=[],
                    help="baseline BENCH_r*.json (repeatable, "
                         "oldest->newest; last match per leg wins)")
    ap.add_argument("--op-report", help="fresh tools/op_bench.py JSON")
    ap.add_argument("--op-baseline",
                    default=os.path.join(REPO, "tools",
                                         "op_bench_baseline.json"))
    ap.add_argument("--floor-tol", type=float, default=FLOOR_TOL,
                    help="minimum relative tolerance (cross-run chip "
                         "drift floor; default 0.10)")
    ap.add_argument("--op-threshold", type=float, default=OP_THRESHOLD)
    ap.add_argument("--json", action="store_true",
                    help="emit the full verdict as JSON on stdout")
    ap.add_argument("--smoke", action="store_true",
                    help="self-test the gate logic on committed "
                         "fixtures and exit (no benchmark run)")
    args = ap.parse_args(argv)

    if args.smoke:
        return run_smoke()
    if not args.report and not args.op_report:
        ap.error("need --report and/or --op-report (or --smoke)")

    verdict = {"ok": True}
    if args.report:
        if not args.baseline:
            ap.error("--report needs at least one --baseline")
        bench = compare_bench(load_report(args.report),
                              [load_report(p) for p in args.baseline],
                              args.floor_tol)
        verdict["bench"] = bench
        verdict["ok"] &= bench["ok"]
    if args.op_report:
        with open(args.op_report, encoding="utf-8") as f:
            new_ops = json.load(f)
        with open(args.op_baseline, encoding="utf-8") as f:
            base_ops = json.load(f)
        ops = compare_ops(new_ops, base_ops, args.op_threshold)
        verdict["ops"] = ops
        verdict["ok"] &= ops["ok"]

    if args.json:
        print(json.dumps(verdict, indent=1, sort_keys=True))
    else:
        for leg in (verdict.get("bench") or {}).get("legs", []):
            line = f"  {leg['leg']:12s} {leg['status']:10s}"
            if "new_median" in leg and "base_median" in leg:
                line += (f" new {leg['new_median']:>10} vs base "
                         f"{leg['base_median']:>10} "
                         f"(tol {leg.get('tolerance')})")
            if "reason" in leg:
                line += f" -- {leg['reason']}"
            print(line)
        ops = verdict.get("ops")
        if ops:
            if ops.get("skipped"):
                print(f"  ops: SKIP -- {ops['reason']}")
            else:
                for r in ops.get("regressions", []):
                    print(f"  op {r['op']}: {r['ratio']}x "
                          f"({r['base_us']} -> {r['new_us']} us) "
                          f"<< REGRESSION")
                if ops.get("missing"):
                    print(f"  ops newly failing: {ops['missing']}")
        print("GATE " + ("PASSED" if verdict["ok"] else "FAILED"))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
