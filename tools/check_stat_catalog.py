#!/usr/bin/env python
"""Lint: every metric name used in paddle_tpu/ must be documented.

Counters, gauges, and histograms are only useful if an operator can
find out what they mean — and names drift silently: a renamed stat
breaks every dashboard reading the old one with no test failing.  This
gate extracts every *literal* metric name passed to the monitor /
telemetry APIs and requires each to appear (backtick-quoted) in the
README's stat catalog ("Observability" section).

Recognized call shapes (first argument must be a string literal;
dynamic f-string names like ``fault_<site>_<kind>`` are out of scope):

* bare calls:      ``stat_add(n)``, ``stat_get(n)``, ``gauge_set(n, v)``,
                   ``histogram_observe(n, v)``
* monitor handles: ``monitor.get(n)`` / ``_monitor.get(n)``
* telemetry attrs: ``telemetry.gauge_set/histogram_observe/timer(n)``
* registry attrs:  ``metrics.gauge/histogram/timer(n)``

This tool also owns the strict Prometheus text-exposition validator
(:func:`validate_exposition`): the serving ``/metrics`` endpoint and
the ``metrics.prom`` textfile claim the format, so tier-1
(``tests/test_lint.py``) scrapes a live ``/metrics`` response and
fails the build on any violation — missing/duplicated ``# HELP`` /
``# TYPE`` lines, bad metric-name charset, malformed samples, or
duplicate series.

Usage: python tools/check_stat_catalog.py [--readme README.md] [--list]
       [--validate-prom FILE]  [root ...]   (default root: paddle_tpu)
"""
from __future__ import annotations

import argparse
import ast
import os
import re
import sys

BARE_FUNCS = {"stat_add", "stat_get", "gauge_set", "histogram_observe"}
TELEMETRY_ATTRS = {"gauge_set", "histogram_observe", "timer"}
REGISTRY_ATTRS = {"gauge", "histogram", "timer"}


def _first_str_arg(node: ast.Call):
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def _value_id(node) -> str:
    """Best-effort identifier of an attribute's object ('telemetry',
    '_monitor', 'self._metrics' -> '_metrics', ...)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def extract_names(path: str):
    """(name, path, lineno) for every literal metric name in one file."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, path)
    except SyntaxError as e:
        raise SystemExit(f"{path}:{e.lineno}: syntax error: {e.msg}")
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        hit = False
        if isinstance(func, ast.Name) and func.id in BARE_FUNCS:
            hit = True
        elif isinstance(func, ast.Attribute):
            # exact-id match (modulo leading underscores for module
            # aliases like `_monitor`): a substring match would drag in
            # ordinary dict .get() calls on unrelated names
            vid = _value_id(func.value).lstrip("_")
            if func.attr == "get" and vid == "monitor":
                hit = True
            elif func.attr in TELEMETRY_ATTRS and vid == "telemetry":
                hit = True
            elif func.attr in REGISTRY_ATTRS and vid == "metrics":
                hit = True
        if not hit:
            continue
        name = _first_str_arg(node)
        if name is not None:
            out.append((name, path, node.lineno))
    return out


# ---------------------------------------------------------------------------
# strict Prometheus text-exposition validation
# ---------------------------------------------------------------------------

PROM_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
PROM_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"           # metric name
    r"(\{[^{}]*\})?"                          # optional {labels}
    r" (-?(?:[0-9.eE+-]+|\+?Inf|-Inf|NaN))"   # value (one space before)
    r"( [0-9]+)?$")                           # optional ms timestamp
_LABELS_RE = re.compile(
    r'^\{(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(?:,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*,?)?\}$')


def _family_of(name: str, typed: dict) -> str:
    """Map a histogram/summary component sample back to its family
    (``x_bucket``/``x_sum``/``x_count`` -> ``x`` when ``x`` is typed
    histogram or summary)."""
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if typed.get(base) in ("histogram", "summary"):
                return base
    return name


def validate_exposition(text: str):
    """Strictly validate Prometheus text exposition format.  Returns a
    list of ``"line N: problem"`` strings (empty = valid).

    Enforced: every non-comment line is a well-formed sample
    (``name{labels} value [timestamp]``); metric names match the
    Prometheus charset; every sample's family carries ``# HELP`` and
    ``# TYPE`` lines that PRECEDE its samples; at most one HELP/TYPE
    per family; TYPE values are real Prometheus types; no duplicate
    series (same name + label set); histogram families expose
    ``_bucket``/``_sum``/``_count`` with a ``+Inf`` bucket."""
    errors = []
    helped: dict = {}
    typed: dict = {}
    sampled_families = set()
    seen_series = {}
    bucket_infs = {}

    for lineno, line in enumerate(text.splitlines(), 1):
        def err(msg):
            errors.append(f"line {lineno}: {msg} -- {line[:80]!r}")

        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            kind = parts[1] if len(parts) > 1 else ""
            if kind not in ("HELP", "TYPE"):
                continue  # free-form comment: allowed
            if len(parts) < 3:
                err(f"{kind} line without a metric name")
                continue
            name = parts[2]
            if not PROM_NAME_RE.match(name):
                err(f"bad metric name {name!r} in {kind} line")
                continue
            book = helped if kind == "HELP" else typed
            if name in book:
                err(f"duplicate # {kind} for {name}")
            if kind == "HELP":
                if len(parts) < 4 or not parts[3].strip():
                    err(f"HELP for {name} has empty docstring")
                helped.setdefault(name, lineno)
            else:
                t = parts[3].strip() if len(parts) > 3 else ""
                if t not in PROM_TYPES:
                    err(f"TYPE for {name} is {t!r}, not one of "
                        f"{sorted(PROM_TYPES)}")
                typed.setdefault(name, t)
                if name in sampled_families:
                    err(f"# TYPE for {name} appears after its samples")
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            err("malformed sample line (want 'name{labels} value "
                "[timestamp]', single spaces)")
            continue
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        if labels and not _LABELS_RE.match(labels):
            err(f"malformed label set {labels!r}")
        try:
            float(value.replace("Inf", "inf").replace("NaN", "nan"))
        except ValueError:
            err(f"unparseable sample value {value!r}")
        series = (name, labels)
        if series in seen_series:
            err(f"duplicate series {name}{labels} (first at line "
                f"{seen_series[series]})")
        else:
            seen_series[series] = lineno
        fam = _family_of(name, typed)
        sampled_families.add(fam)
        if fam not in typed:
            err(f"sample for {name} with no preceding # TYPE {fam}")
        elif fam not in helped:
            err(f"sample for {name} with no # HELP {fam}")
        if typed.get(fam) == "histogram" and name == fam + "_bucket":
            if 'le="+Inf"' in labels:
                bucket_infs[fam] = True
            bucket_infs.setdefault(fam, False)

    for fam, has_inf in sorted(bucket_infs.items()):
        if not has_inf:
            errors.append(f"histogram {fam} has no le=\"+Inf\" bucket")
    for fam in sorted(f for f, t in typed.items() if t == "histogram"):
        if fam in sampled_families:
            for part in ("_sum", "_count"):
                if (fam + part, "") not in seen_series:
                    errors.append(f"histogram {fam} is missing "
                                  f"{fam}{part}")
    return errors


CATALOG_MARKER = "**Stat catalog**"


def catalog_names(readme_path: str) -> set:
    """Backtick-quoted identifiers in the README's stat-catalog section
    (from the CATALOG_MARKER to the next `## ` heading).  Scoping to
    the catalog matters: a metric name that happens to collide with any
    backticked word elsewhere in the README (a flag, a heartbeat field)
    must not pass as documented.  Falls back to the whole file when the
    marker is absent (minimal/test READMEs)."""
    with open(readme_path, encoding="utf-8") as f:
        text = f.read()
    start = text.find(CATALOG_MARKER)
    if start >= 0:
        end = text.find("\n## ", start)
        text = text[start:end if end >= 0 else len(text)]
    return set(re.findall(r"`([A-Za-z_][A-Za-z0-9_]*)`", text))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("roots", nargs="*", default=None)
    ap.add_argument("--readme", default=None)
    ap.add_argument("--list", action="store_true",
                    help="print every extracted name and exit 0")
    ap.add_argument("--validate-prom", metavar="FILE",
                    help="instead of the catalog lint, strictly "
                         "validate a Prometheus text exposition file "
                         "('-' = stdin; e.g. a /metrics scrape or "
                         "metrics.prom) and exit 1 on violations")
    args = ap.parse_args(argv)
    if args.validate_prom:
        if args.validate_prom == "-":
            text = sys.stdin.read()
        else:
            with open(args.validate_prom, encoding="utf-8") as f:
                text = f.read()
        errs = validate_exposition(text)
        for e in errs:
            print(e)
        if errs:
            print(f"{len(errs)} exposition-format violation(s)")
        return 1 if errs else 0
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    roots = args.roots or [os.path.join(here, "paddle_tpu")]
    readme = args.readme or os.path.join(here, "README.md")

    found = []
    for root in roots:
        if os.path.isfile(root):
            found += extract_names(root)
            continue
        for dirpath, _dirs, files in os.walk(root):
            for name in sorted(files):
                if name.endswith(".py"):
                    found += extract_names(os.path.join(dirpath, name))
    if args.list:
        for n in sorted({n for n, _, _ in found}):
            print(n)
        return 0

    documented = catalog_names(readme)
    missing = sorted({(n, p, ln) for n, p, ln in found
                      if n not in documented})
    for n, p, ln in missing:
        print(f"{p}:{ln}: metric {n!r} is not in the README stat "
              f"catalog ({os.path.basename(readme)}) -- document it "
              f"(backtick-quoted) or rename it to a documented one")
    if missing:
        print(f"{len(missing)} undocumented metric name use(s)")
    return 1 if missing else 0


if __name__ == "__main__":
    sys.exit(main())
