#!/usr/bin/env python
"""Lint: every metric name used in paddle_tpu/ must be documented —
plus the strict Prometheus text-exposition validator.

THIN SHIM: the analysis lives in graftcheck
(``tools/graftcheck/passes/stat_catalog.py``, rule
``stat-undocumented``) — this CLI remains so existing docs/commands
keep working.  Prefer::

    python -m tools.graftcheck --rule stat-catalog

``--validate-prom`` validates a Prometheus exposition file (a
``/metrics`` scrape or ``metrics.prom``); findings carry ``file:line``
provenance in the shared graftcheck violation format.

Usage: python tools/check_stat_catalog.py [--readme README.md] [--list]
       [--validate-prom FILE]  [root ...]   (default root: paddle_tpu)
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.graftcheck import core  # noqa: E402
from tools.graftcheck.core import walk_files  # noqa: E402
from tools.graftcheck.passes import stat_catalog as _sc  # noqa: E402
from tools.graftcheck.passes.stat_catalog import (  # noqa: E402,F401
    catalog_names, extract_names, extract_names_from_tree,
    validate_exposition, validate_exposition_violations)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("roots", nargs="*", default=None)
    ap.add_argument("--readme", default=None)
    ap.add_argument("--list", action="store_true",
                    help="print every extracted name and exit 0")
    ap.add_argument("--validate-prom", metavar="FILE",
                    help="instead of the catalog lint, strictly "
                         "validate a Prometheus text exposition file "
                         "('-' = stdin; e.g. a /metrics scrape or "
                         "metrics.prom) and exit 1 on violations")
    args = ap.parse_args(argv)
    if args.validate_prom:
        if args.validate_prom == "-":
            text, src = sys.stdin.read(), "<stdin>"
        else:
            with open(args.validate_prom, encoding="utf-8") as f:
                text = f.read()
            src = args.validate_prom
        errs = validate_exposition_violations(text, src)
        for v in errs:
            print(v.render())
        if errs:
            print(f"{len(errs)} exposition-format violation(s)")
        return 1 if errs else 0
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    roots = args.roots or [os.path.join(here, "paddle_tpu")]

    if args.list:
        found = set()
        for sf in walk_files(roots):
            if sf.tree is None:
                raise SystemExit(f"{sf.path}:{sf.parse_error.lineno}: "
                                 f"syntax error: {sf.parse_error.msg}")
            found |= {n for n, _ in extract_names_from_tree(sf.tree)}
        for n in sorted(found):
            print(n)
        return 0

    # one code path with `python -m tools.graftcheck`: gc-ok/baseline
    # waivers and syntax-error handling apply identically
    if args.readme:
        _sc.README_PATH = args.readme
    try:
        report = core.run(roots=roots, rule_filter=["stat-catalog"])
    except FileNotFoundError as e:
        print(f"check_stat_catalog: {e}", file=sys.stderr)
        return 2
    for v in report.violations:
        print(v.render())
    n_rule = sum(v.rule == "stat-undocumented"
                 for v in report.violations)
    extra = len(report.violations) - n_rule
    if report.violations:
        print(f"{n_rule} undocumented metric name use(s)"
              + (f" (+{extra} other finding(s))" if extra else ""))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
