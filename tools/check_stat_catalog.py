#!/usr/bin/env python
"""Lint: every metric name used in paddle_tpu/ must be documented.

Counters, gauges, and histograms are only useful if an operator can
find out what they mean — and names drift silently: a renamed stat
breaks every dashboard reading the old one with no test failing.  This
gate extracts every *literal* metric name passed to the monitor /
telemetry APIs and requires each to appear (backtick-quoted) in the
README's stat catalog ("Observability" section).

Recognized call shapes (first argument must be a string literal;
dynamic f-string names like ``fault_<site>_<kind>`` are out of scope):

* bare calls:      ``stat_add(n)``, ``stat_get(n)``, ``gauge_set(n, v)``,
                   ``histogram_observe(n, v)``
* monitor handles: ``monitor.get(n)`` / ``_monitor.get(n)``
* telemetry attrs: ``telemetry.gauge_set/histogram_observe/timer(n)``
* registry attrs:  ``metrics.gauge/histogram/timer(n)``

Usage: python tools/check_stat_catalog.py [--readme README.md] [--list]
       [root ...]   (default root: paddle_tpu)
"""
from __future__ import annotations

import argparse
import ast
import os
import re
import sys

BARE_FUNCS = {"stat_add", "stat_get", "gauge_set", "histogram_observe"}
TELEMETRY_ATTRS = {"gauge_set", "histogram_observe", "timer"}
REGISTRY_ATTRS = {"gauge", "histogram", "timer"}


def _first_str_arg(node: ast.Call):
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def _value_id(node) -> str:
    """Best-effort identifier of an attribute's object ('telemetry',
    '_monitor', 'self._metrics' -> '_metrics', ...)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def extract_names(path: str):
    """(name, path, lineno) for every literal metric name in one file."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, path)
    except SyntaxError as e:
        raise SystemExit(f"{path}:{e.lineno}: syntax error: {e.msg}")
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        hit = False
        if isinstance(func, ast.Name) and func.id in BARE_FUNCS:
            hit = True
        elif isinstance(func, ast.Attribute):
            # exact-id match (modulo leading underscores for module
            # aliases like `_monitor`): a substring match would drag in
            # ordinary dict .get() calls on unrelated names
            vid = _value_id(func.value).lstrip("_")
            if func.attr == "get" and vid == "monitor":
                hit = True
            elif func.attr in TELEMETRY_ATTRS and vid == "telemetry":
                hit = True
            elif func.attr in REGISTRY_ATTRS and vid == "metrics":
                hit = True
        if not hit:
            continue
        name = _first_str_arg(node)
        if name is not None:
            out.append((name, path, node.lineno))
    return out


CATALOG_MARKER = "**Stat catalog**"


def catalog_names(readme_path: str) -> set:
    """Backtick-quoted identifiers in the README's stat-catalog section
    (from the CATALOG_MARKER to the next `## ` heading).  Scoping to
    the catalog matters: a metric name that happens to collide with any
    backticked word elsewhere in the README (a flag, a heartbeat field)
    must not pass as documented.  Falls back to the whole file when the
    marker is absent (minimal/test READMEs)."""
    with open(readme_path, encoding="utf-8") as f:
        text = f.read()
    start = text.find(CATALOG_MARKER)
    if start >= 0:
        end = text.find("\n## ", start)
        text = text[start:end if end >= 0 else len(text)]
    return set(re.findall(r"`([A-Za-z_][A-Za-z0-9_]*)`", text))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("roots", nargs="*", default=None)
    ap.add_argument("--readme", default=None)
    ap.add_argument("--list", action="store_true",
                    help="print every extracted name and exit 0")
    args = ap.parse_args(argv)
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    roots = args.roots or [os.path.join(here, "paddle_tpu")]
    readme = args.readme or os.path.join(here, "README.md")

    found = []
    for root in roots:
        if os.path.isfile(root):
            found += extract_names(root)
            continue
        for dirpath, _dirs, files in os.walk(root):
            for name in sorted(files):
                if name.endswith(".py"):
                    found += extract_names(os.path.join(dirpath, name))
    if args.list:
        for n in sorted({n for n, _, _ in found}):
            print(n)
        return 0

    documented = catalog_names(readme)
    missing = sorted({(n, p, ln) for n, p, ln in found
                      if n not in documented})
    for n, p, ln in missing:
        print(f"{p}:{ln}: metric {n!r} is not in the README stat "
              f"catalog ({os.path.basename(readme)}) -- document it "
              f"(backtick-quoted) or rename it to a documented one")
    if missing:
        print(f"{len(missing)} undocumented metric name use(s)")
    return 1 if missing else 0


if __name__ == "__main__":
    sys.exit(main())
