"""Attention fwd+bwd microbenchmark on the real chip.

Times one training-style attention call (value + grads wrt q,k,v) for the
pallas flash kernel vs the unfused einsum formulation, across seq lengths
and block sizes. Used to pick DEFAULT_BLOCK_Q/K and the per-seq default
impl (bench.py cites the result).
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(f, *args, iters=20):
    # fence via host readback of the scalar loss — block_until_ready is
    # not a reliable fence through the axon tunnel (bench.py discipline)
    np.asarray(f(*args)[0])  # compile + settle
    t0 = time.perf_counter()
    r = None
    for _ in range(iters):
        r = f(*args)
    np.asarray(r[0])
    return (time.perf_counter() - t0) / iters


def main():
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    B = int(os.environ.get("MB_B", "32"))
    H, D = 12, 64
    dt = jnp.bfloat16
    for S in (int(s) for s in os.environ.get("MB_SEQS", "512,1024,2048").split(",")):
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(B, H, S, D), dt)
        k = jnp.asarray(rng.randn(B, H, S, D), dt)
        v = jnp.asarray(rng.randn(B, H, S, D), dt)

        def unfused_loss(q, k, v):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (1.0 / np.sqrt(D))
            p = jax.nn.softmax(s.astype(jnp.float32), -1).astype(q.dtype)
            return jnp.einsum("bhqk,bhkd->bhqd", p, v).astype(
                jnp.float32).sum()

        g_unf = jax.jit(jax.value_and_grad(unfused_loss, (0, 1, 2)))
        t = timeit(g_unf, q, k, v)
        print(f"S={S} unfused: {t*1e3:.2f} ms")

        for bq in (128, 256, 512):
            for bk in (128, 256, 512):
                if bq > S or bk > S:
                    continue

                def floss(q, k, v, bq=bq, bk=bk):
                    return flash_attention(
                        q, k, v, False, None, bq, bk, False).astype(
                            jnp.float32).sum()

                gf = jax.jit(jax.value_and_grad(floss, (0, 1, 2)))
                try:
                    t = timeit(gf, q, k, v)
                    print(f"S={S} pallas bq={bq} bk={bk}: {t*1e3:.2f} ms")
                except Exception as e:
                    print(f"S={S} pallas bq={bq} bk={bk}: FAIL "
                          f"{type(e).__name__}")


if __name__ == "__main__":
    main()
