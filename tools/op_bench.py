"""Per-op benchmark harness (reference operators/benchmark/op_tester.cc
+ tools/check_op_benchmark_result.py).

Config-driven: each entry builds a one-op program, jits it through the
normal executor path, and times it on the current device with a host
readback fence (the repo's measurement discipline — block_until_ready is
not a reliable fence through the remote-device tunnel).

Usage:
    python tools/op_bench.py                      # run, print JSON
    python tools/op_bench.py --out results.json   # save
    python tools/check_op_bench.py results.json   # gate vs baseline

The committed baseline (tools/op_bench_baseline.json) was measured on
TPU v5 lite; the gate only compares results from the same device_kind.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np

WARMUP = 3
ITERS = 20


def spec(op_type, inputs, outputs=None, attrs=None, name=None):
    return {"name": name or op_type, "op": op_type, "inputs": inputs,
            "outputs": outputs or {"Out": 1}, "attrs": attrs or {}}


def _rand(shape, dtype="float32", lo=None, hi=None, seed=0):
    rng = np.random.RandomState(seed)
    if dtype in ("int64", "int32"):
        return rng.randint(lo or 0, hi or 100, shape).astype(dtype)
    x = rng.randn(*shape).astype(dtype)
    if lo is not None:
        x = np.clip(x, lo, hi)
    return x


# the top-50 hot ops of the flagship models (BERT/ResNet/seq2seq):
# matmuls, convs, norms, elementwise chains, reductions, embeddings,
# attention, optimizer update, dropout, losses
B, S, H = 32, 128, 768
CONFIGS = [
    spec("matmul", {"X": _rand((B * S, H)), "Y": _rand((H, H), seed=1)}),
    spec("matmul", {"X": _rand((B * S, H)),
                    "Y": _rand((H, 4 * H), seed=1)}, name="matmul_ffn"),
    spec("mul", {"X": _rand((B, S, H)), "Y": _rand((H, H), seed=1)},
         attrs={"x_num_col_dims": 2, "y_num_col_dims": 1}),
    spec("bmm", {"X": _rand((B * 12, S, 64)),
                 "Y": _rand((B * 12, 64, S), seed=1)}),
    spec("conv2d", {"Input": _rand((B, 64, 56, 56)),
                    "Filter": _rand((64, 64, 3, 3), seed=1)},
         outputs={"Output": 1},
         attrs={"strides": [1, 1], "paddings": [1, 1],
                "dilations": [1, 1], "groups": 1,
                "data_format": "NCHW"}),
    spec("conv3d", {"Input": _rand((4, 16, 8, 28, 28)),
                    "Filter": _rand((32, 16, 3, 3, 3), seed=1)},
         outputs={"Output": 1},
         attrs={"strides": [1, 1, 1], "paddings": [1, 1, 1]}),
    spec("pool2d", {"X": _rand((B, 64, 56, 56))},
         attrs={"pooling_type": "max", "ksize": [2, 2],
                "strides": [2, 2], "paddings": [0, 0]}),
    spec("softmax", {"X": _rand((B * 12, S, S))}),
    spec("log_softmax", {"X": _rand((B * S, 30522 // 4))}),
    spec("layer_norm", {"X": _rand((B, S, H)),
                        "Scale": _rand((H,), seed=1),
                        "Bias": _rand((H,), seed=2)},
         outputs={"Y": 1, "Mean": 1, "Variance": 1},
         attrs={"begin_norm_axis": 2, "epsilon": 1e-5}),
    spec("batch_norm", {"X": _rand((B, 64, 56, 56)),
                        "Scale": _rand((64,), seed=1),
                        "Bias": _rand((64,), seed=2),
                        "Mean": _rand((64,), seed=3),
                        "Variance": np.abs(_rand((64,), seed=4)) + 0.5},
         outputs={"Y": 1, "MeanOut": 1, "VarianceOut": 1,
                  "SavedMean": 1, "SavedVariance": 1},
         attrs={"is_test": True, "epsilon": 1e-5}),
    spec("rms_norm", {"X": _rand((B, S, H)), "Scale": _rand((H,),
                                                            seed=1)},
         outputs={"Y": 1}),
    spec("group_norm", {"X": _rand((B, 64, 28, 28)),
                        "Scale": _rand((64,), seed=1),
                        "Bias": _rand((64,), seed=2)},
         outputs={"Y": 1, "Mean": 1, "Variance": 1},
         attrs={"groups": 8, "epsilon": 1e-5}),
    spec("dropout", {"X": _rand((B, S, H))},
         attrs={"dropout_prob": 0.1,
                "dropout_implementation": "upscale_in_train"}),
    spec("gelu", {"X": _rand((B, S, 4 * H))}),
    spec("relu", {"X": _rand((B, S, 4 * H))}),
    spec("tanh", {"X": _rand((B, S, H))}),
    spec("sigmoid", {"X": _rand((B, S, H))}),
    spec("elementwise_add", {"X": _rand((B, S, H)),
                             "Y": _rand((B, S, H), seed=1)}),
    spec("elementwise_mul", {"X": _rand((B, S, H)),
                             "Y": _rand((B, S, H), seed=1)}),
    spec("elementwise_div", {"X": _rand((B, S, H)),
                             "Y": np.abs(_rand((B, S, H), seed=1)) + 1}),
    spec("elementwise_max", {"X": _rand((B, S, H)),
                             "Y": _rand((B, S, H), seed=1)}),
    spec("reduce_sum", {"X": _rand((B, S, H))}, attrs={"dim": [2]}),
    spec("reduce_mean", {"X": _rand((B, S, H))},
         attrs={"dim": [1, 2]}),
    spec("reduce_max", {"X": _rand((B, S, H))}, attrs={"dim": [2]}),
    spec("lookup_table_v2",
         {"W": _rand((30522, H)),
          "Ids": _rand((B, S), "int64", 0, 30522, seed=1)}),
    spec("transpose2", {"X": _rand((B, S, 12, 64))},
         outputs={"Out": 1, "XShape": 1}, attrs={"axis": [0, 2, 1, 3]}),
    spec("reshape2", {"X": _rand((B, S, H))},
         outputs={"Out": 1, "XShape": 1},
         attrs={"shape": [B * S, H]}),
    spec("concat", {"X": [_rand((B, S, H)), _rand((B, S, H), seed=1)]},
         attrs={"axis": 2}),
    spec("split", {"X": _rand((B, S, H))}, outputs={"Out": 2},
         attrs={"num": 2, "axis": 2, "sections": []}),
    spec("slice", {"Input": _rand((B, S, H))},
         attrs={"axes": [1], "starts": [0], "ends": [64]}),
    spec("gather_nd", {"X": _rand((B, S, H)),
                       "Index": _rand((B, 20, 2), "int64", 0, 32,
                                      seed=1)}),
    spec("top_k", {"X": _rand((B, 30522 // 4))},
         outputs={"Out": 1, "Indices": 1}, attrs={"k": 4}),
    spec("arg_max", {"X": _rand((B * S, 30522 // 4))},
         attrs={"axis": -1}),
    spec("cast", {"X": _rand((B, S, H))},
         attrs={"out_dtype": "bfloat16"}),
    spec("scale", {"X": _rand((B, S, H))},
         attrs={"scale": 2.0, "bias": 1.0}),
    spec("sqrt", {"X": np.abs(_rand((B, S, H))) + 0.1}),
    spec("square", {"X": _rand((B, S, H))}),
    spec("clip", {"X": _rand((B, S, H))},
         attrs={"min": -1.0, "max": 1.0}),
    spec("softmax_with_cross_entropy",
         {"Logits": _rand((B * 20, 30522 // 4)),
          "Label": _rand((B * 20, 1), "int64", 0, 30522 // 4, seed=1)},
         outputs={"Softmax": 1, "Loss": 1}),
    spec("cross_entropy",
         {"X": np.abs(_rand((B * S, 100))) + 0.01,
          "Label": _rand((B * S, 1), "int64", 0, 100, seed=1)},
         outputs={"Y": 1}),
    spec("mean", {"X": _rand((B, S, H))}),
    spec("sum", {"X": [_rand((B, S, H)), _rand((B, S, H), seed=1)]}),
    spec("stack", {"X": [_rand((B, S)), _rand((B, S), seed=1)]},
         outputs={"Y": 1}, attrs={"axis": 0}),
    spec("where", {"Condition": _rand((B, S, H)) > 0,
                   "X": _rand((B, S, H), seed=1),
                   "Y": _rand((B, S, H), seed=2)}),
    spec("flash_attention_qkv", {"QKV": _rand((8, 512, 3 * H))},
         attrs={"num_heads": 12}),
    spec("sgd", {"Param": _rand((H, 4 * H)),
                 "Grad": _rand((H, 4 * H), seed=1),
                 "LearningRate": np.array([0.01], "float32")},
         outputs={"ParamOut": 1}),
    spec("adam",
         {"Param": _rand((H, 4 * H)), "Grad": _rand((H, 4 * H), seed=1),
          "Moment1": _rand((H, 4 * H), seed=2) * 0.01,
          "Moment2": np.abs(_rand((H, 4 * H), seed=3)) * 0.01,
          "LearningRate": np.array([0.001], "float32"),
          "Beta1Pow": np.array([0.9], "float32"),
          "Beta2Pow": np.array([0.999], "float32")},
         outputs={"ParamOut": 1, "Moment1Out": 1, "Moment2Out": 1,
                  "Beta1PowOut": 1, "Beta2PowOut": 1}),
    spec("linear_chain_crf",
         {"Emission": _rand((B, 64, 32)),
          "Transition": _rand((34, 32), seed=1) * 0.1,
          "Label": _rand((B, 64), "int64", 0, 32, seed=2),
          "Length": np.full((B,), 64, "int64")},
         outputs={"LogLikelihood": 1}),
    spec("warpctc",
         {"Logits": _rand((B, 64, 50)),
          "Label": _rand((B, 16), "int64", 1, 50, seed=1),
          "LogitsLength": np.full((B,), 64, "int64"),
          "LabelLength": np.full((B,), 16, "int64")},
         outputs={"Loss": 1}),
]


def bench_one(cfg):
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.framework.layer_helper import LayerHelper

    main_p, startup = pt.Program(), pt.Program()
    startup._is_startup = True
    feeds = {}
    with pt.program_guard(main_p, startup):
        in_map = {}
        for slot, arr in cfg["inputs"].items():
            arrs = arr if isinstance(arr, list) else [arr]
            vs = []
            for i, a in enumerate(arrs):
                n = f"in_{slot}_{i}"
                v = layers.data(n, list(a.shape), dtype=str(a.dtype),
                                append_batch_size=False)
                feeds[n] = a
                vs.append(v)
            in_map[slot] = vs
        h = LayerHelper(cfg["op"])
        outs = {}
        for slot, k in cfg["outputs"].items():
            outs[slot] = [h.create_variable_for_type_inference("float32")
                          for _ in range(k)]
        h.append_op(cfg["op"], inputs=in_map, outputs=outs,
                    attrs=cfg["attrs"])
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    # stage feeds on device ONCE — re-uploading through the remote
    # tunnel would swamp the op time; fence on a single element, not a
    # full fetch download
    import jax
    feeds = {n: jax.device_put(a) for n, a in feeds.items()}
    fetch = [v for vs in outs.values() for v in vs][:1]

    def fence(r):
        a = r[0]
        return np.asarray(a.ravel()[0] if hasattr(a, "ravel")
                          else a)

    for _ in range(WARMUP):
        r = exe.run(main_p, feed=feeds, fetch_list=fetch, scope=scope,
                    return_numpy=False)
    fence(r)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        r = exe.run(main_p, feed=feeds, fetch_list=fetch, scope=scope,
                    return_numpy=False)
    fence(r)
    dt = (time.perf_counter() - t0) / ITERS
    return dt * 1e6  # us


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--filter", default=None,
                    help="substring filter on config names")
    args = ap.parse_args()
    import jax
    device = jax.devices()[0]
    results = {"device_kind": getattr(device, "device_kind",
                                      str(device)),
               "iters": ITERS, "ops": {}}
    for cfg in CONFIGS:
        if args.filter and args.filter not in cfg["name"]:
            continue
        try:
            us = bench_one(cfg)
            results["ops"][cfg["name"]] = round(us, 1)
            print(f"{cfg['name']:32s} {us:10.1f} us", file=sys.stderr)
        except Exception as e:  # never let one op kill the sweep
            results["ops"][cfg["name"]] = None
            print(f"{cfg['name']:32s} FAIL {type(e).__name__}: "
                  f"{str(e)[:80]}", file=sys.stderr)
    print(json.dumps(results))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
