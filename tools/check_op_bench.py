"""Per-op perf regression gate (reference
tools/check_op_benchmark_result.py).

Compares an op_bench.py results JSON against the committed baseline and
fails (exit 1) when any op regressed by more than --threshold (default
50% — the shared v5e chip drifts +-10% between runs with byte-identical
programs, so a tight gate would flap; 1.5x catches real lowering
regressions like a fusion break or an accidental f32 fallback).

Usage:
    python tools/op_bench.py --out /tmp/r.json
    python tools/check_op_bench.py /tmp/r.json \
        [--baseline tools/op_bench_baseline.json] [--threshold 1.5]
"""
from __future__ import annotations

import argparse
import json
import sys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("results")
    ap.add_argument("--baseline", default="tools/op_bench_baseline.json")
    ap.add_argument("--threshold", type=float, default=1.5)
    args = ap.parse_args()

    res = json.load(open(args.results))
    base = json.load(open(args.baseline))
    if res.get("device_kind") != base.get("device_kind"):
        print(f"SKIP: device_kind mismatch "
              f"({res.get('device_kind')!r} vs baseline "
              f"{base.get('device_kind')!r}) — baseline only applies to "
              "its own hardware")
        return 0

    failures, improved, missing = [], [], []
    for name, b_us in base["ops"].items():
        r_us = res["ops"].get(name)
        if b_us is None:
            continue
        if r_us is None:
            missing.append(name)
            continue
        ratio = r_us / b_us
        tag = ""
        if ratio > args.threshold:
            failures.append((name, b_us, r_us, ratio))
            tag = "  << REGRESSION"
        elif ratio < 1 / args.threshold:
            improved.append(name)
        print(f"{name:32s} base {b_us:10.1f} us  now {r_us:10.1f} us "
              f"({ratio:5.2f}x){tag}")
    if missing:
        print(f"\nops that now FAIL to run: {missing}")
    if improved:
        print(f"\nimproved >{args.threshold}x: {improved} — consider "
              "refreshing the baseline")
    if failures or missing:
        print(f"\nGATE FAILED: {len(failures)} regression(s), "
              f"{len(missing)} newly-failing op(s)")
        return 1
    print("\nGATE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
