#!/usr/bin/env python
"""Export paddle_tpu telemetry as one Perfetto-loadable trace.

Merges a ``FLAGS_metrics_dir``'s artifacts into a single
chrome://tracing / Perfetto JSON file:

* ``trace.json`` — the span ring (``executor/step``, ``ckpt/write``, ...)
  exported by paddle_tpu/telemetry.py, passed through after validation;
* ``events.jsonl`` — the structured event log, converted to instant
  ('i'-phase) events so checkpoint publishes, guard skips, resumes, and
  SIGTERMs show as markers on the same timeline.

Usage::

    python tools/trace_export.py <metrics_dir | trace.json> [out.json]
        [--filter SUBSTR]     keep only spans whose name contains SUBSTR
        [--no-events]         skip the events.jsonl markers

Load the output in https://ui.perfetto.dev (or chrome://tracing).
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def load_span_events(trace_path: str) -> list:
    with open(trace_path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise SystemExit(f"{trace_path}: not a chrome trace "
                         f"(no traceEvents list)")
    bad = [e for e in events
           if not isinstance(e, dict) or "name" not in e or "ph" not in e]
    if bad:
        raise SystemExit(f"{trace_path}: {len(bad)} malformed trace "
                         f"event(s), e.g. {bad[0]!r}")
    return events


def load_event_markers(jsonl_path: str) -> list:
    """events.jsonl lines -> instant events on the merged timeline.

    Malformed lines are skipped with a warning, not fatal: a crashed
    run leaves a torn final append, and the post-mortem tool must keep
    working exactly then."""
    markers = []
    with open(jsonl_path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                print(f"warning: {jsonl_path}:{lineno}: skipping bad "
                      f"JSON line (torn write?): {e}", file=sys.stderr)
                continue
            markers.append({
                "ph": "i", "s": "p",
                "name": f"event/{rec.get('event', 'unknown')}",
                "cat": "paddle_tpu.events",
                "pid": rec.get("pid", 0), "tid": 0,
                "ts": float(rec.get("ts", 0.0)) * 1e6,
                "args": rec,
            })
    return markers


def export(src: str, out: str, name_filter: str = "",
           include_events: bool = True) -> dict:
    if os.path.isdir(src):
        trace_path = os.path.join(src, "trace.json")
        events_path = os.path.join(src, "events.jsonl")
    else:
        trace_path = src
        events_path = os.path.join(os.path.dirname(src) or ".",
                                   "events.jsonl")
    events = load_span_events(trace_path)
    if name_filter:
        events = [e for e in events if name_filter in e.get("name", "")]
    n_spans = len(events)
    n_markers = 0
    if include_events and os.path.isfile(events_path):
        markers = load_event_markers(events_path)
        n_markers = len(markers)
        events = events + markers
    events.sort(key=lambda e: e.get("ts", 0.0))
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(out, "w") as f:
        json.dump(doc, f)
    return {"out": out, "spans": n_spans, "markers": n_markers}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("src", help="FLAGS_metrics_dir or a trace.json")
    ap.add_argument("out", nargs="?", default="perfetto_trace.json")
    ap.add_argument("--filter", default="",
                    help="keep only spans whose name contains this")
    ap.add_argument("--no-events", action="store_true",
                    help="skip events.jsonl markers")
    args = ap.parse_args(argv)
    info = export(args.src, args.out, args.filter,
                  include_events=not args.no_events)
    print(f"wrote {info['out']}: {info['spans']} span(s), "
          f"{info['markers']} event marker(s) — load in "
          f"https://ui.perfetto.dev")
    return 0


if __name__ == "__main__":
    sys.exit(main())
