#!/usr/bin/env python
"""Export paddle_tpu telemetry as one Perfetto-loadable trace.

Merges one or more ``FLAGS_metrics_dir``s' artifacts into a single
chrome://tracing / Perfetto JSON file:

* ``trace.json`` — the span ring (``executor/step``, ``ckpt/write``,
  ``serving/request``, ...) exported by paddle_tpu/telemetry.py,
  passed through after validation;
* ``events.jsonl`` — the structured event log, converted to instant
  ('i'-phase) events so checkpoint publishes, guard skips, resumes, and
  SIGTERMs show as markers on the same timeline.

With repeated ``--metrics-dir`` arguments (e.g. a trainer dir and a
serving dir), each source gets its own Perfetto process track group: a
synthetic pid per source plus a ``process_name`` metadata event naming
it, so two runs' (or the same process's two subsystems') spans stay
visually separate but share one wall-clock timeline.  Spans keep their
``trace_id`` args — a serving request found in ``/tracez`` or the
access log is findable by id in the merged view.

Usage::

    python tools/trace_export.py <metrics_dir | trace.json> [out.json]
    python tools/trace_export.py --metrics-dir A --metrics-dir B [out.json]
        [--metrics-dir DIR]   source dir (repeatable; when given, a
                              lone positional arg is the OUTPUT path)
        [--filter SUBSTR]     keep only spans whose name contains SUBSTR
                              (counter tracks and metadata always pass:
                              a filtered view keeps its occupancy/HBM
                              context)
        [--no-events]         skip the events.jsonl markers

Counter tracks ride along: the span ring's 'C'-phase samples — the
HBM timeline, the generation engine's per-slot occupancy track
(``generation_slots``) — merge with the spans, re-pidded per source
like everything else, so a multi-replica fleet export shows every
replica's slot occupancy as its own stacked counter track beside its
sequence timelines (``generation/sequence`` spans, trace-id-linked to
``/tracez``).

Postmortems ride along too: a source dir's ``postmortem/*.json``
flight-recorder dumps (paddle_tpu/blackbox.py) each carry the dead
process's final span ring under ``trace_events``.  Every dead pid
becomes one more process track group — labelled with the pid and the
death reason — so a crashed replica's last seconds sit on the same
wall-clock timeline as the survivors that kept serving around it.  A
crash/exception dump supersedes the cadence ``rolling`` dump from the
same life (the crash dump is written later and contains the final
ring); a ring whose pid is already on the source's own trace.json
timeline is skipped (a live run's rolling dump mirrors its trace —
merging it would double every span); torn dumps are skipped with a
warning, never fatal.

Load the output in https://ui.perfetto.dev (or chrome://tracing).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def load_span_events(trace_path: str) -> list:
    with open(trace_path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise SystemExit(f"{trace_path}: not a chrome trace "
                         f"(no traceEvents list)")
    bad = [e for e in events
           if not isinstance(e, dict) or "name" not in e or "ph" not in e]
    if bad:
        raise SystemExit(f"{trace_path}: {len(bad)} malformed trace "
                         f"event(s), e.g. {bad[0]!r}")
    return events


def load_event_markers(jsonl_path: str) -> list:
    """events.jsonl lines -> instant events on the merged timeline.

    Malformed lines are skipped with a warning, not fatal: a crashed
    run leaves a torn final append, and the post-mortem tool must keep
    working exactly then."""
    markers = []
    with open(jsonl_path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                print(f"warning: {jsonl_path}:{lineno}: skipping bad "
                      f"JSON line (torn write?): {e}", file=sys.stderr)
                continue
            markers.append({
                "ph": "i", "s": "p",
                "name": f"event/{rec.get('event', 'unknown')}",
                "cat": "paddle_tpu.events",
                "pid": rec.get("pid", 0), "tid": 0,
                "ts": float(rec.get("ts", 0.0)) * 1e6,
                "args": rec,
            })
    return markers


def _filter_spans(events: list, name_filter: str) -> list:
    # the name filter narrows SPANS; counter tracks ('C': per-slot
    # occupancy, the HBM timeline) and metadata ('M') survive any
    # filter — a filtered view without its counter context is how
    # "the grid looked idle" misreadings happen
    if not name_filter:
        return events
    return [e for e in events
            if e.get("ph") in ("C", "M")
            or name_filter in e.get("name", "")]


def load_postmortems(pm_dir: str, name_filter: str = "",
                     exclude_pids=()) -> list:
    """``postmortem/*.json`` flight-recorder dumps -> one extra track
    group per dead pid.  Each dump carries the dead process's final
    span ring under ``trace_events``; a crash/exception dump
    supersedes the cadence ``rolling`` dump from the same life, so
    each dead pid contributes exactly one ring.  ``exclude_pids``
    drops rings whose pid is already on the source's own timeline (a
    live run's rolling dump mirrors its trace.json — merging it would
    duplicate every span).  Unreadable (torn) dumps are skipped with
    a warning — the export must keep working exactly when processes
    died mid-write."""
    by_pid = {}
    exclude = set(exclude_pids)
    for path in sorted(glob.glob(os.path.join(pm_dir, "*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"warning: {path}: skipping unreadable postmortem "
                  f"(torn write?): {e}", file=sys.stderr)
            continue
        events = doc.get("trace_events")
        if not isinstance(events, list):
            continue
        pid = doc.get("pid") or 0
        if pid in exclude:
            continue
        reason = doc.get("reason", "unknown")
        prev = by_pid.get(pid)
        if prev is not None and reason == "rolling" \
                and prev["reason"] != "rolling":
            continue
        spans = _filter_spans(
            [e for e in events if isinstance(e, dict)], name_filter)
        by_pid[pid] = {"src": path, "reason": reason,
                       "label": f"postmortem pid {pid} ({reason})",
                       "spans": spans, "markers": []}
    return [by_pid[k] for k in sorted(by_pid)]


def _load_source(src: str, name_filter: str,
                 include_events: bool) -> dict:
    """One metrics dir (or trace.json) -> its span events + markers
    (+ the dir's postmortem dumps as extra track-group parts)."""
    if os.path.isdir(src):
        trace_path = os.path.join(src, "trace.json")
        events_path = os.path.join(src, "events.jsonl")
        pm_dir = os.path.join(src, "postmortem")
    else:
        trace_path = src
        events_path = os.path.join(os.path.dirname(src) or ".",
                                   "events.jsonl")
        pm_dir = None
    raw = load_span_events(trace_path)
    events = _filter_spans(raw, name_filter)
    markers = []
    if include_events and os.path.isfile(events_path):
        markers = load_event_markers(events_path)
    postmortems = []
    if pm_dir is not None and os.path.isdir(pm_dir):
        # pids already on this source's timeline are alive (or the
        # latest life): their rolling dump would duplicate trace.json
        live_pids = {e.get("pid") for e in raw}
        postmortems = load_postmortems(pm_dir, name_filter,
                                       exclude_pids=live_pids)
    return {"src": src, "spans": events, "markers": markers,
            "postmortems": postmortems}


def export(src, out: str, name_filter: str = "",
           include_events: bool = True) -> dict:
    """``src`` is one metrics dir / trace.json, or a list of them.
    Multiple sources merge onto one wall-clock timeline with one
    Perfetto process track group per source: events are re-pidded
    (synthetic pid = 1-based source index) and a ``process_name``
    metadata event labels the group — two dirs written by the same
    real pid (one process's trainer dir and serving dir) must not
    interleave into one track.  Spans keep their ``trace_id`` args, so
    a request surfaced by ``/tracez`` or the access log is findable by
    id in the merged view."""
    srcs = [src] if isinstance(src, str) else list(src)
    if not srcs:
        raise SystemExit("no source dir given")
    loaded = [_load_source(s, name_filter, include_events) for s in srcs]
    # flatten: each source, then its dead replicas' postmortem rings as
    # extra track groups of their own
    parts, n_postmortems = [], 0
    for src_part in loaded:
        pm = src_part.pop("postmortems", [])
        parts.append(src_part)
        parts.extend(pm)
        n_postmortems += len(pm)
    events = []
    n_spans = n_markers = 0
    for i, part in enumerate(parts):
        n_spans += len(part["spans"])
        n_markers += len(part["markers"])
        if len(parts) == 1:
            events += part["spans"] + part["markers"]
            continue
        pid = i + 1
        label = part.get("label") \
            or os.path.basename(os.path.normpath(part["src"])) \
            or part["src"]
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "ts": 0.0,
                       "args": {"name": f"{label} ({part['src']})"}})
        events += [dict(e, pid=pid)
                   for e in part["spans"] + part["markers"]]
    # metadata first, then time order (Perfetto wants names early)
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0.0)))
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(out, "w") as f:
        json.dump(doc, f)
    return {"out": out, "spans": n_spans, "markers": n_markers,
            "sources": len(loaded), "postmortems": n_postmortems}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("src", nargs="?",
                    help="FLAGS_metrics_dir or a trace.json")
    ap.add_argument("out", nargs="?", default=None)
    ap.add_argument("--metrics-dir", action="append", default=[],
                    metavar="DIR", dest="metrics_dirs",
                    help="additional metrics dir to merge (repeatable; "
                         "each source gets its own process track group)")
    ap.add_argument("--filter", default="",
                    help="keep only spans whose name contains this")
    ap.add_argument("--no-events", action="store_true",
                    help="skip events.jsonl markers")
    args = ap.parse_args(argv)
    srcs, out = list(args.metrics_dirs), args.out
    if args.src:
        if srcs and out is None:
            # `trace_export.py --metrics-dir a --metrics-dir b out.json`:
            # the lone positional fills `src`, but with --metrics-dir
            # sources present it is the OUTPUT (deterministic — never
            # keyed on whether the path happens to exist, so re-running
            # the same command cannot re-ingest its own output)
            out = args.src
        else:
            srcs.insert(0, args.src)
    if not srcs:
        ap.error("give a positional src and/or --metrics-dir DIR")
    info = export(srcs if len(srcs) > 1 else srcs[0],
                  out or "perfetto_trace.json",
                  args.filter, include_events=not args.no_events)
    pm = info.get("postmortems", 0)
    pm_note = f" (+{pm} postmortem ring(s))" if pm else ""
    print(f"wrote {info['out']}: {info['spans']} span(s), "
          f"{info['markers']} event marker(s) from {info['sources']} "
          f"source(s){pm_note} — load in https://ui.perfetto.dev")
    return 0


if __name__ == "__main__":
    sys.exit(main())
