// Package paddle: Go inference client over the paddle_tpu C ABI
// (reference go/paddle/predictor.go over the C API of
// paddle/fluid/inference/capi).
//
// Build: requires cgo and libpaddle_tpu_c.so (built by
// paddle_tpu/native/embed.py) on the linker path:
//
//	CGO_LDFLAGS="-L${REPO}/paddle_tpu/native -lpaddle_tpu_c" go build
//
// NOTE: the build environment of this repo has no Go toolchain — this
// client mirrors the reference API surface 1:1 over the TESTED C ABI
// (paddle_tpu/native/capi.cc, exercised by tests/test_native_entries.py);
// compile it wherever Go is available. The exact ABI call sequence this
// file makes (allocation pattern, pt_run wrapper, two-pass PT_GetOutput
// with a long[16] shape buffer) is replayed from C in
// native/go_mirror_harness.c and CI-tested by
// tests/test_native_entries.py::test_go_client_abi_sequence, so the
// contract is exercised even without cgo.
package paddle

/*
#cgo LDFLAGS: -lpaddle_tpu_c
#include <stdlib.h>
#include "paddle_tpu_c_api.h"

// cgo cannot index C pointer arrays from Go slices of pointers directly;
// small helpers keep the hot path in C.
static int pt_run(PT_Predictor* p, const float** ins, const long** shapes,
                  const long* ndims, long n) {
    return PT_PredictorRun(p, ins, shapes, ndims, n);
}
*/
import "C"

import (
	"errors"
	"unsafe"
)

// Predictor wraps a native paddle_tpu inference session.
type Predictor struct {
	ptr *C.PT_Predictor
}

// NewPredictor loads a saved inference model directory
// (io.save_inference_model output).
func NewPredictor(modelDir string) (*Predictor, error) {
	cdir := C.CString(modelDir)
	defer C.free(unsafe.Pointer(cdir))
	p := C.PT_CreatePredictor(cdir)
	if p == nil {
		return nil, errors.New("paddle: PT_CreatePredictor failed for " + modelDir)
	}
	return &Predictor{ptr: p}, nil
}

// Delete releases the native predictor.
func (p *Predictor) Delete() {
	if p.ptr != nil {
		C.PT_DeletePredictor(p.ptr)
		p.ptr = nil
	}
}

// InputNames returns the feed names in declaration order.
func (p *Predictor) InputNames() []string {
	n := int(C.PT_GetInputNum(p.ptr))
	names := make([]string, n)
	for i := 0; i < n; i++ {
		names[i] = C.GoString(C.PT_GetInputName(p.ptr, C.long(i)))
	}
	return names
}

// OutputNames returns the fetch names in declaration order.
func (p *Predictor) OutputNames() []string {
	n := int(C.PT_GetOutputNum(p.ptr))
	names := make([]string, n)
	for i := 0; i < n; i++ {
		names[i] = C.GoString(C.PT_GetOutputName(p.ptr, C.long(i)))
	}
	return names
}

// Tensor is one dense float32 input/output.
type Tensor struct {
	Shape []int64
	Data  []float32
}

// Run feeds `inputs` (aligned with InputNames) and executes the model.
//
// All pointer arrays AND the data/shape buffers are copied into
// C-allocated memory: passing a Go pointer to memory that itself holds
// Go pointers violates the cgo pointer-passing rules (panics under the
// default cgocheck), and C-side copies also pin nothing against the GC.
func (p *Predictor) Run(inputs []Tensor) error {
	n := len(inputs)
	if n == 0 {
		return errors.New("paddle: Run needs at least one input")
	}
	ptrSz := C.size_t(unsafe.Sizeof(uintptr(0)))
	longSz := C.size_t(unsafe.Sizeof(C.long(0)))
	ins := (*[1 << 20]*C.float)(C.malloc(C.size_t(n) * ptrSz))
	shapes := (*[1 << 20]*C.long)(C.malloc(C.size_t(n) * ptrSz))
	ndims := (*[1 << 20]C.long)(C.malloc(C.size_t(n) * longSz))
	var owned []unsafe.Pointer
	defer func() {
		for _, q := range owned {
			C.free(q)
		}
		C.free(unsafe.Pointer(ins))
		C.free(unsafe.Pointer(shapes))
		C.free(unsafe.Pointer(ndims))
	}()
	for i, t := range inputs {
		nd := len(t.Shape)
		dbuf := C.malloc(C.size_t(len(t.Data)+1) * 4)
		owned = append(owned, dbuf)
		dslice := (*[1 << 28]C.float)(dbuf)
		for j, v := range t.Data {
			dslice[j] = C.float(v)
		}
		sbuf := C.malloc(C.size_t(nd+1) * longSz)
		owned = append(owned, sbuf)
		sslice := (*[64]C.long)(sbuf)
		for j, d := range t.Shape {
			sslice[j] = C.long(d)
		}
		ins[i] = &dslice[0]
		shapes[i] = &sslice[0]
		ndims[i] = C.long(nd)
	}
	rc := C.pt_run(p.ptr, (**C.float)(unsafe.Pointer(ins)),
		(**C.long)(unsafe.Pointer(shapes)), &ndims[0], C.long(n))
	if rc != 0 {
		return errors.New("paddle: PT_PredictorRun failed")
	}
	return nil
}

// GetOutput copies output i of the last Run.
func (p *Predictor) GetOutput(i int) (Tensor, error) {
	var shape [16]C.long
	var ndim C.long
	// size query pass (capacity 0 reports the element count)
	n := C.PT_GetOutput(p.ptr, C.long(i), nil, 0, &shape[0], 16, &ndim)
	if n < 0 {
		return Tensor{}, errors.New("paddle: PT_GetOutput failed")
	}
	buf := make([]float32, int(n))
	var bufP *C.float
	if n > 0 {
		bufP = (*C.float)(unsafe.Pointer(&buf[0]))
	}
	if C.PT_GetOutput(p.ptr, C.long(i), bufP, n, &shape[0], 16,
		&ndim) < 0 {
		return Tensor{}, errors.New("paddle: PT_GetOutput failed")
	}
	nd := int(ndim)
	if nd > len(shape) { // C truncates writes at max_ndim; clamp reads too
		nd = len(shape)
	}
	out := Tensor{Data: buf, Shape: make([]int64, nd)}
	for j := 0; j < nd; j++ {
		out.Shape[j] = int64(shape[j])
	}
	return out, nil
}
