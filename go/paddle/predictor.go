// Package paddle: Go inference client over the paddle_tpu C ABI
// (reference go/paddle/predictor.go over the C API of
// paddle/fluid/inference/capi).
//
// Build: requires cgo and libpaddle_tpu_c.so (built by
// paddle_tpu/native/embed.py) on the linker path:
//
//	CGO_LDFLAGS="-L${REPO}/paddle_tpu/native -lpaddle_tpu_c" go build
//
// NOTE: the build environment of this repo has no Go toolchain — this
// client mirrors the reference API surface 1:1 over the TESTED C ABI
// (paddle_tpu/native/capi.cc, exercised by tests/test_native_entries.py);
// compile it wherever Go is available.
package paddle

/*
#cgo LDFLAGS: -lpaddle_tpu_c
#include <stdlib.h>
#include "paddle_tpu_c_api.h"

// cgo cannot index C pointer arrays from Go slices of pointers directly;
// small helpers keep the hot path in C.
static int pt_run(PT_Predictor* p, const float** ins, const long** shapes,
                  const long* ndims, long n) {
    return PT_PredictorRun(p, ins, shapes, ndims, n);
}
*/
import "C"

import (
	"errors"
	"unsafe"
)

// Predictor wraps a native paddle_tpu inference session.
type Predictor struct {
	ptr *C.PT_Predictor
}

// NewPredictor loads a saved inference model directory
// (io.save_inference_model output).
func NewPredictor(modelDir string) (*Predictor, error) {
	cdir := C.CString(modelDir)
	defer C.free(unsafe.Pointer(cdir))
	p := C.PT_CreatePredictor(cdir)
	if p == nil {
		return nil, errors.New("paddle: PT_CreatePredictor failed for " + modelDir)
	}
	return &Predictor{ptr: p}, nil
}

// Delete releases the native predictor.
func (p *Predictor) Delete() {
	if p.ptr != nil {
		C.PT_DeletePredictor(p.ptr)
		p.ptr = nil
	}
}

// InputNames returns the feed names in declaration order.
func (p *Predictor) InputNames() []string {
	n := int(C.PT_GetInputNum(p.ptr))
	names := make([]string, n)
	for i := 0; i < n; i++ {
		names[i] = C.GoString(C.PT_GetInputName(p.ptr, C.long(i)))
	}
	return names
}

// OutputNames returns the fetch names in declaration order.
func (p *Predictor) OutputNames() []string {
	n := int(C.PT_GetOutputNum(p.ptr))
	names := make([]string, n)
	for i := 0; i < n; i++ {
		names[i] = C.GoString(C.PT_GetOutputName(p.ptr, C.long(i)))
	}
	return names
}

// Tensor is one dense float32 input/output.
type Tensor struct {
	Shape []int64
	Data  []float32
}

// Run feeds `inputs` (aligned with InputNames) and executes the model.
func (p *Predictor) Run(inputs []Tensor) error {
	n := len(inputs)
	ins := make([]*C.float, n)
	shapes := make([]*C.long, n)
	ndims := make([]C.long, n)
	// keep Go slices alive across the call
	pinShapes := make([][]C.long, n)
	for i, t := range inputs {
		if len(t.Data) > 0 {
			ins[i] = (*C.float)(unsafe.Pointer(&t.Data[0]))
		}
		cs := make([]C.long, len(t.Shape))
		for j, d := range t.Shape {
			cs[j] = C.long(d)
		}
		pinShapes[i] = cs
		if len(cs) > 0 {
			shapes[i] = &cs[0]
		}
		ndims[i] = C.long(len(t.Shape))
	}
	var insP **C.float
	var shapesP **C.long
	var ndimsP *C.long
	if n > 0 {
		insP = &ins[0]
		shapesP = &shapes[0]
		ndimsP = &ndims[0]
	}
	rc := C.pt_run(p.ptr, (**C.float)(unsafe.Pointer(insP)),
		(**C.long)(unsafe.Pointer(shapesP)), ndimsP, C.long(n))
	_ = pinShapes
	if rc != 0 {
		return errors.New("paddle: PT_PredictorRun failed")
	}
	return nil
}

// GetOutput copies output i of the last Run.
func (p *Predictor) GetOutput(i int) (Tensor, error) {
	var shape [16]C.long
	var ndim C.long
	// size query pass (capacity 0 reports the element count)
	n := C.PT_GetOutput(p.ptr, C.long(i), nil, 0, &shape[0], 16, &ndim)
	if n < 0 {
		return Tensor{}, errors.New("paddle: PT_GetOutput failed")
	}
	buf := make([]float32, int(n))
	var bufP *C.float
	if n > 0 {
		bufP = (*C.float)(unsafe.Pointer(&buf[0]))
	}
	if C.PT_GetOutput(p.ptr, C.long(i), bufP, n, &shape[0], 16,
		&ndim) < 0 {
		return Tensor{}, errors.New("paddle: PT_GetOutput failed")
	}
	out := Tensor{Data: buf, Shape: make([]int64, int(ndim))}
	for j := 0; j < int(ndim); j++ {
		out.Shape[j] = int64(shape[j])
	}
	return out, nil
}
